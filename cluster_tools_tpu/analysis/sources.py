"""Shared source/artifact discovery for every lint that walks the repo.

Before ISSUE 18 three call sites hand-rolled their own "walk the package
source files" loop (the stage-name grep lint, the metric-name grep lint,
the committed-artifact schema lint) with three subtly different exclude
lists.  This module is the ONE iterator they all share: the analyzer,
the test shims and bench tooling see the same file set by construction.
"""

from __future__ import annotations

import glob
import os
from typing import Iterator, List, Sequence

#: directory names never descended into when walking package sources
EXCLUDE_DIRS = frozenset({
    "__pycache__", ".git", ".pytest_cache", ".ipynb_checkpoints",
})

#: top-level driver scripts that carry lintable literals (metric names,
#: config keys) but live outside the package directory
TOP_LEVEL_SCRIPTS = ("bench.py", "bench_configs.py", "calibrate_fused.py")


def package_root() -> str:
    """Absolute path of the ``cluster_tools_tpu`` package directory."""
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def repo_root() -> str:
    """Absolute path of the repository checkout (the package's parent)."""
    return os.path.dirname(package_root())


def iter_source_files(root: str | None = None,
                      include_scripts: bool = True) -> Iterator[str]:
    """Yield every ``.py`` file of the package (sorted, exclude-list
    honored), then the known top-level scripts.  ``root`` overrides the
    package directory (fixture corpora in tests)."""
    base = root or package_root()
    for dirpath, dirnames, filenames in os.walk(base):
        dirnames[:] = sorted(d for d in dirnames if d not in EXCLUDE_DIRS)
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)
    if include_scripts and root is None:
        for script in TOP_LEVEL_SCRIPTS:
            path = os.path.join(repo_root(), script)
            if os.path.exists(path):
                yield path


def source_files(root: str | None = None,
                 include_scripts: bool = True) -> List[str]:
    return list(iter_source_files(root, include_scripts))


def committed_artifacts(pattern: str) -> List[str]:
    """Committed artifact files (``BENCH_*.json`` / ``TRACE_*.json`` /
    ``LINT_*.json``) matching ``pattern`` under the repo root, sorted."""
    return sorted(glob.glob(os.path.join(repo_root(), pattern)))


def relpath(path: str) -> str:
    """Repo-relative display path (what findings carry)."""
    try:
        rel = os.path.relpath(os.path.abspath(path), repo_root())
    except ValueError:          # different drive (windows) — keep absolute
        return path
    return path if rel.startswith("..") else rel
