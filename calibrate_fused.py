"""Calibration: per-stage device time of the fused resident program.

The round-4 bench showed the flagship's wall is dominated by one opaque
``sync-meta`` bucket — the host blocking on the whole per-block device
program (~2.2 s/block).  This tool breaks that program open: it rebuilds
the exact chain of ``workflows/fused_pipeline._resident_program`` as a
ladder of CUMULATIVE-PREFIX jitted programs (stage 1, stages 1-2,
stages 1-3, ...), runs each on the real chip against the same
reflect-padded synthetic block the bench uses, and reports the
per-stage device time as consecutive differences.  Cumulative prefixes
(rather than isolated stages) keep every stage's input exactly what the
fused program feeds it and charge each stage its marginal cost including
the fusion XLA actually performs.

Run:  python calibrate_fused.py [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np

from cluster_tools_tpu.core.config import write_config

BLOCK = [50, 512, 512]
HALO = [4, 32, 32]
CFG = dict(threshold=0.25, sigma_seeds=2.0, sigma_weights=2.0, alpha=0.8,
           min_size=25, e_max=16384, rle_cap=1 << 20, refine_rounds=6,
           pair_cap=1 << 21, coarse_factor=4)


def make_block(seed=0):
    """One outer block of the bench's synthetic boundary map (uint8)."""
    from bench import synthetic_instance

    outer = tuple(b + 2 * h for b, h in zip(BLOCK, HALO))
    _, bnd = synthetic_instance(shape=outer, seed=seed)
    return np.round(bnd * 255).astype("uint8")


def build_prefices(outer_shape, halo):
    """Ordered (name, jitted_program) list; program i runs stages 0..i of
    the resident chain and returns a tiny reduction (forces execution,
    keeps d2h out of the timing)."""
    import jax
    import jax.numpy as jnp

    from cluster_tools_tpu.ops.components import connected_components
    from cluster_tools_tpu.ops.edt import distance_transform_edt
    from cluster_tools_tpu.ops.filters import gaussian, local_maxima
    from cluster_tools_tpu.ops.rag import (
        _edge_stats_hist_packed, boundary_pair_values_dual, compact_valid)
    from cluster_tools_tpu.ops.sweep import rle_encode_packed
    from cluster_tools_tpu.ops.watershed import _coarse_impl

    inner_sl = tuple(slice(h, o - h) for h, o in zip(halo, outer_shape))
    n_outer = int(np.prod(outer_shape))
    inner_shape = tuple(o - 2 * h for h, o in zip(halo, outer_shape))
    n_inner = int(np.prod(inner_shape))
    c = CFG

    def normalize(x):
        return x.astype(jnp.float32) * (1.0 / 255.0)

    def to_edt(x):
        xf = normalize(x)
        fg = xf < c["threshold"]
        return xf, fg, distance_transform_edt(fg)

    def to_height(x):
        xf, fg, dt = to_edt(x)
        height = c["alpha"] * gaussian(xf, c["sigma_weights"]) + \
            (1.0 - c["alpha"]) * (1.0 - dt / jnp.maximum(dt.max(), 1e-6))
        return xf, fg, dt, height

    def to_maxima(x):
        xf, fg, dt, height = to_height(x)
        maxima = local_maxima(gaussian(dt, c["sigma_seeds"]), radius=2) & fg
        return xf, height, maxima

    def to_seeds(x):
        xf, height, maxima = to_maxima(x)
        seeds = connected_components(maxima, connectivity=3,
                                     method="propagation")
        return xf, height, seeds

    def to_ws(x):
        xf, height, seeds = to_seeds(x)
        ws, ok = _coarse_impl(height, seeds, c["min_size"],
                              c["refine_rounds"], c["coarse_factor"],
                              dense_ids=True)
        return xf, ws, ok

    def to_dense(x):
        xf, ws, ok = to_ws(x)
        cn_bound = int(np.prod([-(-o // c["coarse_factor"])
                                for o in outer_shape]))
        inner = ws[inner_sl]
        flat = inner.reshape(-1)
        pres = jnp.zeros((cn_bound + 2,), jnp.int32).at[flat].set(
            1, mode="drop")
        pres = pres.at[0].set(0)
        rank = jnp.cumsum(pres)
        dense = jnp.where(flat > 0, rank[flat], 0).astype(jnp.int32)
        return xf, dense.reshape(inner.shape), rank[-1]

    def to_stats(x):
        xf, dense_grid, k = to_dense(x)
        u, v, va, vb, okp = boundary_pair_values_dual(dense_grid,
                                                      x[inner_sl])
        n = int(u.shape[0])
        cap = max(min(c["pair_cap"],
                      1 << int(np.ceil(np.log2(max(n, 2))))), 1 << 13)
        key = u * 32768 + v
        vab = va.astype(jnp.int32) * 256 + vb.astype(jnp.int32)
        (ckey, cvab), cok, cap_overflow = compact_valid(
            okp, [key, vab], cap)
        uv, feats, n_runs, e_overflow = _edge_stats_hist_packed(
            ckey, cvab, cok, e_max=c["e_max"])
        return dense_grid, uv, feats, n_runs, k

    def to_rle(x):
        dense_grid, uv, feats, n_runs, k = to_stats(x)
        packed, n_rle, rle_ok = rle_encode_packed(
            dense_grid.reshape(-1), c["rle_cap"])
        return uv, feats, n_runs, k, packed, n_rle

    def small(*outs):
        """Tiny summary forcing all outputs."""
        acc = jnp.float32(0)
        for o in outs:
            acc = acc + jnp.asarray(o).astype(jnp.float32).sum() % 1024
        return acc

    prefices = [
        ("normalize", jax.jit(lambda x: small(normalize(x)))),
        ("edt", jax.jit(lambda x: small(*to_edt(x)))),
        ("height(gauss)", jax.jit(lambda x: small(*to_height(x)))),
        ("seed-maxima", jax.jit(lambda x: small(*to_maxima(x)))),
        ("seed-cc", jax.jit(lambda x: small(*to_seeds(x)))),
        ("coarse-ws", jax.jit(lambda x: small(*to_ws(x)))),
        ("dense-relabel", jax.jit(lambda x: small(*to_dense(x)))),
        ("pairs+hist", jax.jit(lambda x: small(*to_stats(x)))),
        ("rle", jax.jit(lambda x: small(*to_rle(x)))),
    ]
    return prefices


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--json", default=None)
    ap.add_argument("--reps", type=int, default=3)
    ap.add_argument("--seeds", type=int, default=2,
                    help="distinct blocks (averages data-dependent "
                    "while_loop trip counts)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    outer_shape = tuple(b + 2 * h for b, h in zip(BLOCK, HALO))
    blocks = [jnp.asarray(make_block(seed=s)) for s in range(args.seeds)]
    jax.block_until_ready(blocks)
    print(f"device: {jax.devices()[0]}  outer block: {outer_shape} "
          f"({np.prod(outer_shape)/1e6:.1f} Mvox)")

    prefices = build_prefices(outer_shape, tuple(HALO))
    cum = []
    compile_s = {}
    for name, prog in prefices:
        # warmup on each distinct block shape/value; the FIRST call pays
        # the XLA build — record it so the per-stage table separates
        # compile from steady-state execute, mirroring the runtime's
        # sync-compile / sync-execute stage split
        first = None
        for b in blocks:
            t0 = time.perf_counter()
            jax.block_until_ready(prog(b))
            if first is None:
                first = time.perf_counter() - t0
        ts = []
        for _ in range(args.reps):
            for b in blocks:
                t0 = time.perf_counter()
                jax.block_until_ready(prog(b))
                ts.append(time.perf_counter() - t0)
        cum.append((name, float(np.median(ts))))
        compile_s[name] = round(max(first - float(np.median(ts)), 0.0), 3)
        print(f"  cumulative through {name:<14s} {np.median(ts):7.3f}s "
              f"(compile ~{compile_s[name]:.1f}s)")

    print("\nper-stage device time (marginal):")
    table = {}
    total = cum[-1][1]
    prev = 0.0
    for name, t in cum:
        dt = t - prev
        table[name] = round(dt, 4)
        print(f"  {name:<14s} {dt:7.3f}s  ({100*dt/max(total, 1e-9):5.1f}%)")
        prev = t
    print(f"  {'TOTAL':<14s} {total:7.3f}s")

    if args.json:
        write_config(args.json,
                     {"outer_shape": list(outer_shape),
                      "cumulative": dict(cum), "per_stage": table,
                      "compile_s": compile_s,
                      "total_s": cum[-1][1]})


if __name__ == "__main__":
    main()
