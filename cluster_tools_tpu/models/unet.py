"""3D U-Net for EM boundary / affinity prediction — the flagship model.

TPU-native replacement for the reference's externally-trained torch CNNs
(reference: inference/frameworks.py:32-87 loads a pytorch checkpoint and runs
``model(input_)`` per block; the nets themselves live out-of-repo in
neurofire/inferno).  Here the model is a first-class citizen: a flax.linen
3D U-Net predicting long-range affinities, designed for the MXU —

* all convs are 3D with channel counts that are multiples of 8/16 so XLA can
  tile them onto the 128x128 systolic array;
* compute in bfloat16 (params stay float32) — ``dtype=jnp.bfloat16``;
* anisotropic option: EM volumes have coarse z; the first level can
  downsample only in-plane (scale (1,2,2)) like typical connectomics nets;
* static shapes end-to-end, no data-dependent control flow: jit/pjit clean.

The number of output channels defaults to the reference's standard long-range
affinity neighborhood used by the mutex-watershed stack
(mutex_watershed/mws_blocks.py default offsets: 3 direct + 9 long-range).
"""

from __future__ import annotations

from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

#: default long-range offset pattern (reference: mws default offsets — the
#: 12-channel neighborhood of mutex_watershed/mws_blocks.py / SURVEY §2.1)
DEFAULT_OFFSETS: Tuple[Tuple[int, int, int], ...] = (
    (-1, 0, 0), (0, -1, 0), (0, 0, -1),
    (-2, 0, 0), (0, -3, 0), (0, 0, -3),
    (-3, 0, 0), (0, -9, 0), (0, 0, -9),
    (-4, 0, 0), (0, -27, 0), (0, 0, -27),
)


class ConvBlock(nn.Module):
    """Two 3x3x3 convs with GroupNorm + GELU, bfloat16 compute."""

    features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        for _ in range(2):
            x = nn.Conv(self.features, (3, 3, 3), padding="SAME",
                        dtype=self.dtype, name=None)(x)
            # GroupNorm in f32 for stable statistics
            x = nn.GroupNorm(num_groups=min(8, self.features),
                             dtype=jnp.float32)(x.astype(jnp.float32))
            x = nn.gelu(x).astype(self.dtype)
        return x


class UNet3D(nn.Module):
    """3D U-Net: encoder/decoder with skip connections.

    Input  ``(B, D, H, W, C_in)``; output ``(B, D, H, W, out_channels)``
    (sigmoid probabilities when ``final_activation='sigmoid'``).
    """

    out_channels: int = len(DEFAULT_OFFSETS)
    features: Sequence[int] = (16, 32, 64, 128)
    #: per-level downsample factors; (1,2,2) on level 0 = anisotropic EM mode
    scale_factors: Sequence[Tuple[int, int, int]] = ((1, 2, 2), (2, 2, 2), (2, 2, 2))
    final_activation: str = "sigmoid"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        skips = []
        # encoder
        for level, feats in enumerate(self.features[:-1]):
            x = ConvBlock(feats, dtype=self.dtype)(x)
            skips.append(x)
            s = self.scale_factors[level]
            x = nn.max_pool(x, window_shape=s, strides=s)
        # bottleneck
        x = ConvBlock(self.features[-1], dtype=self.dtype)(x)
        # decoder
        for level in reversed(range(len(self.features) - 1)):
            s = self.scale_factors[level]
            x = nn.ConvTranspose(self.features[level], kernel_size=s,
                                 strides=s, dtype=self.dtype)(x)
            x = jnp.concatenate([x, skips[level]], axis=-1)
            x = ConvBlock(self.features[level], dtype=self.dtype)(x)
        x = nn.Conv(self.out_channels, (1, 1, 1), dtype=jnp.float32)(
            x.astype(jnp.float32))
        if self.final_activation == "sigmoid":
            x = jax.nn.sigmoid(x)
        return x

    def min_divisor(self) -> Tuple[int, int, int]:
        """Spatial dims must be divisible by the product of scale factors."""
        d = [1, 1, 1]
        for s in self.scale_factors:
            for i in range(3):
                d[i] *= s[i]
        return tuple(d)


def create_unet(out_channels: int = len(DEFAULT_OFFSETS),
                features: Sequence[int] = (16, 32, 64, 128),
                anisotropic: bool = True) -> UNet3D:
    n_levels = len(features) - 1
    if anisotropic:  # first level downsamples in-plane only (coarse EM z)
        scales = ((1, 2, 2),) + tuple((2, 2, 2) for _ in range(n_levels - 1))
    else:
        scales = tuple((2, 2, 2) for _ in range(n_levels))
    return UNet3D(out_channels=out_channels, features=tuple(features),
                  scale_factors=scales)
