"""Label multisets: per-voxel (label, count) multisets at downsampled
scales.

The reference leaves this component as an empty stub
(label_multisets/__init__.py is 1 line; paintera/conversion_workflow.py:14-15
carries the TODO) — Paintera's multiscale label datasets want, for every
coarse voxel, the multiset of fine labels inside its window so proofreading
tools can render and pick ids without touching full resolution.  This is a
working implementation with a documented flat serialization (not Paintera's
java binary layout, which cannot be validated here):

Per coarse block (one VarlenDataset chunk per block id), a single uint64
array::

    [n_voxels,
     offsets[0..n_voxels]          (exclusive prefix sum, last = n_entries),
     ids[0..n_entries),
     counts[0..n_entries)]

where voxel ``i`` of the C-ordered coarse block owns entries
``offsets[i]:offsets[i+1]``, sorted by id.  ``unpack_multiset_block``
restores (offsets, ids, counts).

The multiset computation is a sort + run-length encode over pooling
windows — pure vectorized numpy per block, no per-voxel Python.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

from ..core.blocking import Blocking
from ..core.runtime import BlockTask
from ..core.storage import VarlenDataset, file_reader
from ..core.workflow import FileTarget, Task
from .downscaling import ScaleFactor, _factor3


def compute_multisets(fine: np.ndarray, factor: Sequence[int]
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Multisets of ``fine`` labels per ``factor`` pooling window.

    Returns (offsets[n+1], ids[m], counts[m]) over the C-ordered coarse
    voxels; ids are sorted within each voxel.  Windows at the upper border
    are padded by edge replication and the pad contributions removed from
    the counts, so border voxels carry exactly their real fine voxels.
    """
    from .downscaling import pooling_windows

    out_shape = tuple(-(-s // f) for s, f in zip(fine.shape, factor))
    w = int(np.prod(factor))
    windows = pooling_windows(fine, factor, out_shape).reshape(-1, w)
    # pad-tracking: count only real voxels
    rmask = pooling_windows(np.ones(fine.shape, "int64"), factor,
                            out_shape, pad_mode="constant").reshape(-1, w)
    n, w = windows.shape
    order = np.argsort(windows, axis=1, kind="stable")
    sw = np.take_along_axis(windows, order, axis=1)
    sm = np.take_along_axis(rmask, order, axis=1)
    # run starts within each row
    first = np.ones((n, w), bool)
    first[:, 1:] = sw[:, 1:] != sw[:, :-1]
    # real-voxel count per run via prefix sums of the mask
    csum = np.cumsum(sm, axis=1)
    run_start_flat = np.flatnonzero(first.ravel())
    row = run_start_flat // w
    ends = np.r_[run_start_flat[1:], [n * w]]
    # runs never cross rows (first[:,0] is always True)
    ends = np.where(np.r_[row[1:] != row[:-1], [True]],
                    (row + 1) * w, ends)
    csum_flat = csum.ravel()
    total_at_end = csum_flat[ends - 1]
    prev = run_start_flat - 1
    total_before = np.where(run_start_flat % w == 0, 0, csum_flat[prev])
    counts = total_at_end - total_before
    ids = sw.ravel()[run_start_flat]
    keep = counts > 0  # runs made purely of pad voxels
    ids, counts, row = ids[keep], counts[keep], row[keep]
    offsets = np.zeros(n + 1, "int64")
    np.add.at(offsets, row + 1, 1)
    offsets = np.cumsum(offsets)
    return offsets, ids.astype("uint64"), counts.astype("int64")


def pack_multiset_block(offsets: np.ndarray, ids: np.ndarray,
                        counts: np.ndarray) -> np.ndarray:
    n = len(offsets) - 1
    return np.concatenate([
        np.asarray([n], "uint64"), offsets.astype("uint64"),
        ids.astype("uint64"), counts.astype("uint64")])


def unpack_multiset_block(flat: np.ndarray
                          ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    n = int(flat[0])
    offsets = flat[1:n + 2].astype("int64")
    m = int(offsets[-1])
    ids = flat[n + 2:n + 2 + m]
    counts = flat[n + 2 + m:n + 2 + 2 * m].astype("int64")
    return offsets, ids, counts


def merge_multisets(entries, n_parents: int
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Union child multisets into parent multisets (exact: pooling windows
    partition the volume, so summing child counts per id is byte-identical
    to recomputing from level 0).

    ``entries`` = iterable of (parent_voxel_indices[int64], ids, counts)
    flat triples.  Returns (offsets[n_parents + 1], ids, counts) sorted by
    (parent, id).
    """
    all_parents, all_ids, all_counts = [], [], []
    for parent_vox, ids, counts in entries:
        all_parents.append(parent_vox)
        all_ids.append(ids)
        all_counts.append(counts)
    if not all_parents:
        return np.zeros(n_parents + 1, "int64"), \
            np.zeros(0, "uint64"), np.zeros(0, "int64")
    parents = np.concatenate(all_parents)
    ids = np.concatenate(all_ids)
    counts = np.concatenate(all_counts)
    order = np.lexsort((ids, parents))
    parents, ids, counts = parents[order], ids[order], counts[order]
    first = np.ones(len(parents), bool)
    first[1:] = (parents[1:] != parents[:-1]) | (ids[1:] != ids[:-1])
    starts = np.flatnonzero(first)
    merged_counts = np.add.reduceat(counts, starts)
    merged_ids = ids[starts]
    merged_parents = parents[starts]
    offsets = np.zeros(n_parents + 1, "int64")
    np.add.at(offsets, merged_parents + 1, 1)
    return np.cumsum(offsets), merged_ids, merged_counts.astype("int64")


class LabelMultisetTask(BlockTask):
    """One multiset scale level, blockwise over the COARSE grid.

    From a dense label volume (``input_is_multiset=False``): read the fine
    window, compute per-voxel multisets.  From the previous multiset level
    (``input_is_multiset=True``, ``scale_factor`` = the RELATIVE factor):
    union the child voxels' multisets per parent voxel — exact and far
    cheaper than re-reading level 0 (the fine window grows with the
    cumulative factor cubed)."""

    task_name = "label_multisets"

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, scale_factor: ScaleFactor,
                 effective_factor: Optional[Sequence[int]] = None,
                 input_is_multiset: bool = False, identifier: str = "", **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.scale_factor = _factor3(scale_factor)
        self.effective_factor = list(effective_factor or self.scale_factor)
        self.input_is_multiset = input_is_multiset
        self.identifier = identifier
        super().__init__(**kw)

    def run_impl(self):
        if self.input_is_multiset:
            src = VarlenDataset(os.path.join(self.input_path,
                                             self.input_key),
                                dtype="uint64", mode="r")
            in_shape = list(src.attrs["multisetShape"])
        else:
            with file_reader(self.input_path, "r") as f:
                in_shape = list(f[self.input_key].shape)
        out_shape = [-(-s // f) for s, f in zip(in_shape, self.scale_factor)]
        block_shape = [min(b, s) for b, s in
                       zip(self.global_block_shape(), out_shape)]
        out = VarlenDataset(os.path.join(self.output_path, self.output_key),
                            dtype="uint64")
        out.attrs["isLabelMultiset"] = True
        out.attrs["downsamplingFactors"] = self.effective_factor[::-1]
        out.attrs["multisetShape"] = out_shape
        out.attrs["blockShape"] = block_shape
        block_list = self.blocks_in_volume(out_shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "scale_factor": self.scale_factor,
            "input_is_multiset": self.input_is_multiset,
            "shape": out_shape, "block_shape": block_shape,
            "in_shape": in_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        factor = cfg["scale_factor"]
        out = VarlenDataset(os.path.join(cfg["output_path"],
                                         cfg["output_key"]), dtype="uint64")
        if cfg.get("input_is_multiset"):
            cls._merge_level_job(job_config, blocking, factor, out, log_fn)
            return
        f_in = file_reader(cfg["input_path"], "r")
        ds = f_in[cfg["input_key"]]
        for block_id in job_config["block_list"]:
            block = blocking.get_block(block_id)
            fine_bb = tuple(slice(b.start * f, min(b.stop * f, s))
                            for b, f, s in zip(block.bb, factor,
                                               cfg["in_shape"]))
            offsets, ids, counts = compute_multisets(
                np.asarray(ds[fine_bb]), factor)
            out.write_chunk((block_id,),
                            pack_multiset_block(offsets, ids, counts))
            log_fn(f"processed block {block_id}")

    @staticmethod
    def _merge_level_job(job_config, blocking, factor, out, log_fn):
        cfg = job_config["config"]
        child_shape = cfg["in_shape"]
        src = VarlenDataset(os.path.join(cfg["input_path"],
                                         cfg["input_key"]),
                            dtype="uint64", mode="r")
        child_bs = src.attrs["blockShape"]
        child_blocking = Blocking(child_shape, child_bs)

        for block_id in job_config["block_list"]:
            block = blocking.get_block(block_id)
            child_bb = [(b.start * f, min(b.stop * f, s))
                        for b, f, s in zip(block.bb, factor, child_shape)]
            pshape = [b.stop - b.start for b in block.bb]
            n_parents = int(np.prod(pshape))
            entries = []
            for cbid in child_blocking.blocks_in_roi(
                    [lo for lo, _ in child_bb], [hi for _, hi in child_bb]):
                flat = src.read_chunk((cbid,))
                if flat is None:
                    continue
                coffsets, cids, ccounts = unpack_multiset_block(flat)
                cblock = child_blocking.get_block(cbid)
                # per-axis 1-D coords broadcast to the block's C-order
                # voxel grid (no dense meshgrids)
                ax_coord = [np.arange(b.start, b.stop) for b in cblock.bb]
                ax_inside = [(c >= lo) & (c < hi)
                             for c, (lo, hi) in zip(ax_coord, child_bb)]
                ax_parent = [c // f - b.start
                             for c, f, b in zip(ax_coord, factor, block.bb)]
                inside = (ax_inside[0][:, None, None]
                          & ax_inside[1][None, :, None]
                          & ax_inside[2][None, None, :])
                pidx = ((ax_parent[0][:, None, None] * pshape[1]
                         + ax_parent[1][None, :, None]) * pshape[2]
                        + ax_parent[2][None, None, :])
                # expand per-voxel offsets to per-entry rows
                lens = np.diff(coffsets)
                vox_of_entry = np.repeat(np.arange(len(lens)), lens)
                keep = inside.ravel()[vox_of_entry]
                entries.append((pidx.ravel()[vox_of_entry[keep]],
                                cids[keep], ccounts[keep]))
            offsets, ids, counts = merge_multisets(entries, n_parents)
            out.write_chunk((block_id,),
                            pack_multiset_block(offsets, ids, counts))
            log_fn(f"processed block {block_id}")


def load_multiset_block(path: str, key: str, block_id: int,
                        ds: Optional[VarlenDataset] = None):
    """(offsets, ids, counts) of one coarse block, or None if absent.
    Pass a pre-opened ``ds`` when reading many blocks."""
    if ds is None:
        ds = VarlenDataset(os.path.join(path, key), dtype="uint64",
                           mode="r")
    flat = ds.read_chunk((block_id,))
    if flat is None:
        return None
    return unpack_multiset_block(flat)


class LabelMultisetWorkflow(Task):
    """Pyramid of multiset levels from a full-resolution label dataset:
    level 1 pools the dense labels; level k > 1 unions level k-1's
    multisets per window — exact counts (pooling windows partition the
    volume) without re-reading the cumulative-factor-cubed fine window."""

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_prefix: str, scale_factors: Sequence[ScaleFactor],
                 tmp_folder: str, config_dir: str, max_jobs: int = 1,
                 target: str = "local", dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_prefix = output_prefix
        self.scale_factors = [_factor3(s) for s in scale_factors]
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        dep = self.dependency
        cumulative = [1, 1, 1]
        prev_key = None
        for scale, factor in enumerate(self.scale_factors):
            cumulative = [c * f for c, f in zip(cumulative, factor)]
            key = os.path.join(self.output_prefix, f"s{scale + 1}")
            if prev_key is None:
                dep = LabelMultisetTask(
                    input_path=self.input_path, input_key=self.input_key,
                    output_path=self.output_path, output_key=key,
                    scale_factor=factor,
                    effective_factor=list(cumulative),
                    identifier=f"s{scale + 1}", dependency=dep, **common)
            else:
                dep = LabelMultisetTask(
                    input_path=self.output_path, input_key=prev_key,
                    output_path=self.output_path, output_key=key,
                    scale_factor=factor,
                    effective_factor=list(cumulative),
                    input_is_multiset=True,
                    identifier=f"s{scale + 1}", dependency=dep, **common)
            prev_key = key
        return dep

    def output(self):
        return FileTarget(os.path.join(
            self.tmp_folder,
            f"label_multisets_s{len(self.scale_factors)}.status"))
