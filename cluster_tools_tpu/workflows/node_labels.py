"""Node-label overlaps: map fragment ids to overlapping labels of a second
volume (groundtruth, semantic maps, ...).

Re-specification of the reference's ``node_labels/`` package
(block_node_labels.py:125-158 per-block ``computeAndSerializeLabelOverlaps``,
merge_node_labels.py:117-153 label-range-sharded ``mergeAndSerializeOverlaps``).
TPU-first differences:

* per-block overlap counting runs **on device** (ops/overlaps.py: lexsorted
  pair runs + segmented sum) instead of in C++;
* per-block results are written **pre-sharded by node-id range**: block b
  writes ``overlaps/shard_<s>/block_<b>.npy`` only for shards its fragment
  ids touch.  The merge job for shard s then reads exactly the files under
  its own shard directory — total merge IO is O(n_blocks), not
  O(n_blocks x n_jobs) (the scaling trap VERDICT flagged for the edge-feature
  merge).

Layout per file: (n, 3) uint64 rows of (node_id, label_id, count).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..core.blocking import Blocking
from ..core.config import write_config
from ..core.runtime import BlockTask
from ..core.storage import VarlenDataset, file_reader
from ..core.workflow import FileTarget, Task


def overlaps_dir(tmp_folder: str, prefix: str) -> str:
    return os.path.join(tmp_folder, f"overlaps_{prefix}" if prefix else "overlaps")


from ..core.storage import read_max_id as _read_max_id  # noqa: E402


class BlockNodeLabels(BlockTask):
    """Per-block overlap extraction (reference: block_node_labels.py).

    Counts, for every fragment (node) id in ``ws`` and every label in the
    second volume, the co-occurring voxels; writes the counts pre-sharded by
    node-id range into the tmp folder.
    """

    task_name = "block_node_labels"

    def __init__(self, ws_path: str, ws_key: str, input_path: str,
                 input_key: str, prefix: str = "",
                 ignore_label: Optional[int] = None,
                 n_labels: Optional[int] = None,
                 include_zeros: bool = False, **kw):
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.input_path = input_path
        self.input_key = input_key
        self.prefix = prefix
        self.ignore_label = ignore_label
        self.n_labels = n_labels
        #: count overlaps of node id 0 too and never skip empty blocks —
        #: required when the table must be an exact contingency table
        #: (evaluation), not just fragment->label assignments
        self.include_zeros = include_zeros
        self.identifier = prefix
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"shard_size": 1_000_000})
        return conf

    def run_impl(self):
        import json as _json

        with file_reader(self.ws_path, "r") as f:
            shape = list(f[self.ws_key].shape)
        n_labels = self.n_labels or (_read_max_id(self.ws_path, self.ws_key) + 1)
        block_shape = self.global_block_shape()[-len(shape):]
        block_list = self.blocks_in_volume(shape, block_shape)
        out_dir = overlaps_dir(self.tmp_folder, self.prefix)
        os.makedirs(out_dir, exist_ok=True)
        # record shard geometry once; the merge task reads it back so the two
        # tasks can never disagree on shard_size/n_labels (separately
        # configurable task configs must not shift shard boundaries)
        write_config(os.path.join(out_dir, "meta.json"),
                     {"shard_size": int(self.task_config["shard_size"]),
                      "n_labels": int(n_labels)})
        self.run_jobs(block_list, {
            "ws_path": self.ws_path, "ws_key": self.ws_key,
            "input_path": self.input_path, "input_key": self.input_key,
            "shape": shape, "block_shape": block_shape,
            "ignore_label": self.ignore_label,
            "include_zeros": self.include_zeros,
            "overlaps_dir": out_dir, "n_labels": n_labels,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from ..ops.overlaps import count_overlaps

        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        shard_size = int(cfg.get("shard_size", 1_000_000))
        ignore_label = cfg.get("ignore_label")
        out_dir = cfg["overlaps_dir"]
        f_ws = file_reader(cfg["ws_path"], "r")
        f_in = file_reader(cfg["input_path"], "r")
        ds_ws, ds_in = f_ws[cfg["ws_key"]], f_in[cfg["input_key"]]
        include_zeros = bool(cfg.get("include_zeros", False))
        for block_id in job_config["block_list"]:
            bb = blocking.get_block(block_id).bb
            ws = ds_ws[bb]
            if not include_zeros and not ws.any():
                log_fn(f"block {block_id} is empty")
                log_fn(f"processed block {block_id}")
                continue
            labels = ds_in[bb]
            ids_ws, ids_lab, counts = count_overlaps(ws, labels)
            keep = np.ones(len(ids_ws), dtype=bool)
            if not include_zeros:
                keep &= ids_ws != 0  # node id 0 is background everywhere
            if ignore_label is not None:
                keep &= ids_lab != np.uint64(ignore_label)
            ids_ws, ids_lab, counts = ids_ws[keep], ids_lab[keep], counts[keep]
            if len(ids_ws) == 0:
                log_fn(f"processed block {block_id}")
                continue
            rows = np.stack([ids_ws, ids_lab, counts], axis=1)
            shards = (ids_ws // np.uint64(shard_size)).astype("int64")
            for s in np.unique(shards):
                shard_dir = os.path.join(out_dir, f"shard_{s}")
                os.makedirs(shard_dir, exist_ok=True)
                # tmp name must not match the block_*.npy aggregation glob
                tmp = os.path.join(shard_dir, f".tmp_block_{block_id}.npy")
                np.save(tmp, rows[shards == s])
                os.replace(tmp, os.path.join(shard_dir, f"block_{block_id}.npy"))
            log_fn(f"processed block {block_id}")


def _aggregate_shard(shard_dir: str) -> np.ndarray:
    """Concatenate a shard's per-block files and sum counts per
    (node, label) pair.  Returns (n, 3) uint64 (node, label, count)."""
    chunks = []
    if os.path.isdir(shard_dir):
        for name in sorted(os.listdir(shard_dir)):
            if name.startswith("block_") and name.endswith(".npy"):
                chunks.append(np.load(os.path.join(shard_dir, name)))
    if not chunks:
        return np.zeros((0, 3), dtype="uint64")
    rows = np.concatenate(chunks, axis=0)
    pairs, inv = np.unique(rows[:, :2], axis=0, return_inverse=True)
    counts = np.bincount(inv, weights=rows[:, 2].astype("float64"),
                         minlength=len(pairs)).astype("uint64")
    return np.concatenate([pairs, counts[:, None]], axis=1)


class MergeNodeLabels(BlockTask):
    """Merge per-block overlaps, sharded over **node-id space** (reference:
    merge_node_labels.py, label-range blocking).

    ``max_overlap=True`` writes the argmax label per node into the output
    dataset (ties break to the smallest label id, deterministically);
    ``max_overlap=False`` serializes the full merged overlaps per shard into a
    varlen dataset for downstream consumers (evaluation measures)."""

    task_name = "merge_node_labels"

    def __init__(self, output_path: str, output_key: str,
                 n_labels: Optional[int] = None,
                 prefix: str = "", max_overlap: bool = True, **kw):
        self.output_path = output_path
        self.output_key = output_key
        self.n_labels = n_labels
        self.prefix = prefix
        self.max_overlap = max_overlap
        self.identifier = prefix
        super().__init__(**kw)

    def run_impl(self):
        import json as _json

        # shard geometry comes from the extraction task's metadata — written
        # when BlockNodeLabels ran (i.e. lazily, not at DAG-construction time)
        meta_path = os.path.join(
            overlaps_dir(self.tmp_folder, self.prefix), "meta.json")
        with open(meta_path) as f:
            meta = _json.load(f)
        shard_size = int(meta["shard_size"])
        n_labels = int(self.n_labels or meta["n_labels"])
        n_shards = max((n_labels + shard_size - 1) // shard_size, 1)
        if self.max_overlap:
            with file_reader(self.output_path) as f:
                f.require_dataset(
                    self.output_key, shape=(n_labels,),
                    chunks=(min(shard_size, n_labels),), dtype="uint64")
        self.run_jobs(list(range(n_shards)), {
            "output_path": self.output_path, "output_key": self.output_key,
            "overlaps_dir": overlaps_dir(self.tmp_folder, self.prefix),
            "max_overlap": self.max_overlap, "n_labels": n_labels,
            "shard_size": shard_size,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        shard_size = int(cfg["shard_size"])
        n_labels = int(cfg["n_labels"])
        for shard_id in job_config["block_list"]:
            rows = _aggregate_shard(
                os.path.join(cfg["overlaps_dir"], f"shard_{shard_id}"))
            if cfg["max_overlap"]:
                begin = shard_id * shard_size
                end = min(begin + shard_size, n_labels)
                out = np.zeros(end - begin, dtype="uint64")
                if len(rows):
                    # argmax count per node, ties to the smallest label id:
                    # sort by (node, -count, label), take the first row per node
                    nodes = rows[:, 0].astype("int64") - begin
                    srt = np.lexsort((rows[:, 1],
                                      -rows[:, 2].astype("int64"), nodes))
                    first = np.flatnonzero(
                        np.r_[True, nodes[srt][1:] != nodes[srt][:-1]])
                    sel = srt[first]
                    out[nodes[sel]] = rows[sel, 1]
                with file_reader(cfg["output_path"]) as f:
                    f[cfg["output_key"]][begin:end] = out
            else:
                ds = VarlenDataset(os.path.join(
                    cfg["output_path"], cfg["output_key"]), dtype="uint64")
                ds.write_chunk((int(shard_id),), rows.ravel())
            log_fn(f"processed block {shard_id}")


def load_merged_overlaps(output_path: str, output_key: str) -> np.ndarray:
    """Read back overlaps serialized by MergeNodeLabels(max_overlap=False) as
    one (n, 3) uint64 array of (node, label, count) rows."""
    ds = VarlenDataset(os.path.join(output_path, output_key), dtype="uint64")
    parts = []
    for chunk_id in ds.chunk_ids():
        data = ds.read_chunk(chunk_id)
        if data is not None and data.size:
            parts.append(data.reshape(-1, 3))
    if not parts:
        return np.zeros((0, 3), dtype="uint64")
    return np.concatenate(parts, axis=0)


class NodeLabelWorkflow(Task):
    """BlockNodeLabels -> MergeNodeLabels (reference:
    node_labels/node_label_workflow.py)."""

    def __init__(self, ws_path: str, ws_key: str, input_path: str,
                 input_key: str, output_path: str, output_key: str,
                 tmp_folder: str, config_dir: str, max_jobs: int = 1,
                 target: str = "local", prefix: str = "",
                 max_overlap: bool = True,
                 ignore_label: Optional[int] = None,
                 n_labels: Optional[int] = None,
                 dependency: Optional[Task] = None):
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.prefix = prefix
        self.max_overlap = max_overlap
        self.ignore_label = ignore_label
        self.n_labels = n_labels
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        t1 = BlockNodeLabels(
            ws_path=self.ws_path, ws_key=self.ws_key,
            input_path=self.input_path, input_key=self.input_key,
            prefix=self.prefix, ignore_label=self.ignore_label,
            n_labels=self.n_labels, dependency=self.dependency,
            **self._common())
        t2 = MergeNodeLabels(
            output_path=self.output_path, output_key=self.output_key,
            prefix=self.prefix, max_overlap=self.max_overlap,
            dependency=t1, **self._common())
        return t2

    def output(self):
        suffix = f"_{self.prefix}" if self.prefix else ""
        return FileTarget(os.path.join(
            self.tmp_folder, f"merge_node_labels{suffix}.status"))
