"""Tests for the scan-based sweep kernels (ops/sweep.py).

Oracles: scipy.ndimage.label for CC; the native bucket-queue flood
(reference vigra-watershed semantics) for the watershed — exact voxel
agreement is not required (plateau/tie divergence, as between vigra and
scipy), so the assertions are structural plus an agreement floor on the
cell interiors.
"""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from cluster_tools_tpu.ops.sweep import (compact_ids, rle_decode, rle_encode,
                                         sweep_cc_impl, sweep_watershed,
                                         sweep_watershed_impl)


def _instance(shape=(24, 48, 48), n_cells=24, seed=0):
    from scipy.spatial import cKDTree

    rng = np.random.RandomState(seed)
    pts = (rng.rand(n_cells, 3) * np.array(shape)).astype("float32")
    tree = cKDTree(pts)
    grids = np.meshgrid(*[np.arange(s, dtype="float32") for s in shape],
                        indexing="ij")
    q = np.stack([g.ravel() for g in grids], 1)
    d, idx = tree.query(q, k=2)
    bnd = np.exp(-0.5 * ((d[:, 1] - d[:, 0]) / 2.0) ** 2)
    return bnd.reshape(shape).astype("float32"), (idx[:, 0] + 1).reshape(shape)


def _height_and_seeds(bnd):
    from scipy import ndimage

    fg = bnd < 0.4
    dt = ndimage.distance_transform_edt(fg)
    height = (0.8 * ndimage.gaussian_filter(bnd, 2.0)
              + 0.2 * (1 - dt / max(dt.max(), 1e-6)))
    dts = ndimage.gaussian_filter(dt, 2.0)
    mx = (dts == ndimage.maximum_filter(dts, size=5)) & fg
    seeds, _ = ndimage.label(mx)
    hq = np.clip(np.round(height * 255), 0, 255).astype("uint8")
    return hq, seeds.astype("int32"), fg


class TestSweepCC:
    def test_matches_scipy_label(self):
        from scipy import ndimage

        rng = np.random.RandomState(1)
        mask = rng.rand(12, 20, 20) < 0.3
        lab, conv = sweep_cc_impl(jnp.asarray(mask))
        assert bool(conv)
        lab = np.asarray(lab)
        ref, n_ref = ndimage.label(
            mask, structure=ndimage.generate_binary_structure(3, 1))
        assert (lab > 0).sum() == mask.sum()
        assert len(np.unique(lab[lab > 0])) == n_ref
        # bijective label correspondence
        pairs = {(a, b) for a, b in zip(ref[mask].ravel(), lab[mask].ravel())}
        assert len(pairs) == n_ref

    def test_empty_mask(self):
        lab, conv = sweep_cc_impl(jnp.zeros((4, 5, 6), bool))
        assert bool(conv) and not np.asarray(lab).any()


class TestSweepWatershed:
    def test_two_basins_split_at_ridge(self):
        h = np.zeros((3, 9, 21), np.uint8)
        for x in range(21):
            h[:, :, x] = min(abs(x - 3), abs(x - 17)) * 12
        seeds = np.zeros_like(h, np.int32)
        seeds[1, 4, 3] = 1
        seeds[1, 4, 17] = 2
        lab, conv = sweep_watershed_impl(jnp.asarray(h), jnp.asarray(seeds),
                                         None)
        assert bool(conv)
        lab = np.asarray(lab)
        assert (lab[:, :, :10] == 1).all()
        assert (lab[:, :, 11:] == 2).all()

    def test_full_coverage_and_seed_preservation(self):
        bnd, _ = _instance()
        hq, seeds, _ = _height_and_seeds(bnd)
        lab, conv = sweep_watershed_impl(jnp.asarray(hq), jnp.asarray(seeds),
                                         None, max_rounds=64)
        assert bool(conv)
        lab = np.asarray(lab)
        assert (lab > 0).all()
        sm = seeds > 0
        assert (lab[sm] == seeds[sm]).all()
        assert set(np.unique(lab)) <= set(np.unique(seeds))

    def test_interior_agreement_with_flood(self):
        from cluster_tools_tpu import native

        bnd, _ = _instance()
        hq, seeds, fg = _height_and_seeds(bnd)
        lab = np.asarray(sweep_watershed_impl(
            jnp.asarray(hq), jnp.asarray(seeds), None, max_rounds=64)[0])
        flood = native.seeded_watershed_u8(hq, seeds.astype("int64"))
        # cell interiors must match the flood almost exactly; ridge-band
        # assignments legitimately diverge (tie-order class)
        agree = (lab[fg] == flood[fg]).mean()
        assert agree > 0.97, f"interior agreement {agree:.3f}"

    def test_mask_blocks_transit(self):
        # two chambers connected only through a masked wall: labels must
        # not cross the wall
        h = np.zeros((1, 5, 11), np.uint8)
        mask = np.ones_like(h, bool)
        mask[:, :, 5] = False
        seeds = np.zeros_like(h, np.int32)
        seeds[0, 2, 1] = 3
        lab, conv = sweep_watershed_impl(jnp.asarray(h), jnp.asarray(seeds),
                                         jnp.asarray(mask))
        assert bool(conv)
        lab = np.asarray(lab)
        assert (lab[:, :, :5] == 3).all()
        assert not lab[:, :, 5:].any()

    def test_min_size_filter(self):
        bnd, _ = _instance()
        hq, seeds, _ = _height_and_seeds(bnd)
        lab, conv = sweep_watershed_impl(
            jnp.asarray(hq), jnp.asarray(seeds), None, max_rounds=64,
            min_size=100, k_cap=int(seeds.max()) + 1)
        assert bool(conv)
        lab = np.asarray(lab)
        assert (lab > 0).all()
        sizes = np.bincount(lab.ravel())
        assert (sizes[sizes > 0] >= 100).all()

    def test_wrapper_restores_ids(self):
        h = np.zeros((1, 4, 10), np.float32)
        h[0, :, 5] = 1.0
        seeds = np.zeros_like(h, np.int32)
        seeds[0, 1, 1] = 17
        seeds[0, 1, 8] = 99
        lab = np.asarray(sweep_watershed(h, seeds))
        assert set(np.unique(lab)) == {17, 99}


class TestRLE:
    def test_roundtrip(self):
        rng = np.random.RandomState(0)
        x = np.repeat(rng.randint(0, 50, 200).astype("int32"),
                      rng.randint(1, 30, 200))
        starts, vals, n, ok = rle_encode(jnp.asarray(x), 1024)
        assert bool(ok)
        n = int(n)
        dec = rle_decode(np.asarray(starts)[:n], np.asarray(vals)[:n], len(x))
        np.testing.assert_array_equal(dec, x)

    def test_overflow_flag(self):
        x = np.arange(100, dtype=np.int32)  # 100 runs of length 1
        *_, ok = rle_encode(jnp.asarray(x), 10)
        assert not bool(ok)

    def test_compact_ids(self):
        lab = np.array([[0, 5, 5], [9, 0, 5]], np.int32)
        dense, k = compact_ids(jnp.asarray(lab), 16)
        assert int(k) == 2
        np.testing.assert_array_equal(np.asarray(dense),
                                      [[0, 1, 1], [2, 0, 1]])


class TestPackedRLE:
    def test_roundtrip(self):
        from cluster_tools_tpu.ops.sweep import (rle_decode_packed,
                                                 rle_encode_packed)

        rng = np.random.RandomState(0)
        x = np.repeat(rng.randint(0, 500, 300).astype("int32"),
                      rng.randint(1, 60000, 300))
        packed, n, ok = rle_encode_packed(jnp.asarray(x), 1 << 16)
        assert bool(ok)
        dec = rle_decode_packed(np.asarray(packed), int(n), len(x))
        np.testing.assert_array_equal(dec, x.astype("uint16"))

    def test_overflow_and_id_range(self):
        from cluster_tools_tpu.ops.sweep import rle_encode_packed

        x = np.arange(100, dtype=np.int32)
        *_, ok = rle_encode_packed(jnp.asarray(x), 10)
        assert not bool(ok)
        big = np.full(10, 1 << 16, np.int32)
        *_, ok = rle_encode_packed(jnp.asarray(big), 64)
        assert not bool(ok)
