"""Postprocess suite tests: numpy-oracle checks for each filter and graph
step (reference capability: postprocess_workflow.py:24-420)."""

import json
import os

import numpy as np
import pytest

from cluster_tools_tpu.core.storage import file_reader
from cluster_tools_tpu.core.workflow import build


def _seg_volume(shape=(16, 16, 16)):
    """Labels 1..4 as axis-aligned slabs + one tiny segment 5."""
    seg = np.zeros(shape, "uint64")
    seg[:4] = 1
    seg[4:8] = 2
    seg[8:12] = 3
    seg[12:] = 4
    seg[0, 0, 0:3] = 5  # 3-voxel sliver inside segment 1
    return seg


def test_size_filter_background(tmp_workdir, tmp_path):
    from cluster_tools_tpu.workflows.postprocess import SizeFilterWorkflow

    tmp_folder, config_dir = tmp_workdir
    seg = _seg_volume()
    path = str(tmp_path / "d.n5")
    with file_reader(path) as f:
        f.create_dataset("seg", data=seg, chunks=[8, 8, 8])

    wf = SizeFilterWorkflow(
        input_path=path, input_key="seg", output_path=path,
        output_key="filtered", size_threshold=10,
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="threads", relabel=False)
    assert build([wf], raise_on_failure=True)

    with file_reader(path, "r") as f:
        out = f["filtered"][:]
    # sliver 5 went to background, others survive untouched
    assert (out[seg == 5] == 0).all()
    for lbl in (1, 2, 3, 4):
        assert (out[(seg == lbl)] == lbl).all()


@pytest.mark.slow
def test_size_filter_filling(tmp_workdir, tmp_path):
    from cluster_tools_tpu.workflows.postprocess import SizeFilterWorkflow

    tmp_folder, config_dir = tmp_workdir
    seg = _seg_volume()
    hmap = np.zeros(seg.shape, "float32")
    path = str(tmp_path / "d.n5")
    with file_reader(path) as f:
        f.create_dataset("seg", data=seg, chunks=[8, 8, 8])
        f.create_dataset("hmap", data=hmap, chunks=[8, 8, 8])

    wf = SizeFilterWorkflow(
        input_path=path, input_key="seg", output_path=path,
        output_key="filled", size_threshold=10,
        hmap_path=path, hmap_key="hmap",
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="threads", relabel=False)
    assert build([wf], raise_on_failure=True)

    with file_reader(path, "r") as f:
        out = f["filled"][:]
    # sliver voxels were regrown into the surrounding segment 1 — no holes
    assert (out[seg == 5] == 1).all()
    assert (out > 0).all()


def test_filter_labels_workflow(tmp_workdir, tmp_path):
    from cluster_tools_tpu.workflows.postprocess import FilterLabelsWorkflow

    tmp_folder, config_dir = tmp_workdir
    seg = _seg_volume()
    # semantic map: label 9 over segments 1/2, label 7 over 3/4
    sem = np.where(np.arange(16)[:, None, None] < 8, 9, 7) * np.ones(
        seg.shape, "uint64")
    path = str(tmp_path / "d.n5")
    with file_reader(path) as f:
        ds = f.create_dataset("seg", data=seg, chunks=[8, 8, 8])
        ds.attrs["maxId"] = int(seg.max())
        f.create_dataset("sem", data=sem.astype("uint64"), chunks=[8, 8, 8])

    wf = FilterLabelsWorkflow(
        input_path=path, input_key="seg", label_path=path, label_key="sem",
        node_label_path=path, node_label_key="node_labels",
        output_path=path, output_key="filtered", filter_labels=[9],
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="threads")
    assert build([wf], raise_on_failure=True)

    with file_reader(path, "r") as f:
        out = f["filtered"][:]
    # segments under semantic label 9 (= 1, 2, 5) are gone; 3, 4 remain
    assert set(np.unique(out)) == {0, 3, 4}


def test_graph_watershed_assignments(tmp_workdir, tmp_path):
    """Discarded small segment is reassigned to its strongest neighbor."""
    from cluster_tools_tpu.core.graph import save_graph
    from cluster_tools_tpu.workflows.postprocess import (
        GraphWatershedAssignments)

    tmp_folder, config_dir = tmp_workdir
    problem = str(tmp_path / "p.n5")
    # nodes 0..4; assignments: node i -> segment i (0 = background)
    # node 3 (small segment) connects to segment 1 (weak boundary, 0.1)
    # and segment 2 (strong boundary, 0.9) -> should join segment 1
    uv = np.array([[1, 3], [2, 3], [1, 2], [0, 4]], "uint64")
    feats = np.zeros((4, 10), "float64")
    feats[:, 0] = [0.1, 0.9, 0.8, 0.5]
    save_graph(problem, "graph", np.arange(5, dtype="uint64"), uv, (1, 1, 1))
    with file_reader(problem) as f:
        f.create_dataset("features", data=feats)
        f.create_dataset("assignments",
                         data=np.arange(5, dtype="uint64"))
    discard_path = str(tmp_path / "discard.npy")
    np.save(discard_path, np.array([3], "uint64"))

    task = GraphWatershedAssignments(
        problem_path=problem, graph_key="graph", features_key="features",
        assignment_path=problem, assignment_key="assignments",
        output_path=problem, output_key="new_assignments",
        filter_nodes_path=discard_path,
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=1, target="threads")
    assert build([task], raise_on_failure=True)

    with file_reader(problem, "r") as f:
        out = f["new_assignments"][:]
    assert out[3] == 1  # joined via the weakest boundary
    assert out[0] == 0  # background preserved
    assert out[1] == 1 and out[2] == 2 and out[4] == 4


def test_orphan_assignments(tmp_workdir, tmp_path):
    from cluster_tools_tpu.core.graph import save_graph
    from cluster_tools_tpu.workflows.postprocess import OrphanAssignments

    tmp_folder, config_dir = tmp_workdir
    problem = str(tmp_path / "p.n5")
    # segment graph: 1-2, 2-3, 3-1 triangle; 4 hangs off 2 (orphan)
    # node i -> segment assignments
    uv = np.array([[0, 1], [1, 2], [2, 0], [1, 3]], "uint64")
    assignments = np.array([1, 2, 3, 4], "uint64")
    save_graph(problem, "graph", np.arange(4, dtype="uint64"), uv, (1, 1, 1))
    with file_reader(problem) as f:
        f.create_dataset("assignments", data=assignments)

    task = OrphanAssignments(
        graph_path=problem, graph_key="graph",
        assignment_path=problem, assignment_key="assignments",
        output_path=problem, output_key="out",
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=1, target="threads")
    assert build([task], raise_on_failure=True)

    with file_reader(problem, "r") as f:
        out = f["out"][:]
    # orphan segment 4 merged into its only neighbor (2)
    np.testing.assert_array_equal(out, [1, 2, 3, 2])


def test_graph_connected_components(tmp_workdir, tmp_path):
    from cluster_tools_tpu.core.graph import save_graph
    from cluster_tools_tpu.workflows.postprocess import (
        ConnectedComponentsWorkflow)

    tmp_folder, config_dir = tmp_workdir
    problem = str(tmp_path / "p.n5")
    # nodes 0-1 connected, 2-3 connected, but no edge between the pairs;
    # all four share assignment 1 -> must split into two components
    uv = np.array([[0, 1], [2, 3]], "uint64")
    assignments = np.array([1, 1, 1, 1], "uint64")
    save_graph(problem, "graph", np.arange(4, dtype="uint64"), uv, (1, 1, 1))
    with file_reader(problem) as f:
        f.create_dataset("assignments", data=assignments)

    wf = ConnectedComponentsWorkflow(
        problem_path=problem, graph_key="graph",
        assignment_path=problem, assignment_key="assignments",
        output_path=problem, assignment_out_key="cc",
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=1, target="threads")
    assert build([wf], raise_on_failure=True)

    with file_reader(problem, "r") as f:
        out = f["cc"][:]
    assert out[0] == out[1]
    assert out[2] == out[3]
    assert out[0] != out[2]
    # no segment may be erased to background (root-0 components included)
    assert (out != 0).all()


def test_orphan_assignments_mutual_pair(tmp_workdir, tmp_path):
    """Two segments whose only edge is to each other merge (not swap)."""
    from cluster_tools_tpu.core.graph import save_graph
    from cluster_tools_tpu.workflows.postprocess import OrphanAssignments

    tmp_folder, config_dir = tmp_workdir
    problem = str(tmp_path / "p.n5")
    # nodes 0,1 -> segments 1,2 with a single connecting edge
    uv = np.array([[0, 1]], "uint64")
    save_graph(problem, "graph", np.arange(2, dtype="uint64"), uv, (1, 1, 1))
    with file_reader(problem) as f:
        f.create_dataset("assignments", data=np.array([1, 2], "uint64"))

    task = OrphanAssignments(
        graph_path=problem, graph_key="graph",
        assignment_path=problem, assignment_key="assignments",
        output_path=problem, output_key="out",
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=1, target="threads")
    assert build([task], raise_on_failure=True)
    with file_reader(problem, "r") as f:
        out = f["out"][:]
    np.testing.assert_array_equal(out, [1, 1])


def test_size_filter_and_graph_watershed_workflow(tmp_workdir, tmp_path):
    """End-to-end: tiny segment detected by size and re-assigned by graph
    watershed, then written back to the volume."""
    from cluster_tools_tpu.core.graph import save_graph
    from cluster_tools_tpu.workflows.postprocess import (
        SizeFilterAndGraphWatershedWorkflow)

    tmp_folder, config_dir = tmp_workdir
    shape = (8, 8, 8)
    # fragments: 1 fills the left half, 2 the right half, 3 = tiny corner
    frag = np.zeros(shape, "uint64")
    frag[:, :4, :] = 1
    frag[:, 4:, :] = 2
    frag[0, 0, 0] = 3
    # segmentation = identity assignment
    path = str(tmp_path / "d.n5")
    problem = str(tmp_path / "p.n5")
    with file_reader(path) as f:
        f.create_dataset("frags", data=frag, chunks=[8, 8, 8])
        f.create_dataset("seg", data=frag, chunks=[8, 8, 8])
        # the reference keeps segmentation and assignment table in the same
        # container (`path`); mirror that layout
        f.create_dataset("assignments", data=np.arange(4, dtype="uint64"))
    uv = np.array([[1, 2], [1, 3], [2, 3]], "uint64")
    feats = np.zeros((3, 10), "float64")
    feats[:, 0] = [0.9, 0.1, 0.8]  # 3 joins 1
    save_graph(problem, "graph", np.arange(4, dtype="uint64"), uv, shape)
    with file_reader(problem) as f:
        f.create_dataset("features", data=feats)

    wf = SizeFilterAndGraphWatershedWorkflow(
        problem_path=problem, graph_key="graph", features_key="features",
        path=path, segmentation_key="seg", assignment_key="assignments",
        size_threshold=5, output_path=problem,
        assignment_out_key="new_assignments",
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="threads")
    assert build([wf], raise_on_failure=True)

    with file_reader(problem, "r") as f:
        out = f["new_assignments"][:]
    assert out[3] == 1  # tiny segment re-assigned across weakest boundary
    assert out[1] == 1 and out[2] == 2
