"""Per-object surface meshes.

The reference's ``meshes/`` component is an empty placeholder
(compute_meshes.py / mesh_workflow.py are 0 LoC) with the mesh math in
utils/mesh_utils.py; this framework ships the full blockwise workflow: mesh
each object inside its morphology bounding box (label-id-range sharding)
using the first-party marching-tetrahedra extraction (utils/mesh)."""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import FileTarget, Task
from .morphology import MorphologyWorkflow, decode_morphology


class ComputeMeshes(BlockTask):
    """Mesh each object over label-id ranges; one npz (vertices, faces) per
    label under ``<output_path>/<output_key>/``."""

    task_name = "compute_meshes"

    def __init__(self, input_path: str, input_key: str,
                 morphology_path: str, morphology_key: str,
                 output_path: str, output_key: str,
                 n_labels: Optional[int] = None, **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.morphology_path = morphology_path
        self.morphology_key = morphology_key
        self.output_path = output_path
        self.output_key = output_key
        self.n_labels = n_labels
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"id_chunk_size": 1000, "size_threshold": 0,
                     "smoothing_iterations": 0})
        return conf

    def run_impl(self):
        self.resolve_n_labels(self.input_path, self.input_key)
        chunk = int(self.task_config.get("id_chunk_size", 1000))
        os.makedirs(os.path.join(self.output_path, self.output_key),
                    exist_ok=True)
        self.run_jobs(self.id_chunks(self.n_labels, chunk), {
            "input_path": self.input_path, "input_key": self.input_key,
            "morphology_path": self.morphology_path,
            "morphology_key": self.morphology_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "n_labels": self.n_labels, "id_chunk_size": chunk,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from ..utils.mesh import object_mesh

        cfg = job_config["config"]
        chunk, n_labels = cfg["id_chunk_size"], cfg["n_labels"]
        smoothing = int(cfg.get("smoothing_iterations", 0))
        size_threshold = cfg.get("size_threshold", 0)
        f_morph = file_reader(cfg["morphology_path"], "r")
        ds_morph = f_morph[cfg["morphology_key"]]
        f_in = file_reader(cfg["input_path"], "r")
        ds_in = f_in[cfg["input_key"]]
        out_dir = os.path.join(cfg["output_path"], cfg["output_key"])

        for block_id in job_config["block_list"]:
            lo, hi = block_id * chunk, min((block_id + 1) * chunk, n_labels)
            morpho = ds_morph[lo:hi, :]
            sizes, bb_min, bb_max = decode_morphology(morpho)
            for label_id in range(max(lo, 1), hi):
                k = label_id - lo
                if sizes[k] == 0 or (size_threshold
                                     and sizes[k] < size_threshold):
                    continue
                bb = tuple(slice(b, e) for b, e in zip(bb_min[k], bb_max[k]))
                seg = np.asarray(ds_in[bb])
                verts, faces = object_mesh(seg, label_id,
                                           smoothing_iterations=smoothing)
                verts += bb_min[k]  # back to global coordinates
                tmp = os.path.join(out_dir, f"mesh_{label_id}.tmp.npz")
                np.savez(tmp, vertices=verts.astype("float32"),
                         faces=faces.astype("int64"))
                os.replace(tmp, os.path.join(out_dir,
                                             f"mesh_{label_id}.npz"))
            log_fn(f"processed block {block_id}")


def load_mesh(output_path: str, output_key: str, label_id: int):
    """(vertices, faces) of one object's mesh, or None."""
    path = os.path.join(output_path, output_key, f"mesh_{label_id}.npz")
    if not os.path.exists(path):
        return None
    with np.load(path) as d:
        return d["vertices"], d["faces"]


class MeshWorkflow(Task):
    """MorphologyWorkflow -> ComputeMeshes (the mesh_workflow.py the
    reference left empty)."""

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 n_labels: Optional[int] = None,
                 morphology_key: str = "morphology",
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.n_labels = n_labels
        self.morphology_key = morphology_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        morpho = MorphologyWorkflow(
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.morphology_key,
            n_labels=self.n_labels, prefix="mesh",
            dependency=self.dependency, **common)
        return ComputeMeshes(
            input_path=self.input_path, input_key=self.input_key,
            morphology_path=self.output_path,
            morphology_key=self.morphology_key,
            output_path=self.output_path, output_key=self.output_key,
            n_labels=self.n_labels, dependency=morpho, **common)

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "compute_meshes.status"))
