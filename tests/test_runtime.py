"""Runtime tests: job protocol, executors, retry (fault injection).

Ports the reference's test strategy (SURVEY.md §4): the real task machinery is
exercised end-to-end with the local executor as the fake cluster, and a
deterministic FailingTask fixture (reference: test/retry/failing_task.py)
validates block-granular retry.
"""

import os

import numpy as np
import pytest

from cluster_tools_tpu.core import runtime
from cluster_tools_tpu.core.blocking import Blocking
from cluster_tools_tpu.core.config import ConfigDir
from cluster_tools_tpu.core.runtime import BlockTask, FailedJobsError
from cluster_tools_tpu.core.storage import file_reader
from cluster_tools_tpu.core.workflow import DummyTask, FileTarget, Task, build


class FillTask(BlockTask):
    """Write block_id+1 into every voxel of each block."""

    task_name = "fill"

    def __init__(self, output_path, output_key, shape, **kw):
        self.output_path = output_path
        self.output_key = output_key
        self.shape = shape
        super().__init__(**kw)

    def run_impl(self):
        block_shape = self.global_block_shape()[: len(self.shape)]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=self.shape,
                              chunks=block_shape, dtype="uint32")
        block_list = self.blocks_in_volume(self.shape, block_shape)
        self.run_jobs(block_list, {
            "output_path": self.output_path, "output_key": self.output_key,
            "shape": list(self.shape), "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id, job_config, log_fn):
        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        with file_reader(cfg["output_path"]) as f:
            ds = f[cfg["output_key"]]
            for block_id in job_config["block_list"]:
                block = blocking.get_block(block_id)
                ds[block.bb] = np.full(block.shape, block_id + 1, dtype="uint32")
                log_fn(f"processed block {block_id}")


class FailingTask(FillTask):
    """Deterministically fail odd blocks on first attempt (reference:
    test/retry/failing_task.py:74-77), succeed on retry."""

    task_name = "failing"

    @classmethod
    def process_job(cls, job_id, job_config, log_fn):
        cfg = job_config["config"]
        marker_dir = cfg["marker_dir"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        with file_reader(cfg["output_path"]) as f:
            ds = f[cfg["output_key"]]
            for block_id in job_config["block_list"]:
                marker = os.path.join(marker_dir, f"attempted_{block_id}")
                if block_id % 2 == 1 and not os.path.exists(marker):
                    open(marker, "w").close()
                    raise RuntimeError(f"injected failure for block {block_id}")
                block = blocking.get_block(block_id)
                ds[block.bb] = np.full(block.shape, block_id + 1, dtype="uint32")
                log_fn(f"processed block {block_id}")


@pytest.mark.parametrize("target", ["local", "threads", "inline"])
def test_fill_task_all_executors(tmp_workdir, tmp_path, target):
    tmp_folder, config_dir = tmp_workdir
    out = str(tmp_path / f"out_{target}.n5")
    task = FillTask(output_path=out, output_key="data", shape=(20, 20, 20),
                    tmp_folder=tmp_folder, config_dir=config_dir,
                    max_jobs=4, target=target)
    assert build([task])
    with file_reader(out, "r") as f:
        data = f["data"][:]
    blocking = Blocking([20, 20, 20], [10, 10, 10])
    for bid in range(blocking.n_blocks):
        assert (data[blocking.get_block(bid).bb] == bid + 1).all()
    assert task.complete()


def test_retry_fills_failed_blocks(tmp_workdir, tmp_path):
    tmp_folder, config_dir = tmp_workdir
    ConfigDir(config_dir).write_global_config(
        {"block_shape": [10, 10, 10], "max_num_retries": 2})
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir)
    out = str(tmp_path / "out.n5")
    task = FailingTask(output_path=out, output_key="data", shape=(20, 20, 20),
                       tmp_folder=tmp_folder, config_dir=config_dir,
                       max_jobs=4, target="local")
    task.task_config["marker_dir"] = marker_dir

    # marker_dir must reach the workers through the task-specific config
    orig = task.run_jobs

    def run_jobs(block_list, cfg, **kw):
        cfg = {**cfg, "marker_dir": marker_dir}
        return orig(block_list, cfg, **kw)

    task.run_jobs = run_jobs
    assert build([task])
    with file_reader(out, "r") as f:
        data = f["data"][:]
    blocking = Blocking([20, 20, 20], [10, 10, 10])
    for bid in range(blocking.n_blocks):
        assert (data[blocking.get_block(bid).bb] == bid + 1).all(), bid


def test_no_retry_raises(tmp_workdir, tmp_path):
    tmp_folder, config_dir = tmp_workdir  # max_num_retries = 0
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir)
    out = str(tmp_path / "out.n5")
    task = FailingTask(output_path=out, output_key="data", shape=(20, 20, 20),
                       tmp_folder=tmp_folder, config_dir=config_dir,
                       max_jobs=1, target="local")
    orig = task.run_jobs

    def run_jobs(block_list, cfg, **kw):
        return orig(block_list, {**cfg, "marker_dir": marker_dir}, **kw)

    task.run_jobs = run_jobs
    assert not build([task])
    with pytest.raises(FailedJobsError):
        task.run_impl()
    # failed logs renamed -> target invalid -> task not complete
    assert not task.complete()


def test_workflow_resume_skips_complete(tmp_workdir, tmp_path):
    tmp_folder, config_dir = tmp_workdir
    out = str(tmp_path / "out.n5")
    runs = []

    class Recording(FillTask):
        task_name = "recording"

        def run_impl(self):
            runs.append(1)
            super().run_impl()

    t = Recording(output_path=out, output_key="d", shape=(10, 10, 10),
                  tmp_folder=tmp_folder, config_dir=config_dir,
                  max_jobs=1, target="inline")
    assert build([t])
    assert build([Recording(output_path=out, output_key="d", shape=(10, 10, 10),
                            tmp_folder=tmp_folder, config_dir=config_dir,
                            max_jobs=1, target="inline")])
    assert len(runs) == 1  # second build skipped the complete task


def test_dependency_chain_order(tmp_workdir):
    tmp_folder, config_dir = tmp_workdir
    order = []

    class T(Task):
        def __init__(self, name, dep=None):
            self.name, self.dep = name, dep
            super().__init__()
            self._done = False

        def requires(self):
            return self.dep

        def output(self):
            class _T:
                def exists(s):
                    return self._done
            _t = _T()
            _t.path = self.name
            return _t

        @property
        def task_id(self):
            return self.name

        def run(self):
            order.append(self.name)
            self._done = True

    a = T("a")
    b = T("b", a)
    c = T("c", b)
    assert build([c])
    assert order == ["a", "b", "c"]


def test_log_parsing_helpers(tmp_path):
    lp = str(tmp_path / "x.log")
    with open(lp, "w") as f:
        f.write("2026-01-01T00:00:00.000000: processed block 3\n")
        f.write("2026-01-01T00:00:05.000000: processed block 7\n")
        f.write("2026-01-01T00:00:09.000000: processed job 0\n")
    assert runtime.parse_job_success(lp, 0)
    assert not runtime.parse_job_success(lp, 1)
    assert runtime.parse_processed_blocks(lp) == {3, 7}
    rt = runtime.parse_job_runtime(lp)
    assert rt is not None and abs(rt - 9.0) < 1.0


def test_bounded_pool_inline_and_threaded():
    """BoundedPool(0) runs inline (sequential reference mode); a threaded
    pool completes everything by close() and bounds in-flight futures."""
    from cluster_tools_tpu.core.runtime import BoundedPool

    done = []
    with BoundedPool(0) as pool:
        pool.submit(done.append, 1)
        assert done == [1]  # synchronous: visible immediately

    results = []
    with BoundedPool(2, max_inflight=3) as pool:
        for i in range(20):
            pool.submit(results.append, i)
            assert len(pool._pending) <= 3
    assert sorted(results) == list(range(20))


def test_stage_accumulator_thread_safety():
    """Regression (ISSUE 15 satellite): the global stage accumulator
    must hold up under 8 concurrent BoundedPool-style writers.  Unit
    additions (1.0 / 1 / one byte) make the expected totals EXACT — a
    lost read-modify-write shows up as a missing integer, not float
    noise."""
    import threading

    from cluster_tools_tpu.core.runtime import (BoundedPool, stage,
                                                stage_add, stage_bytes)

    n_threads, n_iter = 8, 500
    st0 = runtime.stages_snapshot()
    cn0 = runtime.counts_snapshot()
    by0 = runtime.bytes_snapshot()
    barrier = threading.Barrier(n_threads)

    def hammer():
        barrier.wait()      # maximize interleaving
        for _ in range(n_iter):
            stage_add("host-map", 1.0)
            stage_bytes("host-map", 1)
            with stage("host-scan"):
                pass

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * n_iter
    assert runtime.stages_delta(st0)["host-map"] == float(total)
    cn = runtime.counts_delta(cn0)
    assert cn["host-map"] == total and cn["host-scan"] == total
    assert runtime.bytes_delta(by0)["host-map"] == float(total)

    # same guarantee through the pool the drains actually use
    cn1 = runtime.counts_snapshot()
    with BoundedPool(n_threads) as pool:
        for _ in range(total):
            pool.submit(stage_add, "host-reduce", 1.0)
    assert runtime.counts_delta(cn1)["host-reduce"] == total


def test_bounded_pool_surfaces_worker_errors():
    from cluster_tools_tpu.core.runtime import BoundedPool

    def boom():
        raise RuntimeError("worker failed")

    with pytest.raises(RuntimeError, match="worker failed"):
        with BoundedPool(1) as pool:
            pool.submit(boom)

    # inline mode raises at the submit itself
    pool = BoundedPool(0)
    with pytest.raises(RuntimeError, match="worker failed"):
        pool.submit(boom)


# ---------------------------------------------------------------------------
# persistent executable cache (disk tier): serialize -> deserialize ->
# execute round trip, corruption safety, LRU bound, disk clear, and the
# exec_cache telemetry in task status JSONs.  All tests compile TRIVIAL
# jitted programs (sub-second) — the big resident programs are covered by
# the warm bench (BENCH_warm.json) and the slow server test.
# ---------------------------------------------------------------------------


@pytest.fixture()
def exec_disk(tmp_path):
    """Fresh, isolated disk tier; the session's warm in-memory executables
    are saved and restored so this fixture never forces later tests to
    recompile their (expensive) resident programs."""
    saved_cache = dict(runtime._EXEC_CACHE)
    saved_stats = dict(runtime.EXEC_CACHE_STATS)
    runtime._EXEC_CACHE.clear()
    runtime.exec_cache_clear()
    d = str(tmp_path / "exec_cache")
    runtime.exec_cache_configure(d)
    yield d
    runtime.exec_cache_configure(None)
    runtime._EXEC_CACHE.clear()
    runtime._EXEC_CACHE.update(saved_cache)
    runtime.EXEC_CACHE_STATS.update(saved_stats)


def _trivial_compiled(mult: float = 3.0):
    import jax
    import jax.numpy as jnp

    return jax.jit(lambda x: x * mult).lower(jnp.ones((4,))).compile()


def _needs_serialization():
    if runtime._serialize_api() is None:
        pytest.skip("this jax version cannot serialize AOT executables")


def test_exec_cache_disk_roundtrip(exec_disk):
    """Cold: compile + persist.  After a process-death-equivalent memory
    clear, the same key deserializes from disk (no recompile) and the
    loaded executable computes the same results."""
    _needs_serialization()
    key = ("triv", 3.0)
    runtime.compile_cached(key, _trivial_compiled)
    assert runtime.EXEC_CACHE_STATS["compiles"] == 1
    assert runtime.EXEC_CACHE_STATS["disk_writes"] == 1
    assert len(os.listdir(exec_disk)) == 1

    runtime.exec_cache_clear()     # memory only: the blob survives
    assert len(os.listdir(exec_disk)) == 1
    ent = runtime.compile_cached(key, _trivial_compiled)
    assert runtime.EXEC_CACHE_STATS["compiles"] == 0
    assert runtime.EXEC_CACHE_STATS["disk_hits"] == 1
    assert runtime.EXEC_CACHE_STATS["deserialize_s"] > 0
    import jax.numpy as jnp

    np.testing.assert_array_equal(np.asarray(ent(jnp.ones((4,)))),
                                  np.full(4, 3.0, "float32"))
    # memory hit on the NEXT lookup — disk is only the process boundary
    runtime.compile_cached(key, _trivial_compiled)
    assert runtime.EXEC_CACHE_STATS["hits"] == 1


def test_exec_cache_corrupt_blob_recompiles(exec_disk):
    """A damaged blob must cost one recompile, never a crash; the bad
    file is dropped and replaced by the fresh executable."""
    _needs_serialization()
    key = ("triv", 3.0)
    runtime.compile_cached(key, _trivial_compiled)
    blob = [f for f in os.listdir(exec_disk) if f.endswith(".jexec")][0]
    with open(os.path.join(exec_disk, blob), "wb") as f:
        f.write(b"not an executable")
    runtime.exec_cache_clear()
    runtime.compile_cached(key, _trivial_compiled)
    assert runtime.EXEC_CACHE_STATS["compiles"] == 1
    assert runtime.EXEC_CACHE_STATS["disk_misses"] == 1
    assert runtime.EXEC_CACHE_STATS["disk_hits"] == 0
    # the recompile re-persisted a good blob
    runtime.exec_cache_clear()
    runtime.compile_cached(key, _trivial_compiled)
    assert runtime.EXEC_CACHE_STATS["disk_hits"] == 1


def test_exec_cache_clear_disk(exec_disk):
    """exec_cache_clear(disk=True) purges the persisted tier AND resets
    the counters (satellite: the full cold-start reset)."""
    _needs_serialization()
    runtime.compile_cached(("a",), _trivial_compiled)
    runtime.compile_cached(("b",), lambda: _trivial_compiled(5.0))
    assert len(os.listdir(exec_disk)) == 2
    runtime.exec_cache_clear(disk=True)
    assert [f for f in os.listdir(exec_disk)
            if f.endswith(".jexec")] == []
    assert runtime.EXEC_CACHE_STATS["compiles"] == 0
    assert runtime.EXEC_CACHE_STATS["disk_writes"] == 0
    # cold again: both keys recompile
    runtime.compile_cached(("a",), _trivial_compiled)
    assert runtime.EXEC_CACHE_STATS["compiles"] == 1


def test_exec_cache_lru_eviction(exec_disk):
    """The disk tier is size-bounded: oldest-touched blobs evict first."""
    _needs_serialization()
    runtime.compile_cached(("a",), _trivial_compiled)
    blob = os.path.join(exec_disk, os.listdir(exec_disk)[0])
    one = os.path.getsize(blob)
    # bound holds ONE blob (plus slack): writing a second evicts the first
    runtime.exec_cache_configure(exec_disk, max_bytes=int(one * 1.5))
    os.utime(blob, (1, 1))    # force 'a' to be the LRU entry
    runtime.compile_cached(("b",), lambda: _trivial_compiled(5.0))
    assert runtime.EXEC_CACHE_STATS["disk_evictions"] == 1
    assert len([f for f in os.listdir(exec_disk)
                if f.endswith(".jexec")]) == 1
    # 'a' is gone: a fresh process would recompile it, 'b' still loads
    runtime.exec_cache_clear()
    runtime.compile_cached(("b",), lambda: _trivial_compiled(5.0))
    assert runtime.EXEC_CACHE_STATS["disk_hits"] == 1
    runtime.compile_cached(("a",), _trivial_compiled)
    assert runtime.EXEC_CACHE_STATS["compiles"] == 1


def test_exec_cache_fingerprint_binds_toolchain(exec_disk, monkeypatch):
    """The digest covers (jax/jaxlib version, device topology): a version
    bump means the old blob is simply never found — a MISS, not a load
    of an incompatible executable."""
    _needs_serialization()
    key = ("triv", 3.0)
    runtime.compile_cached(key, _trivial_compiled)
    runtime.exec_cache_clear()
    monkeypatch.setattr(runtime, "_exec_cache_fingerprint",
                        lambda: "jax-from-the-future")
    runtime.compile_cached(key, _trivial_compiled)
    assert runtime.EXEC_CACHE_STATS["disk_hits"] == 0
    assert runtime.EXEC_CACHE_STATS["compiles"] == 1


def test_status_records_exec_cache(tmp_workdir, tmp_path):
    """Every task status JSON carries the exec_cache delta next to
    stage_counts (empty for tasks that never touch the executor cache)."""
    import json

    tmp_folder, config_dir = tmp_workdir
    out = str(tmp_path / "out.n5")
    task = FillTask(output_path=out, output_key="data", shape=(20, 20, 20),
                    tmp_folder=tmp_folder, config_dir=config_dir,
                    max_jobs=2, target="inline")
    assert build([task])
    with open(task.output().path) as f:
        status = json.load(f)
    assert "exec_cache" in status
    assert status["exec_cache"] == {}


def test_global_config_activates_disk_tier(tmp_path):
    """Setting ``exec_cache_dir`` in the global config wires the disk
    tier at task construction — the workflow-level opt-in."""
    saved = dict(runtime._DISK_TIER)
    try:
        d = str(tmp_path / "cfg_cache")
        config_dir = str(tmp_path / "configs")
        ConfigDir(config_dir).write_global_config(
            {"block_shape": [10, 10, 10], "exec_cache_dir": d,
             "exec_cache_max_bytes": 123456})
        FillTask(output_path=str(tmp_path / "o.n5"), output_key="d",
                 shape=(10, 10, 10), tmp_folder=str(tmp_path / "tmp"),
                 config_dir=config_dir, max_jobs=1, target="inline")
        assert runtime._exec_cache_dir() == d
        assert runtime._exec_cache_max_bytes() == 123456
    finally:
        runtime._DISK_TIER.update(saved)


# ---------------------------------------------------------------------------
# live-buffer ledger (ISSUE 17 tentpole b): bytes pinned by warm caches,
# exported through metrics_families and status JSONs
# ---------------------------------------------------------------------------

def test_ledger_accounting_basics():
    runtime.ledger_clear()
    try:
        runtime.ledger_add("fragment_cache", 100, 1)
        runtime.ledger_add("fragment_cache", 50, 1)
        runtime.ledger_set("raw_cache", 2048, 1)
        snap = runtime.ledger_snapshot()
        assert snap["fragment_cache"] == {"bytes": 150, "entries": 2}
        assert snap["raw_cache"] == {"bytes": 2048, "entries": 1}
        # releases clamp at zero — an over-release is a bookkeeping bug,
        # not a reason to report negative resident bytes
        runtime.ledger_add("fragment_cache", -500, -5)
        assert runtime.ledger_snapshot()["fragment_cache"] \
            == {"bytes": 0, "entries": 0}
        # snapshots are copies: mutating one never corrupts the ledger
        snap["raw_cache"]["bytes"] = -1
        assert runtime.ledger_snapshot()["raw_cache"]["bytes"] == 2048
        runtime.ledger_clear("raw_cache")
        assert "raw_cache" not in runtime.ledger_snapshot()
    finally:
        runtime.ledger_clear()


def test_ledger_metrics_families():
    runtime.ledger_clear()
    try:
        runtime.ledger_set("exec_cache", 4096, 2)
        fams = {f[0]: f for f in runtime.metrics_families()}
        assert fams["ctt_ledger_bytes"][3] \
            == [({"account": "exec_cache"}, 4096)]
        assert fams["ctt_ledger_entries"][3] \
            == [({"account": "exec_cache"}, 2)]
        runtime.ledger_clear()
        fams = {f[0]: f for f in runtime.metrics_families()}
        assert fams["ctt_ledger_bytes"][3] == [(None, 0)]
    finally:
        runtime.ledger_clear()


def test_exec_cache_ledger_tracks_blob_bytes(exec_disk):
    """compile_cached accounts the serialized blob's size under the
    'exec_cache' ledger account — on the build path AND the disk-hit
    path — and exec_cache_clear releases it."""
    _needs_serialization()
    runtime.ledger_clear()
    runtime.compile_cached(("triv", 3.0), _trivial_compiled)
    blob = [f for f in os.listdir(exec_disk) if f.endswith(".jexec")][0]
    nbytes = os.path.getsize(os.path.join(exec_disk, blob))
    assert nbytes > 0
    led = runtime.ledger_snapshot()["exec_cache"]
    assert led == {"bytes": nbytes, "entries": 1}
    runtime.exec_cache_clear()
    assert "exec_cache" not in runtime.ledger_snapshot()
    # warm re-load from disk re-pins the same footprint
    runtime.compile_cached(("triv", 3.0), _trivial_compiled)
    assert runtime.EXEC_CACHE_STATS["disk_hits"] == 1
    assert runtime.ledger_snapshot()["exec_cache"] \
        == {"bytes": nbytes, "entries": 1}


def test_fragment_cache_puts_feed_ledger():
    """The fused pipeline's cache-put helpers keep the ledger in sync,
    overwrites included, and clear_caches releases everything."""
    from cluster_tools_tpu.workflows import fused_pipeline as fp

    fp.clear_caches()
    try:
        fp._fragment_cache_put(("p", "k", 0), np.zeros(10, "uint16"),
                               0, ((0, 1),))
        fp._fragment_cache_put(("p", "k", 1), np.zeros(5, "uint16"),
                               0, ((0, 1),))
        fp._raw_cache_put(("p", "k"), np.zeros(7, "uint8"), False)
        snap = runtime.ledger_snapshot()
        assert snap["fragment_cache"] == {"bytes": 30, "entries": 2}
        assert snap["raw_cache"] == {"bytes": 7, "entries": 1}
        # overwriting a key releases the old entry's bytes first
        fp._fragment_cache_put(("p", "k", 0), np.zeros(20, "uint16"),
                               0, ((0, 1),))
        assert runtime.ledger_snapshot()["fragment_cache"] \
            == {"bytes": 50, "entries": 2}
        assert fp._FRAGMENT_CACHE[("p", "k", 0)][0].nbytes == 40
        fp.clear_caches()
        snap = runtime.ledger_snapshot()
        assert "fragment_cache" not in snap and "raw_cache" not in snap
    finally:
        fp.clear_caches()
