"""Pipeline parallelism: microbatched stage execution over a mesh axis.

The reference has no pipeline parallelism (SURVEY §2.4.9 lists it as absent;
its closest structural analog is the checkerboard two-pass schedule,
§2.4.3).  The TPU framework provides it as a first-class primitive so deep
models can be staged across chips when activations, not parameters, are the
memory bound: stage ``i`` of the model lives on device ``i`` along the
``pipe`` mesh axis, microbatches stream through the classic GPipe schedule
(``n_micro + n_stages - 1`` steps), and activations hop stage-to-stage with
``lax.ppermute`` over ICI — the same collective the sharded stencil uses
(parallel/stencil.py).

Everything is a single jitted SPMD program: no host round-trips between
stages, no data-dependent shapes.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def stack_stage_params(per_stage_params) -> Any:
    """Stack a list of per-stage parameter pytrees along a new leading axis
    (the axis ``pipeline_apply`` shards over the pipe dimension)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def pipeline_apply(fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stage_params: Any, x: jnp.ndarray, mesh: Mesh,
                   axis: str = "pipe") -> jnp.ndarray:
    """Apply ``n_stages`` chained stages to microbatched input.

    ``fn(params_i, a) -> a`` is one stage (activation-shape preserving);
    ``stage_params`` has a leading ``n_stages`` axis (see
    :func:`stack_stage_params`); ``x`` is ``(n_micro, *mb_shape)``.
    Returns ``(n_micro, *mb_shape)`` equal to applying stages 0..n-1 in
    order to every microbatch.

    Schedule: T = n_micro + n_stages - 1 steps; at step t, stage 0 ingests
    microbatch t (while t < n_micro), every stage applies ``fn``, the
    result is ppermuted to the next stage, and the last stage emits
    microbatch t - (n_stages - 1).  The emitted buffer is summed over the
    pipe axis at the end (all other stages contribute zeros), so the result
    is replicated — callers re-shard as needed.
    """
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    n_stages = mesh.shape[axis]
    n_micro = x.shape[0]
    n_steps = n_micro + n_stages - 1
    # pad the microbatch axis to n_steps so step indices never leave the
    # buffer (the pads are never consumed as real output)
    pad = [(0, n_steps - n_micro)] + [(0, 0)] * (x.ndim - 1)
    x_pad = jnp.pad(x, pad)

    def stage_body(params, xp):
        # params: leading stage axis of size 1 (this device's slice)
        params = jax.tree_util.tree_map(lambda p: p[0], params)
        idx = jax.lax.axis_index(axis)
        perm = [(i, i + 1) for i in range(n_stages - 1)]

        def step(t, carry):
            act, out = carry
            mb = jax.lax.dynamic_index_in_dim(xp, t, 0, keepdims=False)
            inp = jnp.where(idx == 0, mb, act)
            res = fn(params, inp)
            emit = jnp.where(idx == n_stages - 1, res, jnp.zeros_like(res))
            out = jax.lax.dynamic_update_index_in_dim(out, emit, t, 0)
            act = jax.lax.ppermute(res, axis, perm)
            return act, out

        # initial carries must already be marked device-varying over the
        # pipe axis (the loop body makes them varying via ppermute/where)
        from .stencil import device_varying

        act0 = device_varying(jnp.zeros_like(xp[0]), axis)
        out0 = device_varying(jnp.zeros_like(xp), axis)
        _, out = jax.lax.fori_loop(0, n_steps, step, (act0, out0))
        # only the last stage wrote non-zeros; broadcast via psum
        return jax.lax.psum(out, axis)

    spec_params = P(axis)
    spec_x = P()  # replicated input microbatches
    result = shard_map(
        stage_body, mesh=mesh,
        in_specs=(spec_params, spec_x), out_specs=spec_x,
    )(stage_params, x_pad)
    # microbatch t exits the pipe at step t + n_stages - 1
    return result[n_stages - 1:n_stages - 1 + n_micro]


def make_pipe_mesh(n_stages: int, n_devices: int = None) -> Mesh:
    """Mesh with a leading ``pipe`` axis of size ``n_stages`` (remaining
    devices ride a ``data`` axis)."""
    devices = jax.devices()
    n = n_devices or len(devices)
    assert n % n_stages == 0, (n, n_stages)
    arr = np.array(devices[:n]).reshape(n_stages, n // n_stages)
    return Mesh(arr, ("pipe", "data"))
