"""Top-level segmentation pipelines (reference: cluster_tools/workflows.py).

``ProblemWorkflow`` assembles the multicut problem container (graph +
features + costs, reference workflows.py:29-108); the segmentation workflows
chain it with the solver ladder and the final write
(MulticutSegmentationWorkflow, reference workflows.py:204-233).
"""

from __future__ import annotations

import os
from typing import List, Optional

from ..core.workflow import FileTarget, Task
from .costs import EdgeCostsWorkflow
from .features import EdgeFeaturesWorkflow
from .graph import GraphWorkflow
from .multicut import MulticutWorkflow
from .write import WriteAssignments


class ProblemWorkflow(Task):
    """graph -> edge features -> costs into one problem container
    (reference: ProblemWorkflow, workflows.py:29-108)."""

    def __init__(self, input_path: str, input_key: str, ws_path: str,
                 ws_key: str, problem_path: str, tmp_folder: str,
                 config_dir: str, max_jobs: int = 1, target: str = "local",
                 n_scales_graph: int = 1,
                 offsets: Optional[List[List[int]]] = None,
                 compute_costs: bool = True,
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.problem_path = problem_path
        self.n_scales_graph = n_scales_graph
        self.offsets = offsets
        self.compute_costs = compute_costs
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        graph_wf = GraphWorkflow(
            input_path=self.ws_path, input_key=self.ws_key,
            graph_path=self.problem_path, output_key="s0/graph",
            n_scales=self.n_scales_graph, dependency=self.dependency,
            **self._common())
        feat_wf = EdgeFeaturesWorkflow(
            input_path=self.input_path, input_key=self.input_key,
            labels_path=self.ws_path, labels_key=self.ws_key,
            graph_path=self.problem_path, graph_key="s0/graph",
            output_path=self.problem_path,
            output_key="features", offsets=self.offsets, dependency=graph_wf,
            **self._common())
        if not self.compute_costs:
            # stitching / agglomeration consumers work on raw features
            # (reference: SegmentationWorkflowBase._problem_tasks with
            # compute_costs=False, workflows.py:149-180)
            return feat_wf
        return EdgeCostsWorkflow(
            features_path=self.problem_path, features_key="features",
            output_path=self.problem_path, output_key="s0/costs",
            graph_path=self.problem_path, graph_key="s0/graph",
            dependency=feat_wf, **self._common())

    def output(self):
        if not self.compute_costs:
            return FileTarget(os.path.join(self.tmp_folder,
                                           "merge_edge_features.status"))
        return FileTarget(os.path.join(self.tmp_folder,
                                       "probs_to_costs.status"))


class MulticutSegmentationWorkflow(Task):
    """Problem -> hierarchical multicut -> write segmentation
    (reference: MulticutSegmentationWorkflow, workflows.py:204-233).

    ``ws_path/ws_key`` are the watershed fragments (chain WatershedWorkflow
    upstream via ``dependency`` to produce them)."""

    def __init__(self, input_path: str, input_key: str, ws_path: str,
                 ws_key: str, problem_path: str, output_path: str,
                 output_key: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 n_scales: int = 1,
                 offsets: Optional[List[List[int]]] = None,
                 fused: bool = False,
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.problem_path = problem_path
        self.output_path = output_path
        self.output_key = output_key
        self.n_scales = n_scales
        self.offsets = offsets
        #: fused=True computes the watershed fragments INSIDE the problem
        #: assembly (one device program per block: ws + relabel + RAG +
        #: features, workflows/fused_pipeline.py) — no WatershedWorkflow
        #: dependency needed; ws_path/ws_key become outputs
        self.fused = fused
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        assignment_path = os.path.join(self.tmp_folder,
                                       "multicut_assignments.npy")
        write_bs_kw = {}
        if self.fused:
            if self.offsets is not None:
                raise ValueError("fused=True supports boundary maps only "
                                 "(affinity offsets need the split chain)")
            from .fused_pipeline import (FusedProblemWorkflow,
                                         mesh_resident_block_shape)

            problem = FusedProblemWorkflow(
                input_path=self.input_path, input_key=self.input_key,
                ws_path=self.ws_path, ws_key=self.ws_key,
                problem_path=self.problem_path,
                dependency=self.dependency, **self._common())
            # mesh-resident fused chain: fragments staged one slab per
            # shard — the assignment write iterates the same slab grid so
            # the in-RAM fragment cache hits (store reads otherwise)
            mesh_bs = mesh_resident_block_shape(
                self.config_dir, self.input_path, self.input_key)
            if mesh_bs:
                write_bs_kw = {"block_shape": mesh_bs}
        else:
            problem = ProblemWorkflow(
                input_path=self.input_path, input_key=self.input_key,
                ws_path=self.ws_path, ws_key=self.ws_key,
                problem_path=self.problem_path, offsets=self.offsets,
                dependency=self.dependency, **self._common())
        multicut = MulticutWorkflow(
            problem_path=self.problem_path, assignment_path=assignment_path,
            n_scales=self.n_scales, dependency=problem, **self._common())
        return WriteAssignments(
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=assignment_path, identifier="multicut",
            dependency=multicut, **write_bs_kw, **self._common())

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "write_multicut.status"))


class LiftedMulticutSegmentationWorkflow(Task):
    """Problem -> lifted features from semantic priors -> hierarchical
    lifted multicut -> write (reference:
    LiftedMulticutSegmentationWorkflow, workflows.py:236-323)."""

    def __init__(self, input_path: str, input_key: str, ws_path: str,
                 ws_key: str, labels_path: str, labels_key: str,
                 problem_path: str, output_path: str, output_key: str,
                 lifted_prefix: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local", n_scales: int = 1,
                 nh_graph_depth: int = 4, mode: str = "all",
                 offsets: Optional[List[List[int]]] = None,
                 clear_labels_path: str = "", clear_labels_key: str = "",
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.problem_path = problem_path
        self.output_path = output_path
        self.output_key = output_key
        self.lifted_prefix = lifted_prefix
        self.n_scales = n_scales
        self.nh_graph_depth = nh_graph_depth
        self.mode = mode
        self.offsets = offsets
        self.clear_labels_path = clear_labels_path
        self.clear_labels_key = clear_labels_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        from .lifted_features import LiftedFeaturesFromNodeLabelsWorkflow
        from .lifted_multicut import LiftedMulticutWorkflow

        assignment_path = os.path.join(self.tmp_folder,
                                       "lifted_multicut_assignments.npy")
        problem = ProblemWorkflow(
            input_path=self.input_path, input_key=self.input_key,
            ws_path=self.ws_path, ws_key=self.ws_key,
            problem_path=self.problem_path, offsets=self.offsets,
            dependency=self.dependency, **self._common())
        lifted_feats = LiftedFeaturesFromNodeLabelsWorkflow(
            ws_path=self.ws_path, ws_key=self.ws_key,
            labels_path=self.labels_path, labels_key=self.labels_key,
            graph_path=self.problem_path, graph_key="s0/graph",
            output_path=self.problem_path,
            nh_out_key=f"s0/lifted_nh_{self.lifted_prefix}",
            feat_out_key=f"s0/lifted_costs_{self.lifted_prefix}",
            prefix=self.lifted_prefix, nh_graph_depth=self.nh_graph_depth,
            mode=self.mode, clear_labels_path=self.clear_labels_path,
            clear_labels_key=self.clear_labels_key, dependency=problem,
            **self._common())
        lifted_mc = LiftedMulticutWorkflow(
            problem_path=self.problem_path, assignment_path=assignment_path,
            lifted_prefix=self.lifted_prefix, n_scales=self.n_scales,
            dependency=lifted_feats, **self._common())
        return WriteAssignments(
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=assignment_path, identifier="lifted_multicut",
            dependency=lifted_mc, **self._common())

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "write_lifted_multicut.status"))


class AgglomerativeClusteringWorkflow(Task):
    """Problem (features only) -> global agglomerative clustering -> write
    (reference: AgglomerativeClusteringWorkflow, workflows.py:327-358)."""

    def __init__(self, input_path: str, input_key: str, ws_path: str,
                 ws_key: str, problem_path: str, output_path: str,
                 output_key: str, threshold: float, tmp_folder: str,
                 config_dir: str, max_jobs: int = 1, target: str = "local",
                 offsets: Optional[List[List[int]]] = None,
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.problem_path = problem_path
        self.output_path = output_path
        self.output_key = output_key
        self.threshold = threshold
        self.offsets = offsets
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        from .agglomerative_clustering import AgglomerativeClustering

        assignment_path = os.path.join(self.tmp_folder,
                                       "agglomeration_assignments.npy")
        problem = ProblemWorkflow(
            input_path=self.input_path, input_key=self.input_key,
            ws_path=self.ws_path, ws_key=self.ws_key,
            problem_path=self.problem_path, offsets=self.offsets,
            compute_costs=False, dependency=self.dependency,
            **self._common())
        agglo = AgglomerativeClustering(
            problem_path=self.problem_path, assignment_path=assignment_path,
            threshold=self.threshold, dependency=problem, **self._common())
        return WriteAssignments(
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=assignment_path,
            identifier="agglomerative_clustering", dependency=agglo,
            **self._common())

    def output(self):
        return FileTarget(os.path.join(
            self.tmp_folder, "write_agglomerative_clustering.status"))


class SimpleStitchingWorkflow(Task):
    """Problem (features only) -> merge block-boundary edges -> write
    (reference: SimpleStitchingWorkflow, workflows.py:361-386)."""

    def __init__(self, input_path: str, input_key: str, ws_path: str,
                 ws_key: str, problem_path: str, output_path: str,
                 output_key: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 edge_size_threshold: int = 0,
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.problem_path = problem_path
        self.output_path = output_path
        self.output_key = output_key
        self.edge_size_threshold = edge_size_threshold
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        from .stitching import StitchingAssignmentsWorkflow

        problem = ProblemWorkflow(
            input_path=self.input_path, input_key=self.input_key,
            ws_path=self.ws_path, ws_key=self.ws_key,
            problem_path=self.problem_path, compute_costs=False,
            dependency=self.dependency, **self._common())
        stitch = StitchingAssignmentsWorkflow(
            problem_path=self.problem_path, labels_path=self.ws_path,
            labels_key=self.ws_key, assignments_path=self.problem_path,
            assignments_key="stitch_assignments", graph_key="s0/graph",
            features_key="features",
            edge_size_threshold=self.edge_size_threshold,
            dependency=problem, **self._common())
        return WriteAssignments(
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=self.problem_path,
            assignment_key="stitch_assignments",
            identifier="simple_stitching", dependency=stitch,
            **self._common())

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "write_simple_stitching.status"))
