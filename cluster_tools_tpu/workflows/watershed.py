"""Blockwise distance-transform watershed.

Re-specification of the reference's ``watershed/`` package
(watershed/watershed.py): per block (with halo) — read boundary/affinity map,
threshold + Euclidean distance transform, seeds from smoothed-DT maxima,
seeded watershed on a height map mixing boundary evidence and inverted DT,
size filter, per-block label offset, write inner block.  All pixel compute
runs on device (ops/edt.py, ops/filters.py, ops/watershed.py); under
``target='tpu'`` the whole per-block pipeline is one jitted program.

2d variants (``apply_dt_2d`` / ``apply_ws_2d``, for anisotropic EM stacks)
process z-slices via vmap over the z axis — the reference loops slices in
Python (watershed.py:211-230); here it is one batched device call.
"""

from __future__ import annotations

import os
from functools import lru_cache
from typing import Any, Dict, Optional

import numpy as np

from ..core.blocking import Blocking
from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import Task
from .relabel import RelabelWorkflow


def _normalize_input(data: np.ndarray, cfg) -> np.ndarray:
    """Channel agglomeration + range normalization + optional inversion —
    the single policy shared by every reader (reference:
    watershed.py:267-283 _read_data)."""
    if data.ndim == 4:
        agglo = cfg.get("agglomerate_channels", "mean")
        data = data.max(axis=0) if agglo == "max" else data.mean(axis=0)
    mx = data.max()
    if mx > 1.0:
        data = data / 255.0 if mx <= 255 else data / mx
    if cfg.get("invert_inputs", False):
        data = 1.0 - data
    return data


def as_normalized_float(block: np.ndarray) -> np.ndarray:
    """Raw-path inverse: a uint8 block back to the [0,1] float scale the
    device pipeline uses (shared by every raw-read fallback site)."""
    if block.dtype == np.uint8:
        return block.astype("float32") / 255.0
    return np.asarray(block)


def _channel_slice(ds, cfg):
    cb = cfg.get("channel_begin", 0)
    ce = cfg.get("channel_end", None)
    return slice(cb, ds.shape[0] if ce is None else ce)


def _read_input(ds, bb, cfg) -> np.ndarray:
    """Read + normalize boundary evidence (clipped bounding-box variant)."""
    if ds.ndim == len(bb) + 1:
        data = ds[(_channel_slice(ds, cfg),) + bb].astype("float32")
    else:
        data = ds[bb].astype("float32")
    return _normalize_input(data, cfg)


def reflect_indices(start: int, stop: int, n: int) -> np.ndarray:
    """Volume-level reflection indices for ``range(start, stop)`` over an
    axis of length n: out-of-volume positions fold back as the mirror of
    the WHOLE axis (period 2n-2), so every reader of a block's outer
    window — per-block store reads and resident-volume slicing alike —
    sees identical phantom content (reflecting only the clipped block
    read would make the phantom depend on the block's clip)."""
    idx = np.arange(start, stop)
    if n == 1:
        return np.zeros_like(idx)
    period = 2 * n - 2
    j = np.mod(idx, period)
    return np.where(j < n, j, period - j)


def read_outer_reflect(ds, begin, block_shape, halo) -> np.ndarray:
    """Read ``[begin-halo, begin+block_shape+halo)`` with out-of-volume
    parts filled by volume-level reflection (see reflect_indices)."""
    shape = ds.shape[-len(begin):]
    ridx = [reflect_indices(b - h, b + bs + h, n)
            for b, h, bs, n in zip(begin, halo, block_shape, shape)]
    los = [int(r.min()) for r in ridx]
    his = [int(r.max()) + 1 for r in ridx]
    data = np.asarray(ds[tuple(slice(lo, hi) for lo, hi in zip(los, his))])
    if all(len(r) == hi - lo and (np.diff(r) == 1).all()
           for r, lo, hi in zip(ridx, los, his)):
        return data  # interior block: contiguous read, no gather
    return data[np.ix_(*[r - lo for r, lo in zip(ridx, los)])]


def _read_padded_input(ds, block, cfg, halo, raw: bool = False) -> np.ndarray:
    """Read the block at the uniform outer shape (reflect-padded at volume
    borders), same normalization policy as _read_input.  ``raw=True`` skips
    the host-side float conversion for 3d uint8 stores — the streamed
    device pipeline normalizes on device, so only a quarter of the bytes
    cross the host->device link."""
    from .inference import load_with_halo

    if ds.ndim == len(block.begin) + 1:
        data = load_with_halo(
            ds, block.begin, cfg["block_shape"], halo,
            channel_slice=_channel_slice(ds, cfg)).astype("float32")
    else:
        data = read_outer_reflect(ds, block.begin, cfg["block_shape"], halo)
        # the device pipeline always divides uint8 by 255, so the raw path
        # is only taken when that matches _normalize_input's data-dependent
        # rule (max > 1); degenerate {0,1} blocks go through the host rule
        if raw and data.dtype == np.uint8 and data.max() > 1 \
                and not cfg.get("invert_inputs", False):
            return data
        data = data.astype("float32")
    return _normalize_input(data, cfg)


def suppress_maxima(points: np.ndarray, radii: np.ndarray) -> np.ndarray:
    """Greedy distance-based non-maximum suppression of seed candidates
    (reference: watershed.py:199-203, nifty nonMaximumDistanceSuppression):
    in decreasing distance-transform order, a candidate is dropped when it
    lies inside the dt-radius of an already accepted (stronger) maximum.
    Returns the kept ``(K, ndim)`` integer coordinates."""
    if len(points) == 0:
        return points
    order = np.argsort(-radii)
    pts = points[order].astype("float64")
    rad = radii[order].astype("float64")
    kept = [0]
    for i in range(1, len(pts)):
        kp = pts[kept]
        d2 = ((kp - pts[i]) ** 2).sum(axis=1)
        if not (d2 < rad[kept] ** 2).any():
            kept.append(i)
    return points[order[kept]]


def run_ws_block(data: np.ndarray, cfg: Dict[str, Any],
                 mask: Optional[np.ndarray] = None) -> np.ndarray:
    """The per-block watershed pipeline (reference: _ws_block
    watershed.py:285-341), device compute with host glue."""
    import jax.numpy as jnp

    from ..ops.components import connected_components
    from ..ops.edt import distance_transform_edt
    from ..ops.filters import gaussian, local_maxima
    from ..ops.watershed import (seeded_watershed, seeded_watershed_batched,
                                 size_filter)

    import jax

    threshold = cfg.get("threshold", 0.25)
    sigma_seeds = cfg.get("sigma_seeds", 2.0)
    sigma_weights = cfg.get("sigma_weights", 2.0)
    min_size = cfg.get("size_filter", 25)
    alpha = cfg.get("alpha", 0.8)
    pixel_pitch = cfg.get("pixel_pitch")
    dt_2d = cfg.get("apply_dt_2d", False)
    ws_2d = cfg.get("apply_ws_2d", False)

    x = jnp.asarray(data)
    jmask = None if mask is None else jnp.asarray(mask.astype(bool))

    # distance to boundaries (vigra distanceTransform equivalent)
    fg = x < threshold
    if jmask is not None:
        fg = fg & jmask
    if dt_2d or ws_2d:
        # per-slice 2d EDT via the axes parameter: slices fold into the
        # scanline batch (a vmap here would scramble the Pallas kernel's
        # grid indices — ops/edt.py handles the batching natively)
        dt = distance_transform_edt(fg, axes=(1, 2))
    else:
        sampling = tuple(pixel_pitch) if pixel_pitch else None
        dt = distance_transform_edt(fg, sampling=sampling)

    # height map: boundary evidence blended with inverted DT
    # (reference fit_to_hmap/_make_hmap, utils/volume_utils.py:294-391)
    hmap = gaussian(x, sigma_weights) if sigma_weights else x
    dmax = jnp.maximum(dt.max(), 1e-6)
    height = alpha * hmap + (1.0 - alpha) * (1.0 - dt / dmax)

    if ws_2d:
        # independent watershed per z-slice (reference: watershed.py:211-230
        # loops slices; here one vmapped device program).  Per-slice labels
        # are made unique across slices by a per-slice offset.
        dt_smooth = (jax.vmap(lambda d: gaussian(d, sigma_seeds))(dt)
                     if sigma_seeds else dt)
        maxima = jax.vmap(lambda d, f: local_maxima(d, 2) & f)(dt_smooth, fg)
        # seed clusters are tiny: stencil propagation beats pointer jumping
        seeds = jax.vmap(lambda m: connected_components(
            m, connectivity=2, method="propagation"))(maxima)
        ws = seeded_watershed_batched(height, seeds, jmask, connectivity=1)
        # per-slice offsets in host uint64: device int32 would overflow for
        # n_slices * slice_size >= 2**31 (large in-plane blocks)
        ws = np.array(ws).astype(np.uint64)
        slice_size = np.uint64(np.prod(data.shape[1:]))
        offsets = (np.arange(data.shape[0], dtype=np.uint64)
                   * slice_size)[:, None, None]
        ws = np.where(ws > 0, ws + offsets, 0)
    else:
        # seeds: connected maxima clusters of the smoothed DT (tiny
        # clusters: stencil propagation beats gather-heavy pointer jumping)
        dt_smooth = gaussian(dt, sigma_seeds) if sigma_seeds else dt
        maxima = local_maxima(dt_smooth, radius=2) & fg
        if cfg.get("non_maximum_suppression", False):
            # distance-based suppression of weaker maxima (reference:
            # watershed.py:179-207 nonMaximumDistanceSuppression path).
            # Suppression runs over one representative per connected
            # maxima component (the component's highest-dt voxel), so a
            # plateau contributes a single candidate — same baseline as
            # the plain path — and candidate counts stay small (hundreds
            # per block): a cheap host step between two device programs.
            comp = np.asarray(connected_components(
                maxima, connectivity=len(data.shape),
                method="propagation"))
            pts = np.argwhere(comp > 0)
            if len(pts):
                radii = np.asarray(dt)[tuple(pts.T)]
                cids = comp[tuple(pts.T)]
                order = np.lexsort((-radii, cids))
                first = np.r_[True, np.diff(cids[order]) != 0]
                reps = pts[order[first]]
                kept = suppress_maxima(reps, radii[order[first]])
            else:
                kept = pts
            seeds_np = np.zeros(data.shape, "int32")
            seeds_np[tuple(kept.T)] = np.arange(1, len(kept) + 1)
            seeds = jnp.asarray(seeds_np)
        else:
            seeds = connected_components(maxima,
                                         connectivity=len(data.shape),
                                         method="propagation")
        method = _ws_algorithm(cfg)
        if method == "coarse" and jmask is None and data.ndim == 3:
            # shared watershed core with the fused pipeline
            # (workflows/fused_pipeline._resident_program): identical
            # composition -> identical fragment partitions, and the size
            # filter is integrated in the coarse solve
            from ..ops.watershed import seeded_watershed_coarse

            labels, ok = seeded_watershed_coarse(
                height, seeds, min_size=min_size or 0,
                refine_rounds=int(cfg.get("refine_rounds", 3)),
                factor=int(cfg.get("coarse_factor", 2)))
            if ok:
                return np.array(labels).astype("uint64")
            ws = np.array(seeded_watershed(height, seeds, jmask,
                                           connectivity=1))
        else:
            ws = np.array(seeded_watershed(
                height, seeds, jmask, connectivity=1,
                method=None if method == "coarse" else method))
    if min_size:
        ws = size_filter(ws, np.asarray(height), min_size,
                         mask=None if mask is None else mask.astype(bool),
                         per_slice=ws_2d)
    return ws.astype("uint64")


def run_ws_block_host(data: np.ndarray, cfg: Dict[str, Any],
                      mask: Optional[np.ndarray] = None) -> np.ndarray:
    """Per-block DT watershed on HOST scipy C kernels — the CPU analog of
    the device pipeline, built from the reference's kernel family.

    C implementations stand in one-for-one: scipy distance_transform_edt
    for vigra distanceTransform, gaussian_filter for gaussianSmoothing,
    maximum_filter for localMaxima3D, label for
    labelVolumeWithBackground, and the native C++ bucket-queue priority
    flood for watershedsNew (scipy's own watershed_ift ignores its cost
    image in current scipy and is unusable; reference:
    watershed/watershed.py:139-249).  Selected by task config
    ``impl: 'host'`` — the measured stand-in for the reference's
    ``target='local'`` per-block compute in the benchmark baseline
    (vigra/nifty are not installable here), and a working CPU fallback
    for machines without an accelerator.

    Composition notes (kept IDENTICAL to this framework's device
    pipeline so the bench's device<->CPU quality delta isolates the
    watershed implementation, at the cost of three deviations from the
    reference's defaults): the boundary map is smoothed BEFORE blending
    with the inverted DT (the reference's _make_hmap smooths the blended
    map, watershed.py:163-170), seed maxima use a 5x5x5 window (vigra
    localMaxima3D is 3x3x3), and DT/WS run in 3d (the reference defaults
    apply_dt_2d/apply_ws_2d to true for anisotropic stacks)."""
    from scipy import ndimage

    from ..native import seeded_watershed_u8

    threshold = cfg.get("threshold", 0.25)
    sigma_seeds = cfg.get("sigma_seeds", 2.0)
    sigma_weights = cfg.get("sigma_weights", 2.0)
    min_size = cfg.get("size_filter", 25)
    alpha = cfg.get("alpha", 0.8)
    pitch = cfg.get("pixel_pitch")

    fg = data < threshold
    if mask is not None:
        fg &= mask
    dt = ndimage.distance_transform_edt(fg, sampling=pitch).astype("float32")
    hmap = (ndimage.gaussian_filter(data, sigma_weights)
            if sigma_weights else data)
    height = alpha * hmap + (1.0 - alpha) * (1.0 - dt / max(dt.max(), 1e-6))
    dts = ndimage.gaussian_filter(dt, sigma_seeds) if sigma_seeds else dt
    maxima = (dts >= ndimage.maximum_filter(dts, size=5)) & fg
    seeds, _ = ndimage.label(maxima, structure=np.ones((3,) * data.ndim,
                                                       bool))
    hq = np.clip((height - height.min())
                 / max(float(height.max() - height.min()), 1e-6) * 255,
                 0, 255).astype("uint8")
    markers = seeds.astype("int64")
    if mask is not None:
        markers[~mask] = -1  # barrier: the flood never enters the mask
    ws = seeded_watershed_u8(hq, markers)
    if min_size:
        ids, counts = np.unique(ws[ws > 0], return_counts=True)
        small = set(ids[counts < min_size].tolist())
        if small:
            kept = np.where(np.isin(ws, list(small)), 0, ws)
            ws = seeded_watershed_u8(hq, kept)
    ws[ws < 0] = 0
    return ws.astype("uint64")


def iter_ws_blocks_stream(blocks, cfg: Dict[str, Any]):
    """Process a stream of 3d blocks through ONE fused jitted watershed
    pipeline with async dispatch, yielding results in input order: block
    i+1's host->device transfer and compute overlap block i's device->host
    readback (jax's async dispatch queues everything; only the final np
    conversions synchronize).  This is the deployment pattern of the
    blockwise tasks (the inference task's IO/compute overlap, SURVEY §3.4)
    — per-block latency is hidden, the metric is stream throughput.

    3d path only: 2d modes, masks, NMS and pixel_pitch need run_ws_block."""
    import jax.numpy as jnp

    unsupported = [k for k in ("apply_dt_2d", "apply_ws_2d", "pixel_pitch",
                               "non_maximum_suppression") if cfg.get(k)]
    if unsupported:
        raise ValueError(
            f"iter_ws_blocks_stream supports the plain 3d pipeline only; "
            f"{unsupported} need run_ws_block")
    import jax

    from ..core.runtime import stream_window
    from ..ops.watershed import size_filter

    min_size = int(cfg.get("size_filter", 25) or 0)
    # the fused on-device size filter (bincount + regrow in the jitted
    # program) avoids the height/label host round-trip that dominates on
    # accelerators, but its full-length bincount and second flood are a
    # net loss on the CPU backend — there the host size filter is faster.
    # cfg["fuse_size_filter"] overrides the backend default (tests force
    # both paths on the CPU mesh).
    algo = _ws_algorithm(cfg)
    fuse_filter = cfg.get("fuse_size_filter")
    if fuse_filter is None:
        fuse_filter = jax.default_backend() != "cpu"
    if algo == "coarse":
        fuse_filter = True  # integrated in the coarse solve
    pipeline = _ws_pipeline_3d(
        float(cfg.get("threshold", 0.25)),
        float(cfg.get("sigma_seeds", 2.0)),
        float(cfg.get("sigma_weights", 2.0)),
        float(cfg.get("alpha", 0.8)),
        min_size if fuse_filter else 0,
        return_height=not fuse_filter and bool(min_size),
        ws_method=algo, refine_rounds=int(cfg.get("refine_rounds", 3)),
        coarse_factor=int(cfg.get("coarse_factor", 2)))

    def submit(b):
        return b, pipeline(jnp.asarray(b))

    def _fallback(b):
        # capacity overflow (pathological height field): redo this block
        # through the always-correct per-block path — forcing the
        # exact-capacity basins algorithm (re-running the coarse solve
        # that just overflowed would waste a full device pass)
        return run_ws_block(as_normalized_float(b),
                            {**cfg, "ws_algorithm": "basins"})

    def drain(entry):
        b, handles = entry
        if fuse_filter or not min_size:
            ws, ok = handles
            if not bool(ok):
                return _fallback(b)
            return np.asarray(ws).astype("uint64")
        ws, height, ok = handles
        if not bool(ok):
            return _fallback(b)
        return size_filter(np.asarray(ws), np.asarray(height),
                           min_size).astype("uint64")

    # bounded look-ahead: dispatch a few blocks ahead, drain as results are
    # consumed — unbounded queueing would hold every output buffer in HBM
    # (~150 MB per reference-size block)
    yield from stream_window(
        blocks,
        submit,                                      # queued async
        drain,
        window=int(cfg.get("stream_window", 3)))


def run_ws_blocks_stream(blocks, cfg: Dict[str, Any]):
    """List-returning wrapper over :func:`iter_ws_blocks_stream`."""
    return list(iter_ws_blocks_stream(blocks, cfg))


@lru_cache(maxsize=8)
def _ws_pipeline_3d(threshold: float, sigma_seeds: float,
                    sigma_weights: float, alpha: float, min_size: int = 0,
                    return_height: bool = False, ws_method: str = "basins",
                    refine_rounds: int = 3, coarse_factor: int = 2):
    """Cached fused jitted pipeline — one compile per parameter set (the
    jit cache lives on the returned function, so re-creating the closure per
    call would recompile every time).  With ``min_size`` the size filter is
    fused in: per-label device bincount + one regrow pass over the same
    height map — no height/label round-trip to the host (the transfers
    dominated the streamed task on tunnel-attached chips)."""
    import jax
    import jax.numpy as jnp

    from ..ops.components import connected_components
    from ..ops.edt import distance_transform_edt
    from ..ops.filters import gaussian, local_maxima
    from ..ops.watershed import seeded_watershed

    @jax.jit
    def pipeline(x):
        if x.dtype == jnp.uint8:
            # device-side normalization of quantized boundary maps (the
            # host read path ships the raw bytes: 4x less link traffic)
            x = x.astype(jnp.float32) * (1.0 / 255.0)
        fg = x < threshold
        dt = distance_transform_edt(fg)
        hmap = gaussian(x, sigma_weights) if sigma_weights else x
        height = alpha * hmap + (1.0 - alpha) * (
            1.0 - dt / jnp.maximum(dt.max(), 1e-6))
        dt_smooth = gaussian(dt, sigma_seeds) if sigma_seeds else dt
        maxima = local_maxima(dt_smooth, radius=2) & fg
        seeds = connected_components(maxima, connectivity=3,
                                     method="propagation")
        if ws_method == "coarse":
            # shared watershed core with the fused pipeline
            # (workflows/fused_pipeline._resident_program) — identical
            # composition, size filter integrated
            from ..ops.watershed import _coarse_impl

            ws, ok = _coarse_impl(height, seeds, min_size, refine_rounds,
                                  coarse_factor)
        elif ws_method == "basins":
            # the basin formulation fuses the size filter: small fragments
            # are stripped and re-merged in ~2 extra cheap rounds instead
            # of a full second watershed pass.  Tight capacities for speed;
            # the ok flag is surfaced so the streaming drain can redo an
            # overflowing block through the always-correct path
            from ..ops.watershed import _basins_impl

            n = int(np.prod(fg.shape))
            ws, ok = _basins_impl(height, seeds, None, 1, 64, min_size,
                                  max(n // 64, 1024), max(n // 8, 4096))
        else:
            ok = jnp.bool_(True)
            ws = seeded_watershed(height, seeds, None, connectivity=1,
                                  method=ws_method)
            if min_size:
                # label ids are bounded by the voxel count (CC roots + 1),
                # so a fixed-length bincount stays shape-static under jit
                counts = jnp.bincount(ws.ravel().astype(jnp.int32),
                                      length=int(np.prod(x.shape)) + 1)
                small = counts < min_size
                small = small.at[0].set(False)
                kept = jnp.where(small[ws], 0, ws)
                ws = seeded_watershed(height, kept, None, connectivity=1,
                                      method=ws_method)
        if return_height:  # for a host-side size filter downstream
            return ws, height, ok
        return ws, ok

    return pipeline


def _ws_algorithm(cfg) -> str:
    """Resolve the watershed ALGORITHM ('coarse'/'basins'/'flood') from
    task config or the CTT_WS_METHOD env; distinct from the fused task's
    execution-strategy ws_method (device/hybrid/legacy), whose values
    fall through to the default."""
    m = (cfg.get("ws_algorithm") or cfg.get("ws_method")
         or os.environ.get("CTT_WS_METHOD", "coarse"))
    return m if m in ("coarse", "basins", "flood") else "coarse"


def run_ws_block_seeded(data: np.ndarray, cfg: Dict[str, Any],
                        initial_seeds: np.ndarray, label_offset: int,
                        mask: Optional[np.ndarray] = None) -> np.ndarray:
    """Watershed continuing existing labels: ``initial_seeds`` (uint64,
    0 = free) keep their ids; new seeds from DT maxima in unlabeled areas get
    ids offset by ``label_offset`` (reference: two_pass_watershed.py:210-255
    ``_ws_pass2`` / ``_apply_watershed_with_seeds``).  3d only — the 2d
    variants cannot propagate seeds across slices."""
    import jax.numpy as jnp

    from ..ops.components import connected_components
    from ..ops.edt import distance_transform_edt
    from ..ops.filters import gaussian, local_maxima
    from ..ops.rag import densify_labels
    from ..ops.watershed import seeded_watershed

    if cfg.get("apply_dt_2d") or cfg.get("apply_ws_2d"):
        raise ValueError(
            "two-pass watershed supports 3d only: per-slice 2d watershed "
            "cannot continue seeds across slices — disable apply_dt_2d/"
            "apply_ws_2d or use the single-pass task")
    threshold = cfg.get("threshold", 0.25)
    sigma_seeds = cfg.get("sigma_seeds", 2.0)
    sigma_weights = cfg.get("sigma_weights", 2.0)
    alpha = cfg.get("alpha", 0.8)
    pixel_pitch = cfg.get("pixel_pitch")

    x = jnp.asarray(data)
    jmask = None if mask is None else jnp.asarray(mask.astype(bool))
    fg = x < threshold
    if jmask is not None:
        fg = fg & jmask
    sampling = tuple(pixel_pitch) if pixel_pitch else None
    dt = distance_transform_edt(fg, sampling=sampling)
    hmap = gaussian(x, sigma_weights) if sigma_weights else x
    dmax = jnp.maximum(dt.max(), 1e-6)
    height = alpha * hmap + (1.0 - alpha) * (1.0 - dt / dmax)

    # densify initial seeds to 1..k for the device program (lut[0] == 0)
    lut, dense_init = densify_labels(initial_seeds)
    k = len(lut) - 1

    seeded_area = jnp.asarray(initial_seeds > 0)
    dt_smooth = gaussian(dt, sigma_seeds) if sigma_seeds else dt
    maxima = local_maxima(dt_smooth, radius=2) & fg & ~seeded_area
    new_cc = connected_components(maxima, connectivity=data.ndim,
                                  method="propagation")
    combined = jnp.where(jnp.asarray(dense_init) > 0, jnp.asarray(dense_init),
                         jnp.where(new_cc > 0, new_cc + k, 0))
    ws = np.asarray(seeded_watershed(height, combined, jmask, connectivity=1))

    # map back: 1..k -> original seed ids; >k -> compacted + offset
    out = np.zeros(ws.shape, dtype="uint64")
    init_part = (ws >= 1) & (ws <= k)
    if k:
        out[init_part] = lut[ws[init_part]]
    new_part = ws > k
    if new_part.any():
        new_ids = np.unique(ws[new_part])
        if cfg.get("id_budget") and len(new_ids) >= cfg["id_budget"]:
            raise RuntimeError(
                f"{len(new_ids)} new seeds exceed the per-block id budget "
                f"{cfg['id_budget']} — labels would collide across blocks")
        out[new_part] = (np.searchsorted(new_ids, ws[new_part])
                         .astype("uint64") + np.uint64(label_offset) + 1)

    # size-filter NEW fragments only (continued seeds are protected — they
    # are partial views of segments that extend beyond this block), then
    # regrow the survivors: keeps pass-1/pass-2 fragment statistics aligned
    # (run_ws_block applies the same filter to all fragments)
    min_size = cfg.get("size_filter", 0)
    if min_size and new_part.any():
        ids, sizes = np.unique(out[new_part], return_counts=True)
        small = ids[sizes < min_size]
        if len(small):
            drop = np.isin(out, small)
            out[drop] = 0
            lut2, dense2 = densify_labels(out)
            regrown = np.asarray(seeded_watershed(
                height, jnp.asarray(dense2), jmask, connectivity=1))
            out = lut2[regrown]
    return out


class WatershedTask(BlockTask):
    """Blockwise DT watershed (reference: WatershedBase, watershed.py:34-110).

    Labels are made globally unique by offsetting with
    ``block_id * prod(block_shape)`` (reference: watershed.py:307); chain
    RelabelWorkflow (or use WatershedWorkflow) to compact them.

    ``pass_id``/``seeded`` implement the checkerboard two-pass variant
    (reference: two_pass_watershed.py:60-94): color-0 blocks run the plain
    pipeline; color-1 blocks read the pass-1 labels visible in their halo and
    continue them as seeds — block boundaries between the two colors need no
    stitching.
    """

    task_name = "watershed"
    #: None = all blocks (single pass); 0/1 = checkerboard color
    pass_id: Optional[int] = None
    seeded: bool = False

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, mask_path: str = "", mask_key: str = "", **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({
            "threshold": 0.25, "apply_dt_2d": False, "apply_ws_2d": False,
            "sigma_seeds": 2.0, "sigma_weights": 2.0, "size_filter": 25,
            "alpha": 0.8, "halo": [4, 32, 32], "pixel_pitch": None,
            "non_maximum_suppression": False,
            "invert_inputs": False, "agglomerate_channels": "mean",
            "channel_begin": 0, "channel_end": None,
        })
        return conf

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            in_shape = f[self.input_key].shape
        shape = list(in_shape[1:] if len(in_shape) == 4 else in_shape)
        block_shape = self.global_block_shape()[-len(shape):]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=shape, chunks=block_shape,
                              dtype="uint64")
        block_list = self.blocks_in_volume(shape, block_shape)
        if self.pass_id is not None:
            colors = Blocking(shape, block_shape).checkerboard()
            allowed = set(block_list)
            block_list = [b for b in colors[self.pass_id] if b in allowed]
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "mask_path": self.mask_path, "mask_key": self.mask_key,
            "shape": shape, "block_shape": block_shape,
            "seeded": self.seeded,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        halo = cfg.get("halo") or [0] * blocking.ndim
        halo = halo[-blocking.ndim:]
        f_in = file_reader(cfg["input_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in, ds_out = f_in[cfg["input_key"]], f_out[cfg["output_key"]]
        mask = None
        if cfg.get("mask_path"):
            from ..core.volume_views import load_mask

            mask = load_mask(cfg["mask_path"], cfg["mask_key"], cfg["shape"])

        label_offset_unit = np.uint64(np.prod(cfg["block_shape"]))
        seeded = cfg.get("seeded", False)
        # blocks are loaded at the UNIFORM outer shape (volume borders
        # reflect-padded, like the inference task): every block shares one
        # compiled device program instead of one per clipped border shape
        # (per-shape compiles cost ~a minute each on tunnel-attached chips)
        from .inference import load_with_halo

        outer_shape = tuple(b + 2 * h
                            for b, h in zip(cfg["block_shape"], halo))

        def _write_result(block_id: int, ws: np.ndarray) -> None:
            block = blocking.get_block(block_id)
            inner_sl = tuple(slice(h, h + (b.stop - b.start))
                             for h, b in zip(halo, block.bb))
            inner = ws[inner_sl]
            # compact to 1..k (k <= inner voxel count < offset unit), THEN
            # offset for global uniqueness (reference: watershed.py:307) —
            # uncompacted CC root indices range over the larger outer block
            # and would collide across blocks
            nonzero = np.unique(inner[inner > 0])
            compact = np.searchsorted(nonzero, inner).astype("uint64") + 1
            compact[inner == 0] = 0
            compact = np.where(
                compact > 0,
                compact + np.uint64(block_id) * label_offset_unit, 0)
            ds_out[block.bb] = compact
            log_fn(f"processed block {block_id}")

        # plain 3d path: stream every block of the job through one fused
        # jitted pipeline with async dispatch — transfers and compute of
        # consecutive blocks overlap, hiding per-block device latency
        # (dominant on tunnel-attached chips; profiled 32s -> the single
        # largest task span of BASELINE config 4)
        streamable = (not seeded and mask is None
                      and cfg.get("impl") != "host"
                      and not cfg.get("apply_dt_2d")
                      and not cfg.get("apply_ws_2d")
                      and not cfg.get("pixel_pitch")
                      and not cfg.get("non_maximum_suppression"))
        if streamable and job_config.get("target") == "mesh":
            # SPMD rounds over the device mesh: one block per device, the
            # SAME fused pipeline vmapped — results are bit-identical to
            # the inline streaming path (tests/test_mesh_exec.py)
            import jax
            import jax.numpy as jnp
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..core.runtime import prefetch_iter
            from ..ops.watershed import size_filter
            from ..parallel.mesh import blocks_mesh

            n_dev = len(jax.devices())
            mesh = blocks_mesh(n_dev)
            sharding = NamedSharding(mesh, P("blocks"))
            min_size = int(cfg.get("size_filter", 25) or 0)
            algo = _ws_algorithm(cfg)
            fuse_filter = cfg.get("fuse_size_filter")
            if fuse_filter is None:
                fuse_filter = jax.default_backend() != "cpu"
            if algo == "coarse":
                fuse_filter = True  # integrated in the coarse solve
            pipeline = _ws_pipeline_3d(
                float(cfg.get("threshold", 0.25)),
                float(cfg.get("sigma_seeds", 2.0)),
                float(cfg.get("sigma_weights", 2.0)),
                float(cfg.get("alpha", 0.8)),
                min_size if fuse_filter else 0,
                return_height=not fuse_filter and bool(min_size),
                ws_method=algo,
                refine_rounds=int(cfg.get("refine_rounds", 3)),
                coarse_factor=int(cfg.get("coarse_factor", 2)))
            batched = jax.jit(jax.vmap(pipeline))

            block_ids = list(job_config["block_list"])
            reads = prefetch_iter(
                block_ids,
                lambda bid: _read_padded_input(
                    ds_in, blocking.get_block(bid), cfg, halo, raw=True))
            pending_ids: List[int] = []
            pending: List[np.ndarray] = []

            def _flush():
                if not pending:
                    return
                if len({b.dtype for b in pending}) > 1:
                    # a degenerate block came back float (host-normalized);
                    # normalize the uint8 ones so the round is uniform
                    pending[:] = [as_normalized_float(b)
                                  for b in pending]
                batch = np.stack(
                    pending + [pending[-1]] * (n_dev - len(pending)))
                dev = jax.device_put(jnp.asarray(batch), sharding)
                out = batched(dev)
                if fuse_filter or not min_size:
                    ws_all, oks = out
                    heights = None
                else:
                    ws_all, heights, oks = out
                    heights = np.asarray(heights)
                ws_all = np.asarray(ws_all)
                oks = np.asarray(oks)
                for k, bid in enumerate(pending_ids):
                    if not oks[k]:
                        # capacity overflow: always-correct per-block redo
                        # (basins forced: the coarse solve just overflowed)
                        ws = run_ws_block(as_normalized_float(pending[k]),
                                          {**cfg, "ws_algorithm": "basins"})
                    else:
                        ws = ws_all[k]
                        if heights is not None:
                            ws = size_filter(ws, heights[k], min_size)
                    _write_result(bid, ws.astype("uint64"))
                    log_fn(f"processed block {bid}")
                pending.clear()
                pending_ids.clear()

            for bid, data in zip(block_ids, reads):
                pending_ids.append(bid)
                pending.append(data)
                if len(pending) == n_dev:
                    _flush()
            _flush()
            return

        if streamable:
            from ..core.runtime import prefetch_iter

            block_ids = list(job_config["block_list"])
            # threaded read look-ahead: block i+2's store read overlaps
            # block i's device compute and block i-1's write
            reads = prefetch_iter(
                block_ids,
                lambda bid: _read_padded_input(
                    ds_in, blocking.get_block(bid), cfg, halo, raw=True))
            for bid, ws in zip(block_ids,
                               iter_ws_blocks_stream(reads, cfg)):
                _write_result(bid, ws)
            return

        for block_id in job_config["block_list"]:
            block = blocking.get_block(block_id)
            bh = blocking.get_block_with_halo(block_id, halo)
            data = _read_padded_input(ds_in, block, cfg, halo)
            bmask = None
            if mask is not None:
                m = np.asarray(mask[bh.outer.bb]) > 0
                if not m.any():
                    log_fn(f"processed block {block_id}")
                    continue
                # edge-replicate onto the uniform frame (same geometry the
                # reflect-padded data read uses)
                lo_pad = [h - (b - o.start)
                          for h, b, o in zip(halo, block.begin, bh.outer.bb)]
                hi_pad = [os_ - lp - (o.stop - o.start)
                          for os_, lp, o in zip(outer_shape, lo_pad,
                                                bh.outer.bb)]
                bmask = np.pad(m, list(zip(lo_pad, hi_pad)), mode="edge")
            # actual (clipped) inner extent within the uniform frame
            inner_sl = tuple(slice(h, h + (b.stop - b.start))
                             for h, b in zip(halo, block.bb))
            if seeded:
                # pass-2: labels already written by the other checkerboard
                # color act as seeds; same-color owners (possibly being
                # written concurrently) are masked out so the result is
                # order-independent.  Seeds pad with 0 (reflecting would
                # duplicate label ids).
                seeds = load_with_halo(ds_out, block.begin,
                                       cfg["block_shape"], halo,
                                       padding_mode="constant")
                own_color = sum(blocking.block_grid_position(block_id)) % 2
                grids = np.meshgrid(
                    *[(np.arange(b - h, b - h + o)) // bs
                      for b, h, o, bs in zip(block.begin, halo, outer_shape,
                                             cfg["block_shape"])],
                    indexing="ij")
                seeds[sum(grids) % 2 == own_color] = 0
                ws = run_ws_block_seeded(
                    data, {**cfg, "id_budget": int(label_offset_unit)}, seeds,
                    int(np.uint64(block_id) * label_offset_unit), bmask)
                ds_out[block.bb] = ws[inner_sl]
                log_fn(f"processed block {block_id}")
                continue
            if cfg.get("impl") == "host":
                ws = run_ws_block_host(data, cfg, bmask)
            else:
                ws = run_ws_block(data, cfg, bmask)
            _write_result(block_id, ws)


class WatershedPass1Task(WatershedTask):
    """Checkerboard color-0 blocks, plain pipeline (two_pass_watershed pass 0)."""

    task_name = "watershed_pass1"
    pass_id = 0


class WatershedPass2Task(WatershedTask):
    """Checkerboard color-1 blocks, seeded by the pass-1 labels in the halo
    (reference: two_pass_watershed.py:210-255)."""

    task_name = "watershed_pass2"
    pass_id = 1
    seeded = True


class WatershedFromSeedsTask(BlockTask):
    """Blockwise seeded watershed from a precomputed seed volume (reference:
    watershed_from_seeds.py:25 — grow given seeds over the boundary map; no
    new seeds, no offsets: seed ids are already globally consistent)."""

    task_name = "watershed_from_seeds"

    def __init__(self, input_path: str, input_key: str, seeds_path: str,
                 seeds_key: str, output_path: str, output_key: str,
                 mask_path: str = "", mask_key: str = "", **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.seeds_path = seeds_path
        self.seeds_key = seeds_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"halo": [2, 16, 16], "sigma_weights": 2.0,
                     "invert_inputs": False, "agglomerate_channels": "mean",
                     "channel_begin": 0, "channel_end": None})
        return conf

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            in_shape = f[self.input_key].shape
        shape = list(in_shape[1:] if len(in_shape) == 4 else in_shape)
        block_shape = self.global_block_shape()[-len(shape):]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=shape, chunks=block_shape,
                              dtype="uint64")
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "seeds_path": self.seeds_path, "seeds_key": self.seeds_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "mask_path": self.mask_path, "mask_key": self.mask_key,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        import jax.numpy as jnp

        from ..ops.filters import gaussian
        from ..ops.watershed import seeded_watershed

        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        halo = (cfg.get("halo") or [0] * blocking.ndim)[-blocking.ndim:]
        f_in = file_reader(cfg["input_path"], "r")
        f_seeds = file_reader(cfg["seeds_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in = f_in[cfg["input_key"]]
        ds_seeds = f_seeds[cfg["seeds_key"]]
        ds_out = f_out[cfg["output_key"]]
        mask = None
        if cfg.get("mask_path"):
            from ..core.volume_views import load_mask

            mask = load_mask(cfg["mask_path"], cfg["mask_key"], cfg["shape"])

        sigma = cfg.get("sigma_weights", 2.0)
        for block_id in job_config["block_list"]:
            bh = blocking.get_block_with_halo(block_id, halo)
            data = _read_input(ds_in, bh.outer.bb, cfg)
            bmask = None
            if mask is not None:
                bmask = np.asarray(mask[bh.outer.bb]) > 0
                if not bmask.any():
                    log_fn(f"processed block {block_id}")
                    continue
            seeds = np.asarray(ds_seeds[bh.outer.bb])
            # densify (seed ids are arbitrary uint64; device wants int32)
            from ..ops.rag import densify_labels

            lut, dense = densify_labels(seeds)
            if len(lut) == 1:  # only the reserved 0 entry: no seeds here
                log_fn(f"processed block {block_id}")
                continue
            height = gaussian(jnp.asarray(data), sigma) if sigma else \
                jnp.asarray(data)
            ws = np.asarray(seeded_watershed(
                height, jnp.asarray(dense),
                None if bmask is None else jnp.asarray(bmask),
                connectivity=1))
            out = lut[ws]
            ds_out[bh.inner.bb] = out[bh.inner_local.bb]
            log_fn(f"processed block {block_id}")


class AgglomerateTask(BlockTask):
    """Block-local RAG agglomeration of watershed fragments (reference:
    watershed/agglomerate.py:129+ — gridRag + accumulateEdgeMeanAndLength +
    mala/edge-weighted agglo policy + projectScalarNodeDataToPixels).

    TPU split: edge extraction + per-edge mean boundary evidence run on
    device (ops/rag), the priority-queue agglomeration in first-party C++
    (native.agglomerative_clustering).  Fragment ids are re-offset per block
    (the workflow relabels afterwards, as in the reference)."""

    task_name = "agglomerate"

    def __init__(self, input_path: str, input_key: str, labels_path: str,
                 labels_key: str, output_path: str, output_key: str, **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.output_path = output_path
        self.output_key = output_key
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"threshold": 0.9, "size_regularizer": 0.5,
                     "invert_inputs": False, "agglomerate_channels": "mean",
                     "channel_begin": 0, "channel_end": None})
        return conf

    def run_impl(self):
        with file_reader(self.labels_path, "r") as f:
            shape = list(f[self.labels_key].shape)
        block_shape = self.global_block_shape()[-len(shape):]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=shape, chunks=block_shape,
                              dtype="uint64")
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "labels_path": self.labels_path, "labels_key": self.labels_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        import jax.numpy as jnp

        from .. import native
        from ..ops.rag import boundary_pair_values, densify_labels

        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        f_in = file_reader(cfg["input_path"], "r")
        f_lab = file_reader(cfg["labels_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in = f_in[cfg["input_key"]]
        ds_lab = f_lab[cfg["labels_key"]]
        ds_out = f_out[cfg["output_key"]]
        threshold = cfg.get("threshold", 0.9)
        size_reg = cfg.get("size_regularizer", 0.5)
        offset_unit = np.uint64(np.prod(cfg["block_shape"]))

        for block_id in job_config["block_list"]:
            block = blocking.get_block(block_id)
            labels = np.asarray(ds_lab[block.bb])
            lut, dense = densify_labels(labels)
            n_nodes = len(lut)
            if n_nodes <= 1:
                ds_out[block.bb] = labels
                log_fn(f"processed block {block_id}")
                continue
            bmap = _read_input(ds_in, block.bb, cfg)
            u, v, val, ok = boundary_pair_values(
                jnp.asarray(dense), jnp.asarray(bmap))
            m = np.asarray(ok)
            uv_all = np.stack([np.asarray(u)[m], np.asarray(v)[m]], axis=1)
            vals = np.asarray(val)[m].astype("float64")
            if len(uv_all) == 0:
                ds_out[block.bb] = labels
                log_fn(f"processed block {block_id}")
                continue
            # per-(dense) edge mean + size; drop edges to the ignore label 0
            uv, inv = np.unique(uv_all, axis=0, return_inverse=True)
            sums = np.bincount(inv, weights=vals, minlength=len(uv))
            sizes = np.bincount(inv, minlength=len(uv)).astype("float64")
            keep = (uv[:, 0] != 0) & (uv[:, 1] != 0)
            uv, sums, sizes = uv[keep], sums[keep], sizes[keep]
            node_sizes = np.bincount(dense.ravel(),
                                     minlength=n_nodes).astype("float64")
            clusters = native.agglomerative_clustering(
                n_nodes, uv, sums / np.maximum(sizes, 1), edge_sizes=sizes,
                node_sizes=node_sizes, threshold=threshold,
                size_regularizer=size_reg)
            # keep 0 as background, compact cluster ids, offset per block
            clusters = clusters.astype("uint64")
            nz = np.unique(clusters[1:]) if n_nodes > 1 else clusters
            remap = np.searchsorted(nz, clusters).astype("uint64") + 1
            remap[0] = 0
            out = remap[dense] + np.where(remap[dense] > 0,
                                          np.uint64(block_id) * offset_unit,
                                          np.uint64(0))
            ds_out[block.bb] = out
            log_fn(f"processed block {block_id}")


class WatershedWorkflow(Task):
    """[TwoPass]Watershed -> [Agglomerate] -> RelabelWorkflow (reference:
    watershed/watershed_workflow.py:20-60)."""

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 mask_path: str = "", mask_key: str = "",
                 two_pass: bool = False, agglomeration: bool = False,
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        if two_pass and agglomeration:
            raise ValueError(
                "two_pass and agglomeration are mutually exclusive: the "
                "block-local agglomerate re-offsets ids per block, splitting "
                "every segment the seeded pass-2 stitched across faces")
        self.two_pass = two_pass
        self.agglomeration = agglomeration
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        ws_kwargs = dict(
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            mask_path=self.mask_path, mask_key=self.mask_key)
        if self.two_pass:
            p1 = WatershedPass1Task(dependency=self.dependency, **ws_kwargs,
                                    **common)
            dep: Task = WatershedPass2Task(dependency=p1, **ws_kwargs,
                                           **common)
        else:
            dep = WatershedTask(dependency=self.dependency, **ws_kwargs,
                                **common)
        if self.agglomeration:
            # in-place: block-local transform, each block reads and rewrites
            # only its own chunk-aligned region (single-writer invariant
            # holds; reference chains a separate agglomerate dataset,
            # agglomerate.py:129+, but the copy buys nothing here)
            dep = AgglomerateTask(
                input_path=self.input_path, input_key=self.input_key,
                labels_path=self.output_path, labels_key=self.output_key,
                output_path=self.output_path, output_key=self.output_key,
                dependency=dep, **common)
        return RelabelWorkflow(
            input_path=self.output_path, input_key=self.output_key,
            identifier="relabel_ws", dependency=dep, **common)

    def output(self):
        from ..core.workflow import FileTarget

        return FileTarget(os.path.join(self.tmp_folder,
                                       "write_relabel_ws.status"))
