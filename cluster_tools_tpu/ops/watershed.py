"""Seeded watershed on device.

TPU-native replacement for vigra's ``watershedsNew`` (reference:
utils/volume_utils.py:123-139 ``watershed`` + size filter;
watershed/watershed.py:211-249 per-block 2d/3d watershed).

Sequential priority-flood is inherently serial, so the device algorithm is the
**steepest-descent forest**: every voxel points to its lowest neighbor (itself
if it is a local minimum), seeds are forced to point to themselves, and
pointer jumping (O(log n) gathers) resolves every voxel to a root.  Voxels
whose root is a seed inherit its label; plateau/non-seed-minimum leftovers are
filled by monotone label propagation in height order (bounded while_loop that
at each step adopts the label of the lowest already-labeled neighbor).  The
result has vigra-compatible *structure* (every masked voxel labeled, seeds
preserved, boundaries on ridges); exact voxel assignments on plateaus differ
between implementations, as they already do between vigra and scipy.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .components import _neighbor_offsets, _shifted


def _flat_offsets(shape: Tuple[int, ...], connectivity: int) -> Tuple[Tuple[int, ...], ...]:
    return _neighbor_offsets(len(shape), connectivity)


@partial(jax.jit, static_argnames=("connectivity", "max_iter"))
def seeded_watershed(
    height: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    max_iter: int = 0,
) -> jnp.ndarray:
    """Grow ``seeds`` (int labels, 0 = unlabeled) over ``height`` (flooded in
    increasing order) restricted to ``mask``.  Returns int32 labels; 0 only
    outside the mask."""
    shape = height.shape
    n = int(np.prod(shape))
    height = height.astype(jnp.float32)
    if mask is None:
        mask = jnp.ones(shape, bool)
    else:
        mask = mask.astype(bool)
    if max_iter == 0:
        # the fill loop advances labels one voxel per iteration along geodesic
        # paths, so the only safe data-independent bound is the voxel count
        # (serpentine corridors realize it); both loops exit early on
        # convergence, so the generous bound costs nothing in practice
        max_iter = max(n, 32)
    offsets = _flat_offsets(shape, connectivity)

    big = jnp.float32(np.finfo(np.float32).max)
    h = jnp.where(mask, height, big)
    seeded = (seeds > 0) & mask
    # seeds are below everything: they are the only attractors
    h = jnp.where(seeded, -big, h)

    flat_idx = jnp.arange(n, dtype=jnp.int32).reshape(shape)

    # steepest-descent pointer: index of the strictly-lowest neighbor
    # (ties broken toward lower linear index for determinism)
    best_h = h
    best_i = flat_idx
    for off in offsets:
        nh = _shifted(h, off, big)
        ni = _shifted(flat_idx, off, jnp.int32(n))
        better = (nh < best_h) | ((nh == best_h) & (ni < best_i) & (nh < h))
        best_h = jnp.where(better, nh, best_h)
        best_i = jnp.where(better, ni, best_i)
    parent = jnp.where(mask, best_i, flat_idx).reshape(-1)
    parent = jnp.where(seeded.reshape(-1), jnp.arange(n, dtype=jnp.int32), parent)

    # pointer jumping to roots (bounded: depth halves per step)
    def jump_body(state):
        p, _, it = state
        p2 = p[p]
        return p2, jnp.any(p2 != p), it + 1

    parent, _, _ = jax.lax.while_loop(
        lambda s: s[1] & (s[2] < max_iter), jump_body,
        (parent, jnp.bool_(True), jnp.int32(0)))

    seed_flat = seeds.astype(jnp.int32).reshape(-1)
    labels = seed_flat[parent]
    labels = jnp.where(mask.reshape(-1), labels, 0)

    # fill voxels the descent stage left unlabeled (plateaus, spurious
    # non-seed minima) with a QUANTIZED PRIORITY FLOOD — the vigra
    # watershedsNew ordering: heights are binned into L levels processed in
    # ascending order; at each level, only voxels at-or-below the water
    # level may adopt (from their lowest labeled neighbor), iterated to
    # stability before the level rises.  A label can therefore only cross a
    # saddle once the flood REACHES the saddle's level, by which time every
    # basin below it has been claimed by its own seed — the unordered
    # step-count race freely leaked labels across ridges into late-claimed
    # pockets (fragment purity ~0.7 on CREMI-like geometry).
    n_levels = 256
    hg = jnp.where(mask, height, big)
    finite = jnp.where(mask, height, -big)
    h_lo = jnp.where(mask, height, big).min()
    h_hi = finite.max()
    hq = jnp.clip(((hg - h_lo) / jnp.maximum(h_hi - h_lo, 1e-6)
                   * (n_levels - 1)).astype(jnp.int32), 0, n_levels - 1)
    hq = jnp.where(mask, hq, n_levels)

    def lowest_labeled_neighbor(lab_g):
        nbr_h = jnp.full(shape, big)
        nbr_l = jnp.zeros(shape, jnp.int32)
        for off in offsets:
            oh = _shifted(hg, off, big)
            ol = _shifted(lab_g, off, jnp.int32(0))
            cand = (ol > 0) & (oh < nbr_h)
            nbr_h = jnp.where(cand, oh, nbr_h)
            nbr_l = jnp.where(cand, ol, nbr_l)
        return nbr_l

    def flood_body(state):
        lab, level, it = state
        lab_g = lab.reshape(shape)
        nbr_l = lowest_labeled_neighbor(lab_g)
        adopt = (lab_g == 0) & mask & (nbr_l > 0) & (hq <= level)
        new = jnp.where(adopt, nbr_l, lab_g).reshape(-1)
        changed = jnp.any(new != lab)
        # stable at this water level -> jump straight to the lowest level
        # present on the frontier (skipping empty levels costs nothing and
        # saves hundreds of no-op sweeps)
        frontier = (lab_g == 0) & mask & (nbr_l > 0)
        next_level = jnp.min(jnp.where(frontier, hq, n_levels))
        level = jnp.where(changed, level,
                          jnp.maximum(level + 1, next_level))
        return new, level, it + 1

    def flood_cond(state):
        lab, level, it = state
        return (level < n_levels) & (it < max_iter + n_levels)

    labels, _, _ = jax.lax.while_loop(
        flood_cond, flood_body, (labels, jnp.int32(0), jnp.int32(0)))

    # backstop ONLY: the flood converges exactly (its frontier empties), so
    # this unordered sweep does work solely if the flood's iteration bound
    # (max_iter + n_levels) was hit early on a pathological instance —
    # labelable voxels then still get claimed, arbitrary-side like any tie
    def fill_body(state):
        lab, _, it = state
        lab_g = lab.reshape(shape)
        nbr_l = lowest_labeled_neighbor(lab_g)
        adopt = (lab_g == 0) & mask & (nbr_l > 0)
        new = jnp.where(adopt, nbr_l, lab_g).reshape(-1)
        return new, jnp.any(new != lab), it + 1

    labels, _, _ = jax.lax.while_loop(
        lambda s: s[1] & (s[2] < max_iter), fill_body,
        (labels, jnp.bool_(True), jnp.int32(0)))
    return labels.reshape(shape)


@partial(jax.jit, static_argnames=("connectivity",))
def seeded_watershed_batched(
    heights: jnp.ndarray, seeds: jnp.ndarray, masks: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
) -> jnp.ndarray:
    if masks is None:
        return jax.vmap(
            lambda h, s: seeded_watershed(h, s, None, connectivity)
        )(heights, seeds)
    return jax.vmap(
        lambda h, s, m: seeded_watershed(h, s, m, connectivity)
    )(heights, seeds, masks)


def size_filter(
    labels: np.ndarray, height: np.ndarray, size_threshold: int,
    mask: Optional[np.ndarray] = None, connectivity: int = 1,
    per_slice: bool = False,
) -> np.ndarray:
    """Remove fragments smaller than ``size_threshold`` and regrow the
    remaining seeds over the height map (reference:
    utils/volume_utils.py:123-139 watershed-and-size-filter).  Host-side
    counting + one device watershed pass.  ``per_slice`` regrows each z-slice
    independently (2d watershed mode)."""
    labels = np.asarray(labels)
    flat = labels.ravel()
    uniques, inverse, counts = np.unique(flat, return_inverse=True,
                                         return_counts=True)
    small = (counts < size_threshold) & (uniques != 0)
    if not small.any():
        return labels
    keep = np.where(small[inverse], 0, flat).reshape(labels.shape)
    # regrown labels must fit the watershed's int32 seed ids: compact first,
    # restore original ids after
    nz = uniques[(uniques != 0) & ~small]
    seed_ids = np.searchsorted(nz, keep).astype("int32") + 1
    seed_ids[keep == 0] = 0
    if per_slice:
        out = seeded_watershed_batched(
            jnp.asarray(height), jnp.asarray(seed_ids),
            None if mask is None else jnp.asarray(mask),
            connectivity=connectivity)
    else:
        out = seeded_watershed(
            jnp.asarray(height), jnp.asarray(seed_ids),
            None if mask is None else jnp.asarray(mask),
            connectivity=connectivity)
    out = np.asarray(out)
    restored = np.zeros(out.shape, dtype=labels.dtype)
    fg = out > 0
    restored[fg] = nz[out[fg] - 1]
    return restored
