from .mesh import make_mesh, volume_sharding, param_sharding, replicated
from .stencil import halo_exchange, crop_halo, sharded_stencil
from .pipeline import make_pipe_mesh, pipeline_apply, stack_stage_params
from .experts import make_expert_mesh, moe_apply
from .ring_attention import make_seq_mesh, ring_attention
