"""End-to-end ThresholdedComponentsWorkflow test vs full-volume scipy oracle
(reference test style: recompute-in-numpy, test/thresholded_components/)."""

import numpy as np
import pytest
from scipy import ndimage

from cluster_tools_tpu.core.storage import file_reader
from cluster_tools_tpu.core.workflow import build
from cluster_tools_tpu.workflows.thresholded_components import (
    ThresholdedComponentsWorkflow,
)


def _partitions_equal(a: np.ndarray, b: np.ndarray) -> bool:
    """True iff the two label images induce the same partition (bijective
    label correspondence), background fixed at 0."""
    if not ((a == 0) == (b == 0)).all():
        return False
    fg = a != 0
    pairs = np.unique(np.stack([a[fg], b[fg]]), axis=1)
    return (len(np.unique(pairs[0])) == pairs.shape[1]
            and len(np.unique(pairs[1])) == pairs.shape[1])


def _make_volume(shape, seed=0):
    rng = np.random.RandomState(seed)
    # smooth-ish random field so components span many blocks
    vol = rng.rand(*shape).astype("float32")
    vol = ndimage.uniform_filter(vol, size=3)
    return vol


@pytest.mark.parametrize("target", ["inline", "local"])
def test_thresholded_components_vs_scipy(tmp_workdir, tmp_path, target):
    tmp_folder, config_dir = tmp_workdir
    shape = (30, 30, 30)
    vol = _make_volume(shape)
    threshold = 0.5

    path = str(tmp_path / "data.n5")
    with file_reader(path) as f:
        ds = f.require_dataset("raw", shape=shape, chunks=(10, 10, 10),
                               dtype="float32")
        ds[...] = vol

    wf = ThresholdedComponentsWorkflow(
        input_path=path, input_key="raw", output_path=path, output_key="cc",
        threshold=threshold, tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=4, target=target)
    assert build([wf], raise_on_failure=True)

    with file_reader(path, "r") as f:
        result = f["cc"][...]
        max_id = f["cc"].attrs["maxId"]

    expected, n_exp = ndimage.label(vol > threshold)
    assert _partitions_equal(result, expected.astype("uint64"))
    assert len(np.unique(result[result != 0])) == n_exp
    assert max_id == n_exp
    # consecutive labels 1..n
    assert result.max() == n_exp


def test_single_component_spanning_all_blocks(tmp_workdir, tmp_path):
    tmp_folder, config_dir = tmp_workdir
    shape = (20, 20, 20)
    vol = np.zeros(shape, dtype="float32")
    # a 3D cross through the whole volume: one component crossing all axes
    vol[10, :, :] = 1.0
    vol[:, 10, :] = 1.0
    vol[:, :, 10] = 1.0

    path = str(tmp_path / "data.n5")
    with file_reader(path) as f:
        f.require_dataset("raw", shape=shape, chunks=(10, 10, 10),
                          dtype="float32")[...] = vol
    wf = ThresholdedComponentsWorkflow(
        input_path=path, input_key="raw", output_path=path, output_key="cc",
        threshold=0.5, tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="inline")
    assert build([wf], raise_on_failure=True)
    with file_reader(path, "r") as f:
        result = f["cc"][...]
    assert (result[vol > 0.5] == 1).all()
    assert (result[vol <= 0.5] == 0).all()


def test_empty_volume(tmp_workdir, tmp_path):
    tmp_folder, config_dir = tmp_workdir
    shape = (20, 20, 20)
    path = str(tmp_path / "data.n5")
    with file_reader(path) as f:
        f.require_dataset("raw", shape=shape, chunks=(10, 10, 10),
                          dtype="float32")[...] = np.zeros(shape, "float32")
    wf = ThresholdedComponentsWorkflow(
        input_path=path, input_key="raw", output_path=path, output_key="cc",
        threshold=0.5, tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="inline")
    assert build([wf], raise_on_failure=True)
    with file_reader(path, "r") as f:
        assert (f["cc"][...] == 0).all()


def test_resident_cc_partition_identical(tmp_workdir, tmp_path, monkeypatch):
    """The resident device pass (CTT_FORCE_RESIDENT exercises it on the
    CPU backend) must produce the same partition as scipy and as the
    classic chain."""
    from cluster_tools_tpu.workflows.fused_pipeline import clear_caches

    tmp_folder, config_dir = tmp_workdir
    shape = (25, 30, 30)  # clipped border blocks included
    vol = _make_volume(shape, seed=3)
    threshold = 0.5

    path = str(tmp_path / "data.n5")
    with file_reader(path) as f:
        ds = f.require_dataset("raw", shape=shape, chunks=(10, 10, 10),
                               dtype="float32")
        ds[...] = vol

    monkeypatch.setenv("CTT_FORCE_RESIDENT", "1")
    clear_caches()
    wf = ThresholdedComponentsWorkflow(
        input_path=path, input_key="raw", output_path=path,
        output_key="cc_res", threshold=threshold, tmp_folder=tmp_folder,
        config_dir=config_dir, max_jobs=2, target="tpu")
    assert build([wf], raise_on_failure=True)

    with file_reader(path, "r") as f:
        result = f["cc_res"][...]
        max_id = f["cc_res"].attrs["maxId"]

    expected, n_exp = ndimage.label(vol > threshold)
    assert _partitions_equal(result, expected.astype("uint64"))
    assert max_id == n_exp

    # cache-miss path (fresh process semantics): faces + write fall back
    # to store reads and still agree
    clear_caches()
    import shutil

    shutil.rmtree(tmp_folder, ignore_errors=True)
    wf = ThresholdedComponentsWorkflow(
        input_path=path, input_key="raw", output_path=path,
        output_key="cc_res2", threshold=threshold,
        tmp_folder=tmp_folder + "_2", config_dir=config_dir,
        max_jobs=2, target="tpu")
    assert build([wf], raise_on_failure=True)
    with file_reader(path, "r") as f:
        result2 = f["cc_res2"][...]
    assert _partitions_equal(result2, expected.astype("uint64"))
