"""stage-registry / metric-registry: canonical-name discipline, as AST
passes.

These absorb the PR-15 stage-name grep lint and the PR-16 metric-name
registry lint.  Being AST-based they additionally catch what a quoted-
literal grep structurally cannot: f-string and concatenated names
(``stage_add(f"sync-{kind}")``) that bypass the registry at runtime.

* stage names: every literal first argument of ``stage`` /
  ``timed_stage`` / ``stage_add`` / ``stage_bytes`` must be in
  ``telemetry.STAGE_REGISTRY``; a dynamic first argument is its own
  finding (register the canonical literal instead).
* metric names: every full-string constant matching ``ctt_\\w+`` must
  be in ``telemetry.METRIC_REGISTRY``; f-strings/concatenations whose
  literal head starts with ``ctt_`` are dynamic-name findings.
  (Requiring the FULL constant to match keeps docstrings and prose
  mentioning ``ctt_*`` names out of scope, same as the old grep.)
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional

from .base import Finding, Pass, SourceFile, dotted_name

_STAGE_CALLS = frozenset({"stage", "timed_stage", "stage_add",
                          "stage_bytes"})
_METRIC_RE = re.compile(r"^ctt_[a-zA-Z0-9_]+$")


def _telemetry():
    from ..core import telemetry
    return telemetry


def _stage_name_arg(call: ast.Call) -> Optional[ast.AST]:
    if call.args:
        return call.args[0]
    for kw in call.keywords:
        if kw.arg == "name":
            return kw.value
    return None


def run_stage(sf: SourceFile) -> List[Finding]:
    tele = _telemetry()
    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        fn = dotted_name(node.func)
        if not fn or fn.rsplit(".", 1)[-1] not in _STAGE_CALLS:
            continue
        arg = _stage_name_arg(node)
        if arg is None:
            continue
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            if not tele.is_registered(arg.value):
                out.append(Finding(
                    sf.rel, arg.lineno, "stage-registry",
                    "stage name %r is not in STAGE_REGISTRY — "
                    "register_stage() the canonical name" % arg.value))
        elif isinstance(arg, (ast.JoinedStr, ast.BinOp, ast.Name,
                              ast.Attribute, ast.Call)):
            out.append(Finding(
                sf.rel, arg.lineno, "stage-registry",
                "dynamic stage name in `%s(...)` — pass a registered "
                "literal so the registry stays authoritative" % fn))
    return out


def run_metric(sf: SourceFile) -> List[Finding]:
    tele = _telemetry()
    out: List[Finding] = []
    seen = set()
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if _METRIC_RE.match(node.value) \
                    and not tele.is_registered_metric(node.value):
                key = (node.lineno, node.value)
                if key in seen:
                    continue
                seen.add(key)
                out.append(Finding(
                    sf.rel, node.lineno, "metric-registry",
                    "metric name %r is not in METRIC_REGISTRY — "
                    "register_metric() it" % node.value))
        elif isinstance(node, ast.JoinedStr):
            head = node.values[0] if node.values else None
            if isinstance(head, ast.Constant) \
                    and isinstance(head.value, str) \
                    and head.value.startswith("ctt_"):
                out.append(Finding(
                    sf.rel, node.lineno, "metric-registry",
                    "f-string metric name starting with 'ctt_' — "
                    "dynamic family names bypass the registry"))
        elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left = node.left
            if isinstance(left, ast.Constant) \
                    and isinstance(left.value, str) \
                    and left.value.startswith("ctt_"):
                out.append(Finding(
                    sf.rel, node.lineno, "metric-registry",
                    "concatenated metric name starting with 'ctt_' — "
                    "dynamic family names bypass the registry"))
    return out


STAGE_PASS = Pass(name="stage-registry", rules=("stage-registry",),
                  run=run_stage)
METRIC_PASS = Pass(name="metric-registry", rules=("metric-registry",),
                   run=run_metric)
