"""Interactive proofreading subsystem (edits/, ISSUE 19).

Covers the four tentpole pieces end to end on ONE solved multicut
instance (module-scoped build, per-test copy-on-write workspaces):

* edit log: atomic appends, replay, torn-tail tolerance, validation;
* resolver: >= 2-fragments-in-block criterion, paintera narrowing
  agreeing with (and falling back to) the full scan;
* incremental solver: signature-validated warm start, the
  incremental == from-scratch identity gate on merges and splits, and
  the stale-cache fallback (counter + flight record, correct output);
* patcher: stable relabeling against the previous LUT, paintera
  assignment round-trip, and the server-driven edit lane rewriting
  exactly the touched output blocks.
"""

import glob
import json
import os
import shutil

import numpy as np
import pytest

from test_multicut import _boundary_map, _nested_voronoi


# ---------------------------------------------------------------------------
# one solved problem per module; per-test workspaces are cheap dir copies
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def solved_problem(tmp_path_factory):
    """Build the tiny nested-voronoi instance through the real workflow
    (n_scales=1, [10,10,10] grid over (24,24,24) -> 27 subproblems) once
    for the whole module."""
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.workflows.segmentation import (
        MulticutSegmentationWorkflow)

    base = tmp_path_factory.mktemp("edits_base")
    tmp_folder = str(base / "tmp")
    config_dir = str(base / "configs")
    ConfigDir(config_dir).write_global_config(
        {"block_shape": [10, 10, 10], "max_num_retries": 0})

    true, frags = _nested_voronoi()
    bnd = _boundary_map(true)
    path = str(base / "data.n5")
    with file_reader(path) as f:
        f.require_dataset("bmap", shape=bnd.shape, chunks=(12, 12, 12),
                          dtype="float32")[:] = bnd
        f.require_dataset("ws", shape=frags.shape, chunks=(12, 12, 12),
                          dtype="uint64")[:] = frags

    wf = MulticutSegmentationWorkflow(
        input_path=path, input_key="bmap", ws_path=path, ws_key="ws",
        problem_path=str(base / "problem.n5"), output_path=path,
        output_key="seg", tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="threads", n_scales=1)
    assert ctt.build([wf])
    return base


@pytest.fixture()
def workspace(solved_problem, tmp_path):
    """Mutable copy of the solved instance; returns its root dir."""
    dst = tmp_path / "ws"
    shutil.copytree(solved_problem, dst)
    return dst


def _paths(ws):
    return {
        "data": str(ws / "data.n5"),
        "problem": str(ws / "problem.n5"),
        "assignments": str(ws / "tmp" / "multicut_assignments.npy"),
    }


def _session(ws, **kw):
    from cluster_tools_tpu.edits import EditSession

    return EditSession(_paths(ws)["problem"], **kw)


def _pick_pair(session, table, same_segment):
    """Deterministic adjacent fragment pair that (a) shares at least one
    subproblem block and (b) is currently in the same / different
    segment."""
    for u, v in session.base_uv:
        ou, ov = int(session.s0_nodes[u]), int(session.s0_nodes[v])
        if ou == 0 or ov == 0:
            continue
        if bool(table[ou] == table[ov]) != same_segment:
            continue
        if session.affected_blocks([ou, ov]):
            return ou, ov
    raise AssertionError("no suitable fragment pair in the instance")


# ---------------------------------------------------------------------------
# edit log
# ---------------------------------------------------------------------------


def test_edit_log_append_replay_roundtrip(tmp_path):
    from cluster_tools_tpu.edits import EditLog

    log = EditLog(str(tmp_path / "edits.jsonl"))
    r0 = log.append("merge", [7, 3, 3], note="join")
    r1 = log.append("split", [10, 11], edit_id="fixed-id")
    assert r0.seq == 0 and r1.seq == 1
    assert r0.fragments == (3, 7)          # sorted, deduped
    assert r1.edit_id == "fixed-id"
    assert len(r0.edit_id) == 12 and r0.edit_id != r1.edit_id

    recs = EditLog(log.path).records()     # fresh reader, same file
    assert [(r.op, r.fragments, r.seq, r.edit_id) for r in recs] == \
        [("merge", (3, 7), 0, r0.edit_id),
         ("split", (10, 11), 1, "fixed-id")]
    seen = []
    assert EditLog(log.path).replay(lambda r: seen.append(r.op)) == 2
    assert seen == ["merge", "split"]
    # append after reopen continues the sequence
    r2 = EditLog(log.path).append("merge", [1, 2])
    assert r2.seq == 2 and len(log.records()) == 3


def test_edit_log_validation(tmp_path):
    from cluster_tools_tpu.edits import EditLog

    log = EditLog(str(tmp_path / "edits.jsonl"))
    with pytest.raises(ValueError, match="unknown edit op"):
        log.append("paint", [1, 2])
    with pytest.raises(ValueError, match=">= 2 distinct"):
        log.append("merge", [5, 5])
    with pytest.raises(ValueError, match="positive"):
        log.append("split", [0, 3])
    assert not os.path.exists(log.path)    # nothing was written


def test_edit_log_torn_tail_skipped_unless_strict(tmp_path):
    from cluster_tools_tpu.edits import EditLog

    log = EditLog(str(tmp_path / "edits.jsonl"))
    log.append("merge", [1, 2])
    log.append("split", [3, 4])
    with open(log.path, "ab") as f:        # simulate a crash mid-append
        f.write(b'{"edit_id": "torn", "seq": 2, "op": "mer')
    recs = EditLog(log.path).records()
    assert len(recs) == 2                  # the torn append never happened
    with pytest.raises(ValueError, match="torn trailing record"):
        EditLog(log.path).records(strict=True)
    # WAL recovery: the next append through the API truncates the torn
    # bytes first, so the log stays parseable and the sequence continues
    r2 = EditLog(log.path).append("merge", [5, 6])
    assert r2.seq == 2
    assert [r.op for r in EditLog(log.path).records(strict=True)] == \
        ["merge", "split", "merge"]


def test_edit_log_out_of_order_rejected(tmp_path):
    from cluster_tools_tpu.edits import EditLog, EditRecord

    path = str(tmp_path / "edits.jsonl")
    with open(path, "w") as f:
        f.write(EditRecord("a", 1, "merge", (1, 2), 0.0).to_json() + "\n")
    with pytest.raises(ValueError, match="non-monotonic"):
        EditLog(path).records()


# ---------------------------------------------------------------------------
# signatures + resolver
# ---------------------------------------------------------------------------


def test_persisted_signatures_match_live_problem(workspace):
    """SolveSubproblems stamps each sub_result with the content signature
    of exactly the inputs it solved; an unedited session recomputes the
    identical hash for every block (the warm-start validity proof)."""
    from cluster_tools_tpu.workflows import multicut as mc

    session = _session(workspace)
    assert session.blocking.n_blocks == 27
    n_checked = 0
    for bid in range(session.blocking.n_blocks):
        disk = mc.load_sub_result(_paths(workspace)["problem"], 0, bid)
        if disk is None:
            continue
        assert disk[1] == session.block_signature(bid)[0], bid
        n_checked += 1
    assert n_checked == 27


def test_resolver_affected_blocks_criterion(workspace):
    """A block is affected iff its node set holds >= 2 of the edit's
    fragments — cross-checked against a brute-force scan."""
    from cluster_tools_tpu.edits import resolve_affected

    session = _session(workspace)
    table = np.load(_paths(workspace)["assignments"])
    a, b = _pick_pair(session, table, same_segment=False)
    got = resolve_affected(_paths(workspace)["problem"], [a, b])
    expect = [bid for bid in range(session.blocking.n_blocks)
              if int(np.isin(np.asarray([a, b], "uint64"),
                             session.block_nodes(bid)).sum()) >= 2]
    assert got == expect and got
    # fragments that never share a block resolve to the empty set (the
    # reduce/global stage still sees their biased edge): pick one from
    # each of two opposite corner blocks
    nonempty = [bid for bid in range(session.blocking.n_blocks)
                if len(session.block_nodes(bid))]
    f1 = int(session.block_nodes(nonempty[0])[0])
    for f2 in session.block_nodes(nonempty[-1]):
        if not resolve_affected(_paths(workspace)["problem"],
                                [f1, int(f2)]):
            break
    else:
        pytest.skip("corner fragments unexpectedly share a block")
    assert resolve_affected(_paths(workspace)["problem"],
                            [f1, int(f2)]) == []


def test_resolver_paintera_narrowing_agrees_with_full_scan(workspace):
    """The paintera label-to-block lookup only NARROWS candidates: the
    narrowed resolve equals the full scan, and a missing fragment in the
    lookup degrades to the full scan rather than missing blocks."""
    from cluster_tools_tpu.core.blocking import Blocking
    from cluster_tools_tpu.core.storage import VarlenDataset, file_reader
    from cluster_tools_tpu.edits import resolve_affected

    p = _paths(workspace)
    with file_reader(p["data"], "r") as f:
        frags = f["ws"][:]
    # hand-build the lookup on a DIFFERENT grid than the subproblem one
    # so the voxel-ROI conversion is actually exercised
    paintera_bs = [12, 12, 12]
    lookup_key = "seg/label-to-block-mapping/s0"
    paintera_path = str(workspace / "paintera.n5")
    data_blocking = Blocking(list(frags.shape), paintera_bs)
    inv = {}
    for dbid in range(data_blocking.n_blocks):
        for lab in np.unique(frags[data_blocking.get_block(dbid).bb]):
            inv.setdefault(int(lab), []).append(dbid)
    ds = VarlenDataset(os.path.join(paintera_path, lookup_key),
                       dtype="uint64")
    for lab, blocks in inv.items():
        ds.write_chunk((lab,), np.asarray(blocks, "uint64"))

    session = _session(workspace)
    table = np.load(p["assignments"])
    a, b = _pick_pair(session, table, same_segment=False)
    full = resolve_affected(p["problem"], [a, b])
    narrowed = resolve_affected(
        p["problem"], [a, b], paintera_path=paintera_path,
        paintera_lookup_key=lookup_key, paintera_block_shape=paintera_bs)
    assert narrowed == full and full
    # a lookup that does not know fragment b -> full-scan fallback
    os.remove(os.path.join(paintera_path, lookup_key, f"chunk_{b}.npy"))
    assert resolve_affected(
        p["problem"], [a, b], paintera_path=paintera_path,
        paintera_lookup_key=lookup_key,
        paintera_block_shape=paintera_bs) == full


# ---------------------------------------------------------------------------
# incremental solver
# ---------------------------------------------------------------------------


def test_noop_resolve_is_fully_warm_and_stable(workspace):
    """Re-solving WITHOUT any edit reuses every persisted subproblem
    solution (zero cold solves) and stable-relabels to the committed LUT
    bit-identically."""
    from cluster_tools_tpu.edits import stable_relabel

    session = _session(workspace)
    labels = session.solve(incremental=True)
    assert session.counters["subproblems_solved"] == 0
    assert session.counters["warm_reused"] == 27
    assert session.counters["fallback"] == 0
    old_table = np.load(_paths(workspace)["assignments"])
    new_table = stable_relabel(old_table, session.s0_nodes.astype("int64"),
                               labels)
    np.testing.assert_array_equal(new_table, old_table)
    # second solve: served from the in-memory cache, still zero cold
    session.solve(incremental=True)
    assert session.counters["subproblems_solved"] == 0


def _solve_and_patch(session, rec, assignments, incremental):
    """Apply + solve + stable-relabel WITHOUT touching the on-disk LUT;
    returns the would-be new table."""
    from cluster_tools_tpu.edits import stable_relabel

    affected = session.apply_edit(rec)
    labels = session.solve(incremental=incremental, expected=set(affected),
                           corr_id=rec.edit_id)
    old = np.load(assignments)
    return affected, stable_relabel(old, session.s0_nodes.astype("int64"),
                                    labels)


@pytest.mark.parametrize("op", ["merge", "split"])
def test_incremental_identical_to_scratch(workspace, op):
    """The acceptance gate: warm-started incremental re-solve and a
    from-scratch re-solve of the edited problem produce IDENTICAL
    assignments — and the edit actually took effect."""
    from cluster_tools_tpu.edits import EditLog

    p = _paths(workspace)
    table = np.load(p["assignments"])
    probe = _session(workspace)
    a, b = _pick_pair(probe, table, same_segment=(op == "split"))
    log = EditLog(str(workspace / "edits.jsonl"))
    rec = log.append(op, [a, b])

    inc = _session(workspace)
    affected, table_inc = _solve_and_patch(inc, rec, p["assignments"],
                                           incremental=True)
    assert affected
    # warm start did its job: cold solves bounded by the edit footprint,
    # no stale-cache fallbacks on a healthy container
    assert 0 < inc.counters["subproblems_solved"] <= len(affected)
    assert inc.counters["fallback"] == 0
    assert inc.counters["warm_reused"] >= 27 - len(affected)

    scratch = _session(workspace)
    scratch.replay(log)
    labels_scr = scratch.solve(incremental=False)
    assert scratch.counters["subproblems_solved"] == 27
    from cluster_tools_tpu.edits import stable_relabel

    table_scr = stable_relabel(np.load(p["assignments"]),
                               scratch.s0_nodes.astype("int64"), labels_scr)
    np.testing.assert_array_equal(table_inc, table_scr)
    if op == "merge":
        assert table_inc[a] == table_inc[b] and table[a] != table[b]
    else:
        assert table_inc[a] != table_inc[b] and table[a] == table[b]
    # untouched segments kept their ids: the delta is local to the edit
    changed = np.flatnonzero(table_inc != table)
    assert 0 < changed.size < len(table) // 2


def test_stale_cache_falls_back_with_flight_record(workspace, tmp_path):
    """A persisted sub_result whose signature no longer matches the live
    problem OUTSIDE the edit's footprint is never trusted: full solve,
    fallback counter, flight record carrying the edit's correlation id —
    and the output still matches from-scratch."""
    from cluster_tools_tpu.edits import EditLog, stable_relabel
    from cluster_tools_tpu.workflows import multicut as mc

    p = _paths(workspace)
    table = np.load(p["assignments"])
    probe = _session(workspace)
    a, b = _pick_pair(probe, table, same_segment=False)
    affected_probe = set(probe.affected_blocks([a, b]))
    stale_bid = next(bid for bid in range(probe.blocking.n_blocks)
                     if bid not in affected_probe
                     and len(probe.block_nodes(bid)))
    # corrupt the stored signature (content untouched: the point is the
    # session must NOT reuse it even though the cut ids happen to agree)
    path = mc._sub_result_path(p["problem"], 0, stale_bid)
    with np.load(path) as d:
        cut_ids = d["cut_edge_ids"]
    np.savez(path, cut_edge_ids=cut_ids,
             signature=np.asarray("0" * 16))

    flight_dir = str(tmp_path / "flight")
    log = EditLog(str(workspace / "edits.jsonl"))
    rec = log.append("merge", [a, b], edit_id="corr-42")
    session = _session(workspace, flight_dir=flight_dir)
    affected, table_inc = _solve_and_patch(session, rec, p["assignments"],
                                           incremental=True)
    assert session.counters["fallback"] == 1
    recs = glob.glob(os.path.join(flight_dir, "flightrec_*.json"))
    assert len(recs) == 1
    with open(recs[0]) as f:
        doc = json.load(f)
    assert doc["reason"] == "edit-warm-fallback"
    assert doc["extra"]["edit_id"] == "corr-42"
    assert doc["extra"]["block"] == stale_bid
    assert doc["extra"]["live_signature"] != doc["extra"]["stored_signature"]
    assert doc["extra"]["expected_blocks"] == sorted(affected)

    scratch = _session(workspace)
    scratch.replay(log)
    table_scr = stable_relabel(
        np.load(p["assignments"]), scratch.s0_nodes.astype("int64"),
        scratch.solve(incremental=False))
    np.testing.assert_array_equal(table_inc, table_scr)


def test_unknown_fragment_rejected(workspace):
    session = _session(workspace)
    with pytest.raises(ValueError, match="unknown fragment"):
        session.dense_index([int(session.s0_nodes.max()) + 1000, 1])


# ---------------------------------------------------------------------------
# paintera assignment round-trip (ISSUE 19 satellite)
# ---------------------------------------------------------------------------


def test_paintera_pairs_roundtrip_and_offset_convention():
    from cluster_tools_tpu.workflows.paintera import (assignment_to_pairs,
                                                      pairs_to_table)

    table = np.asarray([0, 3, 3, 5, 1, 5], "uint64")
    pairs = assignment_to_pairs(table)
    # segment ids offset past the largest FRAGMENT id: the two id spaces
    # never collide (dense table: offset == len(table))
    assert pairs.shape == (2, 5)           # fragment 0 dropped
    assert pairs[1].min() >= pairs[0].max() + 1
    assert int(pairs[1][0]) == 3 + len(table)
    back = pairs_to_table(pairs, n_labels=len(table))
    np.testing.assert_array_equal(back, table)
    # empty-assignment edge case round-trips to all-background
    empty = assignment_to_pairs(np.zeros(0, "uint64"))
    assert empty.shape == (2, 0)
    np.testing.assert_array_equal(pairs_to_table(empty, n_labels=4),
                                  np.zeros(4, "uint64"))


def test_paintera_assignment_disk_roundtrip(workspace, tmp_path):
    """load_assignments -> LUT patch (no-op) -> re-load is bit-identical,
    and the paintera pairs dataset survives shape-changing rewrites."""
    from cluster_tools_tpu.edits import patch_assignment_table
    from cluster_tools_tpu.workflows.paintera import (
        assignment_to_pairs, load_fragment_segment_assignment,
        pairs_to_table, write_fragment_segment_assignment)
    from cluster_tools_tpu.workflows.write import load_assignments

    p = _paths(workspace)
    session = _session(workspace)
    table = load_assignments(p["assignments"], None)
    new_table, changed = patch_assignment_table(
        p["assignments"], session.s0_nodes.astype("int64"),
        table[session.s0_nodes.astype("int64")])
    assert changed.size == 0               # identity labels -> no-op patch
    np.testing.assert_array_equal(load_assignments(p["assignments"], None),
                                  table)

    paintera = str(tmp_path / "paintera.n5")
    assert load_fragment_segment_assignment(paintera, "seg") is None \
        or True  # container absent is fine before the first write
    write_fragment_segment_assignment(paintera, "seg",
                                      assignment_to_pairs(table))
    pairs = load_fragment_segment_assignment(paintera, "seg")
    np.testing.assert_array_equal(pairs_to_table(pairs,
                                                 n_labels=len(table)), table)
    # shape-changing rewrite (fewer pairs) goes through recreate
    small = assignment_to_pairs(table[:5])
    write_fragment_segment_assignment(paintera, "seg", small)
    np.testing.assert_array_equal(
        load_fragment_segment_assignment(paintera, "seg"), small)


# ---------------------------------------------------------------------------
# the full edit lane on the resident server
# ---------------------------------------------------------------------------


def test_edit_pipeline_on_server_end_to_end(workspace):
    """submit -> resolve -> incremental solve -> LUT patch -> block
    rewrite through the server's edit lane: the LUT and the segmentation
    volume update consistently, only touched blocks are rewritten, and
    the edit's metrics/log/status all line up."""
    from cluster_tools_tpu.core import telemetry
    from cluster_tools_tpu.core.blocking import Blocking
    from cluster_tools_tpu.core.server import ResidentSegmentationServer
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.edits import EditLog, EditPipeline

    from test_server import StubPipeline

    p = _paths(workspace)
    with file_reader(p["data"], "r") as f:
        frags, seg_before = f["ws"][:], f["seg"][:]
    table_before = np.load(p["assignments"])
    session = _session(workspace)
    a, b = _pick_pair(session, table_before, same_segment=False)

    log = EditLog(str(workspace / "edits.jsonl"))
    pipe = EditPipeline(
        session, log, p["assignments"], ws_path=p["data"], ws_key="ws",
        output_path=p["data"], output_key="seg")
    srv = ResidentSegmentationServer(str(workspace / "srv"), StubPipeline(),
                                     metrics_path="",
                                     lane_pipelines={"edit": pipe})
    h = srv.submit("ann", {"op": "merge", "fragments": [a, b]}, lane="edit")
    while srv.step_once():
        pass
    res = h.result(0)
    assert res["op"] == "merge" and res["fragments"] == sorted([a, b])
    assert res["edit_id"] == log.records()[0].edit_id
    assert res["affected_blocks"] and res["changed_fragments"] > 0
    assert res["round_trip_s"] > 0
    assert res["counters"]["applied"] == 1
    with open(h.status_path) as f:
        status = json.load(f)
    assert status["state"] == "done" and status["lane"] == "edit"
    assert status["n_blocks"] == len(res["affected_blocks"])

    table_after = np.load(p["assignments"])
    assert table_after[a] == table_after[b]
    with file_reader(p["data"], "r") as f:
        seg_after = f["seg"][:]
    # the volume reflects the patched LUT everywhere...
    np.testing.assert_array_equal(seg_after, table_after[frags])
    # ...yet only the touched blocks were actually rewritten
    assert res["touched_blocks"]
    assert pipe.blocks_rewritten == len(res["touched_blocks"])
    blocking = Blocking(list(frags.shape), session.block_shape)
    untouched = [bid for bid in range(blocking.n_blocks)
                 if bid not in res["touched_blocks"]]
    assert untouched
    for bid in untouched:
        bb = blocking.get_block(bid).bb
        np.testing.assert_array_equal(seg_after[bb], seg_before[bb])

    # metrics families use the registered ctt_edit_* names and render to
    # lintable exposition text
    families = pipe.metrics_families()
    names = [fam[0] for fam in families]
    assert names == ["ctt_edit_applied_total", "ctt_edit_subproblems_total",
                     "ctt_edit_warm_reused_total", "ctt_edit_fallback_total",
                     "ctt_edit_blocks_rewritten_total",
                     "ctt_edit_round_trip_seconds"]
    for name in names:
        assert telemetry.is_registered_metric(name), name
    prom = str(workspace / "edit_metrics.prom")
    telemetry.write_prometheus(prom, families)
    with open(prom) as f:
        text = f.read()
    assert telemetry.lint_prometheus(text) == []
    assert "ctt_edit_applied_total 1" in text
    assert "ctt_edit_round_trip_seconds_bucket" in text


def test_edit_pipeline_spans_carry_edit_stages(workspace):
    """Every phase of a server-driven edit lands under its registered
    edit:* stage in the span stream."""
    from cluster_tools_tpu.core import telemetry
    from cluster_tools_tpu.core.server import ResidentSegmentationServer
    from cluster_tools_tpu.edits import EditLog, EditPipeline

    from test_server import StubPipeline

    telemetry.configure(enabled=True)
    p = _paths(workspace)
    session = _session(workspace)
    table = np.load(p["assignments"])
    a, b = _pick_pair(session, table, same_segment=True)
    pipe = EditPipeline(session, EditLog(str(workspace / "edits.jsonl")),
                        p["assignments"], ws_path=p["data"], ws_key="ws",
                        output_path=p["data"], output_key="seg")
    srv = ResidentSegmentationServer(str(workspace / "srv"), StubPipeline(),
                                     metrics_path="",
                                     lane_pipelines={"edit": pipe})
    h = srv.submit("ann", {"op": "split", "fragments": [a, b]}, lane="edit")
    while srv.step_once():
        pass
    h.result(0)
    stages = {s.name for s in telemetry.spans_snapshot()
              if s.cat == "stage"}
    for st in ("edit:resolve", "edit:solve", "edit:patch", "edit:write"):
        assert st in stages, (st, sorted(stages))
        assert telemetry.is_registered(st), st
