"""On-device label-overlap counting.

TPU-native replacement for ``nifty.distributed.computeAndSerializeLabelOverlaps``
/ ``nifty.ground_truth.overlap`` (reference: node_labels/block_node_labels.py:153,
utils/validation_utils.py:24).  The reference counts co-occurrences of two
label volumes in C++; here the counting is a jitted device program built from
XLA-friendly primitives — a lexicographic sort over packed pair keys plus a
segmented sum — with static shapes throughout (run boundaries are returned as
a validity mask, the same padded-output convention as ops/rag.py).

Labels must be densified to int32 before transfer (ops/rag.py
``densify_labels``); callers map results back through the LUTs.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .rag import densify_labels


@jax.jit
def _overlap_runs(a: jnp.ndarray, b: jnp.ndarray):
    """Sort flat (a, b) id pairs lexicographically and count equal runs.

    Returns (a_sorted, b_sorted, run_start_mask, run_counts) — all of length
    len(a); ``run_counts[k]`` is the size of the k-th run for k < n_runs,
    zero-padded beyond.
    """
    order = jnp.lexsort((b, a))
    a_s = a[order]
    b_s = b[order]
    prev_a = jnp.concatenate([jnp.full((1,), -1, a_s.dtype), a_s[:-1]])
    prev_b = jnp.concatenate([jnp.full((1,), -1, b_s.dtype), b_s[:-1]])
    starts = (a_s != prev_a) | (b_s != prev_b)
    run_id = jnp.cumsum(starts) - 1
    counts = jax.ops.segment_sum(
        jnp.ones_like(a_s, dtype=jnp.int32), run_id, num_segments=a_s.size)
    return a_s, b_s, starts, counts


def count_overlaps(seg_a: np.ndarray, seg_b: np.ndarray
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Co-occurrence counts of two label volumes of identical shape.

    Returns (ids_a, ids_b, counts): for each distinct (a, b) pair of labels
    occurring at the same voxel, how many voxels share it.  Counting runs on
    device over densified ids; the result is exact uint64 labels.
    """
    seg_a = np.asarray(seg_a)
    seg_b = np.asarray(seg_b)
    if seg_a.shape != seg_b.shape:
        raise ValueError(f"shape mismatch: {seg_a.shape} vs {seg_b.shape}")
    lut_a, dense_a = densify_labels(seg_a)
    lut_b, dense_b = densify_labels(seg_b)
    a_s, b_s, starts, counts = _overlap_runs(
        jnp.asarray(dense_a.ravel()), jnp.asarray(dense_b.ravel()))
    a_s = np.asarray(a_s)
    b_s = np.asarray(b_s)
    idx = np.flatnonzero(np.asarray(starts))
    counts = np.asarray(counts)[: len(idx)].astype("uint64")
    return lut_a[a_s[idx]], lut_b[b_s[idx]], counts
