"""Derived pixel maps: affinities from labels, object insertion, embedding
distances, smoothed gradients.

Re-specification of the reference's ``affinities/`` package
(insert_affinities.py:159-213 — paste object-derived affinities into a
predicted affinity map; embedding_distances.py:139-165 — affinities from
pixel embeddings; gradients.py:131-176 — smoothed gradient maps).  The
affinity computation (affogato compute_affinities equivalent) is a jitted
shifted-equality over the offset channels — pure device work."""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..core.blocking import Blocking
from ..core.runtime import BlockTask
from ..core.storage import file_reader


def compute_affinities(labels: np.ndarray,
                       offsets: Sequence[Sequence[int]]) -> np.ndarray:
    """(C, *shape) float32 affinities: channel c is 1 where voxel i and
    voxel i + offsets[c] carry the same nonzero label (affogato
    compute_affinities equivalent, device compute)."""
    import jax.numpy as jnp

    from ..ops.rag import densify_labels

    _, dense = densify_labels(np.asarray(labels))
    x = jnp.asarray(dense)
    out = []
    for off in offsets:
        shifted = x
        valid = jnp.ones_like(x, dtype=bool)
        for ax, o in enumerate(off):
            shifted = jnp.roll(shifted, -o, axis=ax)
            idx = jnp.arange(x.shape[ax])
            ok = (idx + o >= 0) & (idx + o < x.shape[ax])
            shape = [1] * x.ndim
            shape[ax] = -1
            valid = valid & ok.reshape(shape)
        aff = (x == shifted) & (x > 0) & valid
        out.append(aff)
    return np.asarray(jnp.stack(out).astype(jnp.float32))


def embedding_distance_affinities(embeddings: np.ndarray,
                                  offsets: Sequence[Sequence[int]],
                                  norm: str = "l2") -> np.ndarray:
    """(C, *shape) affinities from pixel embeddings (E, *shape): channel c =
    exp(-||e_i - e_{i+off}||) (reference: embedding_distances.py:139-165)."""
    import jax.numpy as jnp

    e = jnp.asarray(embeddings.astype("float32"))
    out = []
    for off in offsets:
        shifted = e
        for ax, o in enumerate(off):
            shifted = jnp.roll(shifted, -o, axis=ax + 1)
        if norm == "l2":
            d = jnp.sqrt(((e - shifted) ** 2).sum(axis=0))
        elif norm == "cosine":
            num = (e * shifted).sum(axis=0)
            den = jnp.maximum(
                jnp.linalg.norm(e, axis=0) * jnp.linalg.norm(shifted, axis=0),
                1e-6)
            d = 1.0 - num / den
        else:
            raise ValueError(f"unknown norm {norm}")
        out.append(jnp.exp(-d))
    return np.asarray(jnp.stack(out))


class InsertAffinities(BlockTask):
    """Paste object-derived affinities into a predicted affinity map
    (reference: insert_affinities.py:159-213): where dilated objects exist,
    affinities become the max of prediction and object affinity."""

    task_name = "insert_affinities"

    def __init__(self, input_path: str, input_key: str, objects_path: str,
                 objects_key: str, output_path: str, output_key: str,
                 offsets: Sequence[Sequence[int]], **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.objects_path = objects_path
        self.objects_key = objects_key
        self.output_path = output_path
        self.output_key = output_key
        self.offsets = [list(o) for o in offsets]
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"dilate_by": 2})
        return conf

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            in_shape = list(f[self.input_key].shape)
        assert len(in_shape) == 4
        shape = in_shape[1:]
        block_shape = self.global_block_shape()[-3:]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=in_shape,
                              chunks=[1] + block_shape, dtype="float32")
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "objects_path": self.objects_path,
            "objects_key": self.objects_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "offsets": self.offsets,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from scipy.ndimage import binary_dilation

        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        offsets = cfg["offsets"]
        halo = np.abs(np.asarray(offsets)).max(axis=0).tolist()
        dilate_by = int(cfg.get("dilate_by", 2))
        halo = [h + dilate_by for h in halo]
        f_in = file_reader(cfg["input_path"], "r")
        f_obj = file_reader(cfg["objects_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in = f_in[cfg["input_key"]]
        ds_obj = f_obj[cfg["objects_key"]]
        ds_out = f_out[cfg["output_key"]]

        for block_id in job_config["block_list"]:
            bh = blocking.get_block_with_halo(block_id, halo)
            inner = (slice(None),) + bh.inner.bb
            local = (slice(None),) + bh.inner_local.bb
            objs = np.asarray(ds_obj[bh.outer.bb])
            if not objs.any():
                ds_out[inner] = np.asarray(ds_in[inner])
                log_fn(f"processed block {block_id}")
                continue
            affs = np.asarray(
                ds_in[(slice(None),) + bh.outer.bb]).astype("float32")
            if dilate_by > 0:
                grown = binary_dilation(objs > 0, iterations=dilate_by)
                # grow object ids into the dilated ring (nearest label via
                # one graph-watershed-free trick: keep original ids, dilated
                # ring gets the id of the nearest object voxel along axes)
                from scipy.ndimage import distance_transform_edt

                _, idx = distance_transform_edt(objs == 0,
                                                return_indices=True)
                objs = np.where(grown, objs[tuple(idx)], objs)
            obj_affs = compute_affinities(objs, offsets)
            affs = np.maximum(affs, obj_affs)
            ds_out[inner] = affs[local]
            log_fn(f"processed block {block_id}")


class SmoothedGradients(BlockTask):
    """Gaussian gradient-magnitude map (reference: gradients.py:131-176),
    device filters (ops/filters)."""

    task_name = "smoothed_gradients"

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, sigma: float = 2.0, **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.sigma = sigma
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            shape = list(f[self.input_key].shape)
        block_shape = self.global_block_shape()[-len(shape):]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=shape,
                              chunks=block_shape, dtype="float32")
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "sigma": self.sigma,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        import jax.numpy as jnp

        from ..ops.filters import gaussian_gradient_magnitude

        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        sigma = cfg["sigma"]
        halo = [int(4 * sigma + 1)] * blocking.ndim
        f_in = file_reader(cfg["input_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in, ds_out = f_in[cfg["input_key"]], f_out[cfg["output_key"]]
        for block_id in job_config["block_list"]:
            bh = blocking.get_block_with_halo(block_id, halo)
            x = np.asarray(ds_in[bh.outer.bb]).astype("float32")
            g = np.asarray(gaussian_gradient_magnitude(jnp.asarray(x), sigma))
            ds_out[bh.inner.bb] = g[bh.inner_local.bb]
            log_fn(f"processed block {block_id}")
