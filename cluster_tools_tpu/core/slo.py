"""Latency-SLO engine: declarative objectives, multi-window burn rates,
and the ``overload`` signal (L0.5 observability, ISSUE 16 tentpole 2).

PR 15 gave the resident server *metrics*; this module gives it a
*definition of good*.  An :class:`Objective` says what the serve path
promises per request lane ("99% of edit requests under 250 ms"); the
:class:`SLOEngine` consumes request completions and answers two
questions the scheduler work of ROADMAP item 3 needs answered
continuously:

* **How fast is the error budget burning?**  For each objective and
  each configured window, ``burn_rate = error_rate / (1 - target)`` —
  burn 1.0 spends the budget exactly at the sustainable rate, burn 14
  exhausts a 30-day budget in ~2 days (the classic SRE fast-burn
  threshold).
* **Is the service overloaded right now?**  The multi-window AND rule:
  an objective breaches only when EVERY window's burn rate exceeds its
  threshold — the short window gives fast detection, the long window
  rejects blips.  ``overload`` is true when any objective breaches; the
  server exports it as the ``ctt_server_overload`` gauge and consults
  it in the admission-control hook point (``admission_hook``), which is
  the gate future request-batching / priority-lane scheduling aims at.

Design constraints: pure host python, no deps; the clock is injectable
(the load harness's deterministic virtual-time mode shares one clock
between generator, server and engine); event storage is a bounded deque
so an always-on service cannot grow SLO state forever.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Iterable, List, NamedTuple, \
    Optional, Sequence, Tuple


class Objective(NamedTuple):
    """One service-level objective over a request lane.

    ``latency_s=None`` makes it a pure availability objective (a request
    is bad iff it failed); with a threshold, a request is bad when it
    failed OR took longer than ``latency_s`` — the Prometheus
    "good events / total events" formulation, so compliance and burn
    rate come straight from event counts.
    """

    name: str
    lane: str = "*"                  # "*" matches every lane
    latency_s: Optional[float] = None
    target: float = 0.99             # compliance target in (0, 1)


#: (window_seconds, max_burn_rate) pairs, short window first.  The
#: thresholds follow the SRE multiwindow ladder shape (fast window
#: tolerates a high burn, slow window a low one); the absolute window
#: lengths are tuned for a bench/serve session, not a 30-day budget —
#: pass explicit windows for production-length accounting.
DEFAULT_WINDOWS: Tuple[Tuple[float, float], ...] = ((60.0, 14.0),
                                                    (600.0, 6.0))


def default_objectives() -> List[Objective]:
    """The serve-path defaults BENCH_serve scores against: interactive
    edits get a tight tail bound, bulk re-runs a loose one, and every
    lane shares an availability floor."""
    return [
        Objective("edit-latency", lane="edit", latency_s=0.25,
                  target=0.95),
        Objective("bulk-latency", lane="bulk", latency_s=2.0,
                  target=0.90),
        Objective("availability", lane="*", latency_s=None,
                  target=0.999),
    ]


def objectives_from_config(cfg: Any) -> Optional[List[Objective]]:
    """Parse the ``slo_objectives`` global-config value: a list of
    ``{"name", "lane", "latency_s", "target"}`` dicts.  ``None``/empty
    returns None (caller falls back to :func:`default_objectives`)."""
    if not cfg:
        return None
    out = []
    for row in cfg:
        out.append(Objective(
            name=str(row["name"]),
            lane=str(row.get("lane", "*")),
            latency_s=(None if row.get("latency_s") is None
                       else float(row["latency_s"])),
            target=float(row.get("target", 0.99))))
    return out


class SLOEngine:
    """Sliding-window burn-rate computation over request completions.

    ``record(lane, latency_s, ok)`` is called by the server on every
    terminal request; ``report()`` evaluates every objective over every
    window; ``overload()`` is the boolean the admission hook consults.
    Thread-safe (one lock around the event deque); the bench embeds
    ``report()`` verbatim in BENCH_serve.json.
    """

    def __init__(self, objectives: Optional[Sequence[Objective]] = None,
                 windows: Sequence[Tuple[float, float]] = DEFAULT_WINDOWS,
                 clock: Callable[[], float] = time.monotonic,
                 max_events: int = 1 << 16):
        self.objectives = (list(objectives) if objectives is not None
                           else default_objectives())
        for obj in self.objectives:
            if not 0.0 < obj.target < 1.0:
                raise ValueError(f"objective {obj.name}: target must be "
                                 f"in (0, 1), got {obj.target}")
        self.windows = tuple(sorted((float(w), float(mb))
                                    for w, mb in windows))
        if not self.windows:
            raise ValueError("need at least one burn-rate window")
        self.clock = clock
        self._events: deque = deque(maxlen=int(max_events))
        self._lock = threading.Lock()
        self.total_events = 0

    # -- ingestion -----------------------------------------------------
    def record(self, lane: str, latency_s: float, ok: bool = True
               ) -> None:
        with self._lock:
            self._events.append((float(self.clock()), str(lane),
                                 float(latency_s), bool(ok)))
            self.total_events += 1

    # -- evaluation ----------------------------------------------------
    @staticmethod
    def _is_bad(obj: Objective, latency_s: float, ok: bool) -> bool:
        if not ok:
            return True
        return obj.latency_s is not None and latency_s > obj.latency_s

    def report(self, now: Optional[float] = None) -> Dict[str, Any]:
        """Every objective x every window: event counts, error rate,
        burn rate, per-window breach, and the multi-window-AND breach
        verdict; plus the engine-level ``overload`` bit."""
        if now is None:
            now = self.clock()
        with self._lock:
            events = list(self._events)
        out: Dict[str, Any] = {"now_s": round(float(now), 6),
                               "windows": [list(w) for w in self.windows],
                               "overload": False, "objectives": []}
        for obj in self.objectives:
            lane_events = [e for e in events
                           if obj.lane == "*" or e[1] == obj.lane]
            budget = 1.0 - obj.target
            row: Dict[str, Any] = {
                "name": obj.name, "lane": obj.lane,
                "latency_s": obj.latency_s, "target": obj.target,
                "windows": [],
            }
            breach_all = True
            for window_s, max_burn in self.windows:
                evs = [e for e in lane_events if e[0] >= now - window_s]
                n = len(evs)
                bad = sum(1 for _, _, lat, ok in evs
                          if self._is_bad(obj, lat, ok))
                err = bad / n if n else 0.0
                burn = err / budget
                breach = burn > max_burn
                row["windows"].append({
                    "window_s": window_s, "events": n, "bad": bad,
                    "error_rate": round(err, 6),
                    "burn_rate": round(burn, 4),
                    "max_burn": max_burn, "breach": breach,
                })
                breach_all = breach_all and breach
            # compliance over the LONGEST window is the headline number
            long_win = row["windows"][-1]
            row["compliance"] = round(1.0 - long_win["error_rate"], 6)
            row["breach"] = breach_all
            out["objectives"].append(row)
            out["overload"] = out["overload"] or breach_all
        return out

    def overload(self, now: Optional[float] = None) -> bool:
        """True when any objective breaches on EVERY window (the
        multi-window AND — fast to trip under sustained overload,
        immune to single-request blips)."""
        return bool(self.report(now)["overload"])

    # -- metrics export ------------------------------------------------
    def metrics_families(self, report: Optional[Dict[str, Any]] = None):
        """``ctt_slo_burn_rate`` / ``ctt_slo_compliance`` gauge families
        for ``telemetry.write_prometheus`` (an already-computed report
        can be passed to avoid evaluating twice)."""
        rep = report if report is not None else self.report()
        burn = [({"objective": o["name"],
                  "window_s": str(int(w["window_s"]))}, w["burn_rate"])
                for o in rep["objectives"] for w in o["windows"]]
        comp = [({"objective": o["name"]}, o["compliance"])
                for o in rep["objectives"]]
        return [
            ("ctt_slo_burn_rate", "gauge",
             "Error-budget burn rate per objective and window",
             burn or [(None, 0.0)]),
            ("ctt_slo_compliance", "gauge",
             "Longest-window compliance ratio per objective",
             comp or [(None, 1.0)]),
        ]
