"""Blockwise mutex watershed over long-range affinities.

Re-specification of the reference's ``mutex_watershed/`` package
(mws_blocks.py:136-174, two_pass_mws.py:100-280, two_pass_assignments.py:26,
mws_workflow.py).  Two stitching strategies, as in the reference:

* **MwsWorkflow** — independent per-block MWS with per-block label offsets
  and a consecutive relabel; no stitching (block boundaries stay cuts).
* **TwoPassMwsWorkflow** — checkerboard two-pass: pass-1 blocks run plain
  MWS; pass-2 blocks run *seeded* MWS where the halo-visible pass-1 labels
  act as seeds, and the (segment, seed) co-occurrences are reconciled by a
  global union-find into one assignment table.

TPU-first deviation from the reference: the pass-1 "seed state" there is a
serialized grid-graph edge dump per block (two_pass_mws.py:174-186 — marked
FIXME-incorrect upstream); here seed consistency is expressed directly in the
edge weights of the seeded pass (ops/mws.py: intra-seed edges get maximal
attraction), which needs no inter-block state files beyond the label volume
itself.  Edge extraction runs on device; the Kruskal clustering in first-party
C++ (native.mutex_clustering).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.blocking import Blocking
from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import FileTarget, Task
from .relabel import RelabelWorkflow
from .write import WriteAssignments


def normalize(data: np.ndarray,
              mx: Optional[float] = None) -> np.ndarray:
    """Affinities to float32 in [0, 1]; integer dtypes scale by their dtype
    range (reference vu.normalize, utils/volume_utils.py:113-120).

    ``mx`` pins the scale for float inputs: blockwise callers MUST pass
    the volume-global max so per-block normalization matches the
    device-resident path, which normalizes the whole volume at once —
    otherwise ``impl='auto'`` changes segmentation results by backend
    (ADVICE r5)."""
    if np.issubdtype(data.dtype, np.integer):
        return data.astype("float32") / np.iinfo(data.dtype).max
    data = data.astype("float32")
    mx = float(data.max()) if mx is None else float(mx)
    return data / np.float32(mx) if mx > 1.0 else data


def _chunked_max(ds, slab_voxels: int = 1 << 26) -> float:
    """Volume-global max with BOUNDED memory: one z-slab of the (channel,
    z, y, x) dataset at a time — never the full volume (the blockwise
    host path exists precisely for volumes that do not fit in RAM)."""
    shape = tuple(ds.shape)
    if 0 in shape:
        return 0.0
    per_row = int(np.prod(shape[:1] + shape[2:]))
    rows = max(int(slab_voxels // max(per_row, 1)), 1)
    mx = -np.inf
    for z0 in range(0, shape[1], rows):
        z1 = min(z0 + rows, shape[1])  # tensorstore rejects overruns
        mx = max(mx, float(np.max(ds[(slice(None), slice(z0, z1))])))
    return mx


class MwsBlocksBase(BlockTask):
    """Shared machinery for the single-pass and two-pass MWS block tasks."""

    # pass_id: None = all blocks (single pass); 0/1 = checkerboard color
    pass_id: Optional[int] = None
    seeded: bool = False

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, offsets: Sequence[Sequence[int]],
                 halo: Optional[Sequence[int]] = None,
                 mask_path: str = "", mask_key: str = "", **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.offsets = [list(o) for o in offsets]
        self.halo = list(halo) if halo is not None else None
        self.mask_path = mask_path
        self.mask_key = mask_key
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"strides": [1, 1, 1], "randomize_strides": False,
                     "noise_level": 0.0})
        return conf

    def run_impl(self):
        global_max = None
        with file_reader(self.input_path, "r") as f:
            ds = f[self.input_key]
            shape = list(ds.shape)
            if (self.task_config.get("impl") == "host"
                    and not np.issubdtype(np.dtype(ds.dtype), np.integer)):
                # normalization parity (ADVICE r5): float inputs need the
                # VOLUME-global max so per-block host normalization
                # matches the device-resident path.  One chunked scan in
                # the driver, reused by every worker job via the config —
                # but only when the host path is pinned; under 'auto' the
                # device path may win and computes its own volume max, so
                # host-path workers fall back to a lazy per-job scan
                global_max = _chunked_max(ds)
        assert len(shape) == 4, "need 4d (channel, spatial...) input for MWS"
        n_channels, shape = shape[0], shape[1:]
        assert n_channels == len(self.offsets), (n_channels, len(self.offsets))
        block_shape = self.global_block_shape()[-len(shape):]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=shape, chunks=block_shape,
                              dtype="uint64")
        block_list = self.blocks_in_volume(shape, block_shape)
        if self.pass_id is not None:
            colors = Blocking(shape, block_shape).checkerboard()
            allowed = set(block_list)
            block_list = [b for b in colors[self.pass_id] if b in allowed]
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "offsets": self.offsets, "halo": self.halo,
            "mask_path": self.mask_path, "mask_key": self.mask_key,
            "shape": shape, "block_shape": block_shape,
            "seeded": self.seeded, "global_max": global_max,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from ..ops.mws import mutex_watershed_segmentation

        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        halo = cfg["halo"]
        seeded = cfg["seeded"]
        f_in = file_reader(cfg["input_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in, ds_out = f_in[cfg["input_key"]], f_out[cfg["output_key"]]
        mask = None
        if cfg.get("mask_path"):
            from ..core.volume_views import load_mask

            mask = load_mask(cfg["mask_path"], cfg["mask_key"], cfg["shape"])

        # the per-block id budget must cover the halo-enlarged outer block:
        # labels are compacted over the full outer region so halo-only
        # segments keep valid global ids for the seed assignments
        outer_shape = (cfg["block_shape"] if halo is None else
                       [b + 2 * h for b, h in zip(cfg["block_shape"], halo)])
        offset_unit = int(np.prod(outer_shape))

        impl = cfg.get("impl", "auto")
        if impl == "auto":
            import jax

            # the resident device-sort path needs an accelerator to beat
            # the host C++ (and the CPU-jax fallback would silently turn
            # the reference-faithful 'local' baseline into a hybrid)
            impl = ("device" if (jax.default_backend() != "cpu"
                                 and mask is None
                                 and not cfg.get("noise_level")
                                 and not cfg.get("randomize_strides"))
                    else "host")
        if impl == "device" and offset_unit >= (1 << 29):
            # the device edge stream packs partner indices into 29 bits
            # (ops/mws._sorted_edges_device); oversized outer blocks
            # route to the always-correct host path (ADVICE r5)
            log_fn(f"outer block of {offset_unit} voxels exceeds the "
                   "2^29 packed-edge budget; using the host path")
            impl = "host"
        if impl == "device":
            return cls._process_device_sorted(job_config, log_fn, blocking,
                                              ds_in, ds_out, cfg)

        # normalization parity with the device-resident path (which
        # normalizes the WHOLE volume at once): float inputs need the
        # volume-global max — from the driver's scan when impl='host' was
        # pinned (run_impl), else one lazy chunked scan per job; integer
        # scaling is block-independent already
        global_mx = cfg.get("global_max")
        if global_mx is None and not np.issubdtype(
                np.dtype(ds_in.dtype), np.integer):
            global_mx = _chunked_max(ds_in)

        for block_id in job_config["block_list"]:
            if halo is None:
                block = blocking.get_block(block_id)
                outer_bb = inner_bb = block.bb
                local_bb = tuple(slice(None) for _ in cfg["shape"])
            else:
                bh = blocking.get_block_with_halo(block_id, halo)
                outer_bb, inner_bb = bh.outer.bb, bh.inner.bb
                local_bb = bh.inner_local.bb
            bb_mask = None
            if mask is not None:
                bb_mask = np.asarray(mask[outer_bb]) > 0
                if not bb_mask.any():
                    log_fn(f"processed block {block_id}")
                    continue
            affs = normalize(ds_in[(slice(None),) + outer_bb],
                             mx=global_mx)
            if affs.sum() == 0:
                log_fn(f"processed block {block_id}")
                continue
            seeds = None
            if seeded:
                # only voxels owned by the *other* checkerboard color carry
                # finished pass-1 labels; halo parts of same-color (pass-2)
                # neighbors may be written concurrently by other jobs, so
                # mask them out — this both removes the read race and makes
                # the result order-independent (the reference leaves this as
                # an unresolved TODO, two_pass_mws.py:212-215)
                seeds = np.asarray(ds_out[outer_bb])
                own_color = sum(blocking.block_grid_position(block_id)) % 2
                grids = np.meshgrid(
                    *[np.arange(b.start, b.stop) // bs
                      for b, bs in zip(outer_bb, cfg["block_shape"])],
                    indexing="ij")
                owner_color = sum(grids) % 2
                seeds[owner_color == own_color] = 0
            seg, seed_assignments = mutex_watershed_segmentation(
                affs, cfg["offsets"], strides=cfg.get("strides"),
                randomize_strides=cfg.get("randomize_strides", False),
                mask=bb_mask, noise_level=cfg.get("noise_level", 0.0),
                seed=block_id, seeds=seeds, return_seed_assignments=True)
            # compact the full (outer) labeling so halo-only segments keep
            # valid global ids for the seed assignments, then offset
            nonzero = np.unique(seg[seg > 0])
            if len(nonzero) >= offset_unit:
                raise RuntimeError(
                    f"block {block_id}: {len(nonzero)} labels exceed the "
                    f"per-block offset budget {offset_unit}")
            compact = np.searchsorted(nonzero, seg).astype("uint64")
            compact += np.uint64(block_id * offset_unit + 1)
            compact[seg == 0] = 0
            ds_out[inner_bb] = compact[local_bb]
            if seeded and len(seed_assignments):
                # map the local segment column through compact+offset; keep
                # only segments visible in the written crop or paired with a
                # seed also seen by this block (reference: two_pass_mws.py
                # :282-292 filters to crop ids)
                seg_col = (np.searchsorted(
                    nonzero, seed_assignments[:, 0]).astype("uint64")
                    + np.uint64(block_id * offset_unit + 1))
                pairs = np.stack(
                    [seg_col, seed_assignments[:, 1].astype("uint64")], axis=1)
                np.save(os.path.join(
                    job_config["tmp_folder"],
                    f"mws_two_pass_assignments_block_{block_id}.npy"), pairs)
            log_fn(f"processed block {block_id}")


    @classmethod
    def _process_device_sorted(cls, job_config, log_fn, blocking, ds_in,
                               ds_out, cfg):
        """Resident device-sort pipeline: the affinity volume uploads ONCE
        (kept on device across the pass-1/pass-2 tasks of one driver
        process), each block's program dynamic-slices its outer window,
        extracts every grid edge and sorts them by descending priority on
        device (ops/mws._sorted_edges_device — the host Kruskal's
        stable_sort of 24-byte edge structs was ~60% of each block), and
        the host runs only the sequential union-find scan — on block i
        while the device sorts block i+1 (the r3 hybrid-pipeline
        pattern)."""
        import jax.numpy as jnp

        from ..core.runtime import stage, stage_bytes
        from ..ops.mws import (mutex_watershed_scan_sorted,
                               _sorted_edges_resident)

        halo = cfg["halo"]
        seeded = cfg["seeded"]
        offsets = tuple(tuple(int(o) for o in off) for off in cfg["offsets"])
        strides = tuple(int(s)
                        for s in (cfg.get("strides") or [1, 1, 1]))
        key = (os.path.abspath(cfg["input_path"]), cfg["input_key"])
        ent = _AFFS_DEV_CACHE.get(key)
        if ent is None:
            with stage("store-read"):
                affs_host = normalize(ds_in[...])
            with stage("h2d-upload"):
                affs_dev = jnp.asarray(affs_host)
            stage_bytes("h2d-upload", affs_host.nbytes)
            _AFFS_DEV_CACHE.clear()   # one resident volume at a time
            _AFFS_DEV_CACHE[key] = affs_dev
        else:
            affs_dev = ent

        outer_shape_of = {}
        block_meta = {}
        for block_id in job_config["block_list"]:
            if halo is None:
                block = blocking.get_block(block_id)
                meta = (block.bb, block.bb,
                        tuple(slice(None) for _ in cfg["shape"]))
            else:
                bh = blocking.get_block_with_halo(block_id, halo)
                meta = (bh.outer.bb, bh.inner.bb, bh.inner_local.bb)
            block_meta[block_id] = meta
            outer_shape_of[block_id] = tuple(
                s.stop - s.start for s in meta[0])
        offset_unit = int(np.prod(
            cfg["block_shape"] if halo is None else
            [b + 2 * h for b, h in zip(cfg["block_shape"], halo)]))

        def submit(block_id):
            outer_bb, _, _ = block_meta[block_id]
            seeds = None
            if seeded:
                # only the *other* checkerboard color carries finished
                # pass-1 labels (same masking as the host path)
                with stage("store-read"):
                    seeds = np.asarray(ds_out[outer_bb])
                own_color = sum(blocking.block_grid_position(block_id)) % 2
                grids = np.meshgrid(
                    *[np.arange(b.start, b.stop) // bs
                      for b, bs in zip(outer_bb, cfg["block_shape"])],
                    indexing="ij")
                owner_color = sum(grids) % 2
                seeds[owner_color == own_color] = 0
            with stage("dispatch"):
                handles = _sorted_edges_resident(
                    affs_dev, tuple(s.start for s in outer_bb),
                    outer_shape_of[block_id], offsets, strides, seeds)
            return handles, seeds

        def drain(block_id, handles, seeds):
            outer_bb, inner_bb, local_bb = block_meta[block_id]
            shape_o = outer_shape_of[block_id]
            # three separately-attributed phases: the wait for the device
            # sort (sync-execute), the edge-stream download (d2h-edges),
            # and the sequential host C++ union-find scan (host-scan) —
            # previously one 'sync-meta' stage that credited the host
            # scan to the accelerator path (ADVICE r5)
            with stage("sync-execute"):
                asum = float(np.asarray(handles[2]))
            if asum == 0.0:
                log_fn(f"processed block {block_id}")
                return
            with stage("d2h-edges"):
                u = np.asarray(handles[0])
                vp = np.asarray(handles[1])
            stage_bytes("d2h-edges", u.nbytes + vp.nbytes)
            with stage("host-scan"):
                seg = mutex_watershed_scan_sorted(u, vp, shape_o)
            nonzero = np.unique(seg[seg > 0])
            if len(nonzero) >= offset_unit:
                raise RuntimeError(
                    f"block {block_id}: {len(nonzero)} labels exceed the "
                    f"per-block offset budget {offset_unit}")
            compact = np.searchsorted(nonzero, seg).astype("uint64")
            compact += np.uint64(block_id * offset_unit + 1)
            compact[seg == 0] = 0
            with stage("store-write"):
                ds_out[inner_bb] = compact[local_bb]
            stage_bytes("store-write", compact[local_bb].nbytes)
            if seeded and seeds is not None and (seeds != 0).any():
                sflat = seeds.reshape(-1)
                lflat = compact.reshape(-1)
                sel = sflat != 0
                pairs = np.unique(np.stack(
                    [lflat[sel], sflat[sel].astype("uint64")], axis=1),
                    axis=0)
                pairs = pairs[pairs[:, 0] != 0]
                np.save(os.path.join(
                    job_config["tmp_folder"],
                    f"mws_two_pass_assignments_block_{block_id}.npy"),
                    pairs)
            log_fn(f"processed block {block_id}")

        pending = None
        for block_id in job_config["block_list"]:
            handles, seeds = submit(block_id)
            if pending is not None:
                drain(*pending)
            pending = (block_id, handles, seeds)
        if pending is not None:
            drain(*pending)


#: device-resident normalized affinity volume, shared by the pass-1 and
#: pass-2 tasks of one driver process (~0.4 GB for the bench instance;
#: cleared when a different volume arrives)
_AFFS_DEV_CACHE: Dict = {}


class MwsBlocks(MwsBlocksBase):
    """Single-pass blockwise MWS (reference: mws_blocks.py)."""

    task_name = "mws_blocks"


class MwsPass1(MwsBlocksBase):
    """Checkerboard color-0 blocks, plain MWS (two_pass_mws.py pass 0)."""

    task_name = "mws_pass1"
    pass_id = 0


class MwsPass2(MwsBlocksBase):
    """Checkerboard color-1 blocks, seeded by pass-1 halo labels
    (two_pass_mws.py pass 1)."""

    task_name = "mws_pass2"
    pass_id = 1
    seeded = True


class TwoPassAssignments(BlockTask):
    """Global union-find over the pass-2 (segment, seed) pairs -> sparse
    consecutive assignment table (reference: two_pass_assignments.py:90-150,
    with the intermediate RelabelWorkflow folded in: the table domain is the
    set of ids actually present, collected by FindUniques)."""

    task_name = "two_pass_assignments"
    global_task = True
    allow_retry = False

    def __init__(self, assignment_path: str, uniques_prefix: str, **kw):
        self.assignment_path = assignment_path
        self.uniques_prefix = uniques_prefix
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "tmp_root": self.tmp_folder,
            "uniques_prefix": self.uniques_prefix,
            "assignment_path": self.assignment_path,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from .. import native

        cfg = job_config["config"]
        tmp = cfg["tmp_root"]
        uniques = []
        prefix = cfg["uniques_prefix"] + "_out_"
        for name in os.listdir(tmp):
            if name.startswith(prefix) and name.endswith(".npy"):
                uniques.append(np.load(os.path.join(tmp, name)))
        ids = np.unique(np.concatenate(uniques)) if uniques else np.zeros(0, "uint64")
        if ids.size == 0 or ids[0] != 0:
            ids = np.concatenate([np.zeros(1, "uint64"), ids])
        pair_arrays = [np.zeros((0, 2), "uint64")]
        for name in os.listdir(tmp):
            if (name.startswith("mws_two_pass_assignments_block_")
                    and name.endswith(".npy")):
                pair_arrays.append(np.load(os.path.join(tmp, name)))
        pairs = np.concatenate(pair_arrays, axis=0)
        # pairs may mention halo-only segment ids absent from the volume;
        # include them as union-find nodes so transitive merges survive
        domain = np.unique(np.concatenate([ids, pairs.ravel()]))
        compact_pairs = np.searchsorted(domain, pairs)
        roots = native.ufd_merge_pairs(len(domain), compact_pairs)
        # consecutive relabel over the ids present in the volume, 0 stays 0
        vol_roots = roots[np.searchsorted(domain, ids)]
        nz_roots = vol_roots[ids != 0]
        uniq_roots = np.unique(nz_roots)
        new_ids = np.zeros(len(ids), dtype="uint64")
        new_ids[ids != 0] = np.searchsorted(uniq_roots, nz_roots) + 1
        table = np.stack([ids, new_ids], axis=1)
        np.save(cfg["assignment_path"], table)
        log_fn(f"merged {len(pairs)} seed pairs over {len(ids)} ids -> "
               f"{len(uniq_roots)} segments")


class MwsWorkflow(Task):
    """MwsBlocks -> RelabelWorkflow (reference: mws_workflow.py:12-56)."""

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, offsets: Sequence[Sequence[int]],
                 tmp_folder: str, config_dir: str, max_jobs: int = 1,
                 target: str = "local", halo: Optional[Sequence[int]] = None,
                 mask_path: str = "", mask_key: str = "",
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.offsets = offsets
        self.halo = halo
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        t1 = MwsBlocks(
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            offsets=self.offsets, halo=self.halo,
            mask_path=self.mask_path, mask_key=self.mask_key,
            dependency=self.dependency, **self._common())
        return RelabelWorkflow(
            input_path=self.output_path, input_key=self.output_key,
            identifier="mws_relabel", dependency=t1, **self._common())

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "write_mws_relabel.status"))


class TwoPassMwsWorkflow(Task):
    """MwsPass1 -> MwsPass2 (seeded) -> FindUniques -> TwoPassAssignments ->
    Write (reference: mws_workflow.py:59-125)."""

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, offsets: Sequence[Sequence[int]],
                 halo: Sequence[int], tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 mask_path: str = "", mask_key: str = "",
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.offsets = offsets
        self.halo = list(halo)
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        from .relabel import FindUniques

        kw = dict(input_path=self.input_path, input_key=self.input_key,
                  output_path=self.output_path, output_key=self.output_key,
                  offsets=self.offsets, halo=self.halo,
                  mask_path=self.mask_path, mask_key=self.mask_key)
        t1 = MwsPass1(dependency=self.dependency, **kw, **self._common())
        t2 = MwsPass2(dependency=t1, **kw, **self._common())
        t3 = FindUniques(input_path=self.output_path,
                         input_key=self.output_key,
                         identifier="two_pass_mws", dependency=t2,
                         **self._common())
        assignment_path = os.path.join(self.tmp_folder,
                                       "two_pass_mws_assignments.npy")
        t4 = TwoPassAssignments(assignment_path=assignment_path,
                                uniques_prefix=t3.name_with_id,
                                dependency=t3, **self._common())
        return WriteAssignments(
            input_path=self.output_path, input_key=self.output_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=assignment_path, identifier="two_pass_mws",
            dependency=t4, **self._common())

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "write_two_pass_mws.status"))
