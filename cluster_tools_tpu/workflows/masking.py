"""ROI masking: block lists from masks, min-filtered masks.

Re-specification of the reference's ``masking/`` package
(blocks_from_mask.py:82-97 — list of blocks intersecting a low-res mask,
written to ``block_list_path`` for the global config; minfilter.py:110-121 —
minimum-filter a mask so only fully-valid regions survive)."""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional, Sequence

import numpy as np

from ..core.blocking import Blocking
from ..core.config import write_config
from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import FileTarget, Task


class BlocksFromMask(Task):
    """Write the list of mask-intersecting blocks to ``block_list_path``
    (feeds the global config's block-list restriction, SURVEY §5.6)."""

    def __init__(self, mask_path: str, mask_key: str, shape: Sequence[int],
                 block_shape: Sequence[int], output_path: str,
                 tmp_folder: str, dependency: Optional[Task] = None):
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.shape = list(shape)
        self.block_shape = list(block_shape)
        self.output_path = output_path
        self.tmp_folder = tmp_folder
        self.dependency = dependency
        super().__init__()

    def requires(self):
        return self.dependency

    def run(self):
        from ..core.volume_views import load_mask

        mask = load_mask(self.mask_path, self.mask_key, self.shape)
        blocking = Blocking(self.shape, self.block_shape)
        blocks = [bid for bid in range(blocking.n_blocks)
                  if np.any(np.asarray(
                      mask[blocking.get_block(bid).bb]) > 0)]
        write_config(self.output_path, blocks)
        self.output().touch()

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "blocks_from_mask.status"))


class MinFilterMask(BlockTask):
    """Blockwise minimum filter over a mask (reference:
    minfilter.py:110-121): shrinks the valid region so every surviving
    voxel has a fully-valid filter window."""

    task_name = "minfilter_mask"

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, filter_shape: Sequence[int], **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.filter_shape = list(filter_shape)
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            shape = list(f[self.input_key].shape)
        block_shape = [min(b, s) for b, s in
                       zip(self.global_block_shape(), shape)]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=shape,
                              chunks=block_shape, dtype="uint8")
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "filter_shape": self.filter_shape,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from scipy.ndimage import minimum_filter

        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        halo = [fs // 2 + 1 for fs in cfg["filter_shape"]]
        f_in = file_reader(cfg["input_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in, ds_out = f_in[cfg["input_key"]], f_out[cfg["output_key"]]
        for block_id in job_config["block_list"]:
            bh = blocking.get_block_with_halo(block_id, halo)
            mask = np.asarray(ds_in[bh.outer.bb])
            filtered = minimum_filter(mask, size=cfg["filter_shape"])
            ds_out[bh.inner.bb] = filtered[bh.inner_local.bb].astype("uint8")
            log_fn(f"processed block {block_id}")
