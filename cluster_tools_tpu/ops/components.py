"""On-device connected components via union-find label propagation.

TPU-native replacement for the reference's per-block ``skimage.label``
(thresholded_components/block_components.py:143-180) and vigra
``labelVolumeWithBackground``.  The algorithm is Shiloach–Vishkin-style
hooking + pointer jumping expressed in pure JAX: every voxel starts as its own
parent; each iteration (a) takes the min parent over face/corner neighbors,
(b) scatter-min "hooks" that value onto the current root, (c) compresses paths
by pointer jumping.  Convergence is O(log d) iterations for component diameter
d — data-independent control flow per iteration, static shapes, fully
jit/vmap-compatible (SPMD over blocks via vmap; over shards via shard_map).

Labels are returned as root-voxel linear indices + 1 (0 = background) —
globally meaningful within the block, made consecutive by the caller when
needed (host-side np.unique, reference semantics of relabelConsecutive).
"""

from __future__ import annotations

from functools import partial
from itertools import product
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _neighbor_offsets(ndim: int, connectivity: int) -> Tuple[Tuple[int, ...], ...]:
    """Neighbor offsets with L1 norm <= connectivity (scipy/skimage convention:
    connectivity=1 -> faces, ndim -> full including corners)."""
    offs = []
    for off in product((-1, 0, 1), repeat=ndim):
        d = sum(abs(o) for o in off)
        if 0 < d <= connectivity:
            offs.append(off)
    return tuple(offs)


def _shifted(arr: jnp.ndarray, offset: Sequence[int], fill) -> jnp.ndarray:
    """Value of the neighbor at position ``i + offset`` for every voxel i,
    with out-of-volume neighbors reading ``fill``.  Static pad+slice (no roll
    wraparound), fuses into one XLA op chain."""
    pads = []
    slices = []
    for o, s in zip(offset, arr.shape):
        if o > 0:
            pads.append((0, o))
            slices.append(slice(o, o + s))
        elif o < 0:
            pads.append((-o, 0))
            slices.append(slice(0, s))
        else:
            pads.append((0, 0))
            slices.append(slice(0, s))
    padded = jnp.pad(arr, pads, constant_values=fill)
    return padded[tuple(slices)]


@partial(jax.jit, static_argnames=("connectivity", "max_iter", "method"))
def connected_components(
    mask: jnp.ndarray, connectivity: int = 1, max_iter: int = 0,
    method: str = "hooking",
) -> jnp.ndarray:
    """Label connected components of a boolean mask.

    Returns an int32 array: 0 for background, ``root_linear_index + 1`` for
    foreground.  ``connectivity`` follows the scipy/skimage convention
    (1 = faces, ndim = full).  ``max_iter=0`` derives a safe bound from the
    volume size (2 * sum(shape) covers the worst-case path with pointer
    jumping's logarithmic compression well before the bound is hit; the loop
    exits early on convergence).

    ``method``: both converge to the identical min-linear-index labeling.
    'hooking' (Shiloach-Vishkin hook + pointer jumping) is O(log d)
    iterations but each costs random gathers/scatters — the right choice for
    large-diameter components.  'propagation' (pure neighbor-min stencil,
    one voxel per iteration) has O(d) iterations of cheap fused VPU work
    with NO gathers — far faster when component diameters are small (e.g.
    watershed seed clusters), where the gather-heavy rounds dominate.
    """
    if method not in ("hooking", "propagation"):
        raise ValueError(f"unknown method {method!r}; "
                         "choose 'hooking' or 'propagation'")
    shape = mask.shape
    n = int(np.prod(shape))
    sentinel = jnp.int32(n)
    mask = mask.astype(bool)
    offsets = _neighbor_offsets(len(shape), connectivity)
    if max_iter == 0:
        if method == "propagation":
            # labels advance 4 voxels per iteration; the only safe
            # data-independent bound on a component diameter is the voxel
            # count (serpentine ridges realize it) — early exit on
            # convergence makes the generous bound free in practice
            max_iter = max(n // 4 + 2, 16)
        else:
            max_iter = max(2 * int(np.sum(shape)), 16)

    idx = jnp.arange(n, dtype=jnp.int32)
    fg = mask.reshape(-1)
    p0 = idx  # every voxel its own parent (background voxels stay fixed points)

    def neighbor_min(p: jnp.ndarray) -> jnp.ndarray:
        grid = jnp.where(mask, p.reshape(shape), sentinel)
        m = grid
        for off in offsets:
            m = jnp.minimum(m, _shifted(grid, off, sentinel))
        return jnp.where(fg, m.reshape(-1), p)

    if method == "propagation":
        def body(state):
            p, _, it = state
            # 4 stencil sweeps per convergence check: amortizes the
            # reduction, keeps everything fused elementwise VPU work
            # (neighbor_min includes the center, so it is monotone)
            p2 = p
            for _ in range(4):
                p2 = neighbor_min(p2)
            return p2, jnp.any(p2 != p), it + 1

        p, _, _ = jax.lax.while_loop(
            lambda s: s[1] & (s[2] < max_iter), body,
            (p0, jnp.bool_(True), jnp.int32(0)))
        return jnp.where(fg, p + 1, 0).reshape(shape).astype(jnp.int32)

    def body(state):
        p, _ = state
        m = neighbor_min(p)
        # hook the improved root onto the current root, then compress
        p2 = p.at[p].min(m)
        p2 = p2[p2]
        p2 = p2[p2]
        changed = jnp.any(p2 != p)
        return p2, changed

    def cond(state):
        return state[1]

    p, _ = jax.lax.while_loop(cond, body, (p0, jnp.bool_(True)))
    return jnp.where(fg, p + 1, 0).reshape(shape).astype(jnp.int32)


@partial(jax.jit, static_argnames=("connectivity",))
def connected_components_batched(
    masks: jnp.ndarray, connectivity: int = 1
) -> jnp.ndarray:
    """CC over a batch of equally-shaped blocks (leading batch axis).

    The batch shares one jitted program — blocks are processed SPMD via vmap,
    the TPU-native replacement for the reference's one-subprocess-per-block
    fan-out.
    """
    return jax.vmap(lambda m: connected_components(m, connectivity=connectivity))(masks)


def relabel_consecutive(
    labels: np.ndarray, start_label: int = 1, keep_zeros: bool = True
) -> Tuple[np.ndarray, int]:
    """Host-side consecutive relabeling (reference: vigra relabelConsecutive,
    used ubiquitously).  Returns (relabeled, max_id)."""
    labels = np.asarray(labels)
    uniques = np.unique(labels)
    if keep_zeros and uniques.size and uniques[0] == 0:
        nonzero = uniques[1:]
        mapping_vals = np.arange(start_label, start_label + nonzero.size,
                                 dtype=labels.dtype)
        lookup = {0: 0}
        new = np.searchsorted(nonzero, labels)
        out = np.where(labels == 0, 0, new + start_label).astype(np.uint64)
        max_id = start_label + nonzero.size - 1 if nonzero.size else 0
        del mapping_vals, lookup
        return out, int(max_id)
    new = np.searchsorted(uniques, labels)
    out = (new + start_label).astype(np.uint64)
    return out, int(start_label + uniques.size - 1)


def threshold_volume(
    x: jnp.ndarray, threshold: float, mode: str = "greater"
) -> jnp.ndarray:
    """Thresholding modes of the reference (block_components.py)."""
    if mode == "greater":
        return x > threshold
    if mode == "less":
        return x < threshold
    if mode == "equal":
        return x == threshold
    raise ValueError(f"unknown threshold mode {mode}")
