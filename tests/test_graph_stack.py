"""Graph -> features -> costs stack vs brute-force numpy oracles."""

import os

import numpy as np
import pytest


def _toy_labels(shape=(16, 16, 16), n_seeds=12, seed=0):
    """Voronoi labeling: dense supervoxel-like segmentation, labels 1..n."""
    rng = np.random.RandomState(seed)
    pts = rng.rand(n_seeds, 3) * np.array(shape)
    grids = np.meshgrid(*[np.arange(s) for s in shape], indexing="ij")
    coords = np.stack(grids, -1).astype("float32")
    d = np.stack([np.linalg.norm(coords - p, axis=-1) for p in pts])
    return (np.argmin(d, axis=0) + 1).astype("uint64")


def _brute_force_rag(labels, ignore_label=True):
    pairs = []
    for axis in range(labels.ndim):
        a = np.moveaxis(labels, axis, 0)[:-1].ravel()
        b = np.moveaxis(labels, axis, 0)[1:].ravel()
        m = a != b
        if ignore_label:
            m &= (a != 0) & (b != 0)
        pairs.append(np.stack([np.minimum(a[m], b[m]),
                               np.maximum(a[m], b[m])], 1))
    return np.unique(np.concatenate(pairs), axis=0)


def _write_volume(path, key, data, chunks):
    from cluster_tools_tpu.core.storage import file_reader

    with file_reader(path) as f:
        ds = f.require_dataset(key, shape=data.shape, chunks=chunks,
                               dtype=str(data.dtype))
        ds[:] = data


@pytest.fixture()
def graph_setup(tmp_path, tmp_workdir):
    tmp_folder, config_dir = tmp_workdir
    labels = _toy_labels()
    path = str(tmp_path / "data.n5")
    _write_volume(path, "labels", labels, (10, 10, 10))
    return labels, path, tmp_folder, config_dir


@pytest.mark.parametrize("impl", ["device", "host"])
def test_graph_workflow_matches_bruteforce(graph_setup, tmp_path, impl):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.graph import load_graph, load_sub_graph
    from cluster_tools_tpu.workflows.graph import GraphWorkflow

    labels, path, tmp_folder, config_dir = graph_setup
    if impl == "host":
        ConfigDir(config_dir).write_task_config("initial_sub_graphs",
                                                {"impl": "host"})
    graph_path = str(tmp_path / "graph.n5")
    wf = GraphWorkflow(input_path=path, input_key="labels",
                       graph_path=graph_path, tmp_folder=tmp_folder,
                       config_dir=config_dir, max_jobs=2, target="threads",
                       n_scales=2)
    assert ctt.build([wf])
    nodes, edges, attrs = load_graph(graph_path, "graph")
    expect = _brute_force_rag(labels)
    np.testing.assert_array_equal(edges, expect)
    np.testing.assert_array_equal(nodes, np.unique(labels))
    # per-block sub-graph edges must carry valid global edge ids
    sub = load_sub_graph(graph_path, 0, 0)
    assert "edge_ids" in sub
    np.testing.assert_array_equal(edges[sub["edge_ids"]], sub["edges"])


@pytest.mark.parametrize("impl", ["device", "host"])
def test_edge_features_match_bruteforce(graph_setup, tmp_path, impl):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.graph import load_graph
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.workflows.features import EdgeFeaturesWorkflow
    from cluster_tools_tpu.workflows.graph import GraphWorkflow

    labels, path, tmp_folder, config_dir = graph_setup
    if impl == "host":
        ConfigDir(config_dir).write_task_config("block_edge_features",
                                                {"impl": "host"})
    rng = np.random.RandomState(1)
    bmap = rng.rand(*labels.shape).astype("float32")
    _write_volume(path, "boundaries", bmap, (10, 10, 10))
    graph_path = str(tmp_path / "graph.n5")
    feat_path = str(tmp_path / "features.n5")

    wf = GraphWorkflow(input_path=path, input_key="labels",
                       graph_path=graph_path, tmp_folder=tmp_folder,
                       config_dir=config_dir, max_jobs=2, target="threads")
    fw = EdgeFeaturesWorkflow(
        input_path=path, input_key="boundaries", labels_path=path,
        labels_key="labels", graph_path=graph_path, output_path=feat_path,
        tmp_folder=tmp_folder, config_dir=config_dir, max_jobs=2,
        target="threads", dependency=wf)
    assert ctt.build([fw])

    _, edges, _ = load_graph(graph_path, "graph")
    with file_reader(feat_path, "r") as f:
        feats = f["features"][:]

    # oracle: pool both face voxels per axis-neighbor pair
    samples = {}
    for axis in range(3):
        a = np.moveaxis(labels, axis, 0)[:-1]
        b = np.moveaxis(labels, axis, 0)[1:]
        va = np.moveaxis(bmap, axis, 0)[:-1]
        vb = np.moveaxis(bmap, axis, 0)[1:]
        m = a != b
        for u, v, x, y in zip(a[m], b[m], va[m], vb[m]):
            key = (min(u, v), max(u, v))
            samples.setdefault(key, []).extend([x, y])
    for i, (u, v) in enumerate(edges):
        vals = np.array(samples[(u, v)], dtype="float64")
        assert feats[i, 9] == len(vals)
        np.testing.assert_allclose(feats[i, 0], vals.mean(), rtol=1e-6)
        np.testing.assert_allclose(feats[i, 2], vals.min(), rtol=1e-6)
        np.testing.assert_allclose(feats[i, 8], vals.max(), rtol=1e-6)
        np.testing.assert_allclose(feats[i, 1], vals.var(), rtol=1e-5,
                                   atol=1e-12)


def test_probs_to_costs_formula():
    from cluster_tools_tpu.workflows.costs import (
        transform_probabilities_to_costs)

    p = np.array([0.0, 0.1, 0.5, 0.9, 1.0], "float32")
    c = transform_probabilities_to_costs(p, beta=0.5)
    pc = np.clip((1 - 0.002) * p + 0.001, 0.001, 0.999)
    expect = np.log((1 - pc) / pc)
    np.testing.assert_allclose(c, expect, rtol=1e-4)
    assert c[0] > 0 and c[-1] < 0  # low prob -> attractive, high -> repulsive

    sizes = np.array([1, 2, 4, 8, 8], "float32")
    cw = transform_probabilities_to_costs(p, beta=0.5, edge_sizes=sizes)
    np.testing.assert_allclose(cw, expect * sizes / 8.0, rtol=1e-4)


def test_apply_node_labels_modes():
    from cluster_tools_tpu.workflows.costs import apply_node_labels

    uv = np.array([[0, 1], [1, 2], [2, 3]], "uint64")
    labels = np.array([0, 1, 1, 0], "uint64")
    c = np.zeros(3, "float32")
    out = apply_node_labels(c.copy(), uv, "ignore", labels, -10, 10)
    np.testing.assert_array_equal(out, [-10, -10, -10])
    out = apply_node_labels(c.copy(), uv, "isolate", labels, -10, 10)
    np.testing.assert_array_equal(out, [-10, 10, -10])
    labels2 = np.array([1, 1, 2, 2], "uint64")
    out = apply_node_labels(c.copy(), uv, "ignore_transition", labels2, -10, 10)
    np.testing.assert_array_equal(out, [0, -10, 0])


def test_affinity_features_keep_seam_edges(graph_setup, tmp_path):
    """Affinity anchors owned by the neighbor block must still contribute to
    seam edges (regression: samples were dropped when the anchor's block did
    not own the edge)."""
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.graph import load_graph
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.workflows.features import EdgeFeaturesWorkflow
    from cluster_tools_tpu.workflows.graph import GraphWorkflow

    labels, path, tmp_folder, config_dir = graph_setup
    offsets = [[-1, 0, 0], [0, -1, 0], [0, 0, -1]]
    rng = np.random.RandomState(2)
    affs = rng.rand(3, *labels.shape).astype("float32")
    _write_volume(path, "affs", affs, (3, 10, 10, 10))
    graph_path = str(tmp_path / "graph.n5")
    feat_path = str(tmp_path / "features.n5")

    wf = GraphWorkflow(input_path=path, input_key="labels",
                       graph_path=graph_path, tmp_folder=tmp_folder,
                       config_dir=config_dir, max_jobs=2, target="threads")
    fw = EdgeFeaturesWorkflow(
        input_path=path, input_key="affs", labels_path=path,
        labels_key="labels", graph_path=graph_path, output_path=feat_path,
        offsets=offsets, tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="threads", dependency=wf)
    assert ctt.build([fw])

    _, edges, _ = load_graph(graph_path, "graph")
    with file_reader(feat_path, "r") as f:
        feats = f["features"][:]

    # oracle: every anchor voxel samples its offset channel
    samples = {}
    for c, off in enumerate(offsets):
        ax = [i for i, o in enumerate(off) if o][0]
        a = np.moveaxis(labels, ax, 0)[1:]          # anchors i >= 1
        b = np.moveaxis(labels, ax, 0)[:-1]         # neighbors i-1
        va = np.moveaxis(affs[c], ax, 0)[1:]
        m = a != b
        for u, v, x in zip(a[m], b[m], va[m]):
            samples.setdefault((min(u, v), max(u, v)), []).append(x)
    edge_set = {tuple(e) for e in edges}
    for (u, v), vals in samples.items():
        if (u, v) not in edge_set:
            continue
        i = next(j for j, e in enumerate(edges) if tuple(e) == (u, v))
        vals = np.asarray(vals, "float64")
        assert feats[i, 9] == len(vals), (u, v)
        np.testing.assert_allclose(feats[i, 0], vals.mean(), rtol=1e-6)
    # every RAG edge gets direct-neighbor samples -> no zero-count rows
    assert (feats[:, 9] > 0).all()


def test_graph_workflow_huge_labels(tmp_path, tmp_workdir):
    """Labels above 2**31 must survive device RAG extraction exactly
    (ADVICE r1: jax truncates int64 to int32 without x64 — the kernels run
    on densified per-block ids instead)."""
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.graph import load_graph
    from cluster_tools_tpu.workflows.graph import GraphWorkflow

    tmp_folder, config_dir = tmp_workdir
    labels = _toy_labels(shape=(12, 12, 12), n_seeds=6)
    # per-block voxel offsets at cluster scale push labels past int32
    labels = labels + np.uint64(2 ** 33)
    labels[0, 0, 0] = 0  # keep an ignore-label voxel in play
    path = str(tmp_path / "data.n5")
    _write_volume(path, "labels", labels, (10, 10, 10))
    graph_path = str(tmp_path / "graph.n5")
    wf = GraphWorkflow(input_path=path, input_key="labels",
                       graph_path=graph_path, tmp_folder=tmp_folder,
                       config_dir=config_dir, max_jobs=2, target="threads",
                       n_scales=1)
    assert ctt.build([wf])
    nodes, edges, _ = load_graph(graph_path, "graph")
    expect = _brute_force_rag(labels)
    np.testing.assert_array_equal(edges, expect)
    assert edges.min() > 2 ** 33 - 1
    np.testing.assert_array_equal(nodes, np.unique(labels)[1:])


def test_densify_labels_roundtrip():
    from cluster_tools_tpu.ops.rag import densify_labels

    labels = np.array([[5, 0], [2 ** 40, 5]], dtype="uint64")
    lut, dense = densify_labels(labels)
    assert lut[0] == 0
    assert dense.dtype == np.int32
    np.testing.assert_array_equal(lut[dense], labels)
    # no zero present: lut must still reserve index 0 for the ignore label
    lut, dense = densify_labels(np.array([7, 9], dtype="uint64"))
    assert lut[0] == 0 and (dense > 0).all()
    np.testing.assert_array_equal(lut[dense], [7, 9])


def test_filter_bank_edge_features(graph_setup, tmp_path):
    """Filter-bank features (reference: block_edge_features.py:165-230):
    each (filter, sigma) response contributes a 9-column stat group + one
    shared count column.  Oracle: group k of the filtered run must equal the
    plain-feature run on the precomputed filter response (halo covers the
    kernel support, so blockwise filtering is exact)."""
    import jax.numpy as jnp

    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.ops.filters import apply_filter
    from cluster_tools_tpu.workflows.features import EdgeFeaturesWorkflow
    from cluster_tools_tpu.workflows.graph import GraphWorkflow

    labels, path, tmp_folder, config_dir = graph_setup
    rng = np.random.RandomState(2)
    bmap = rng.rand(*labels.shape).astype("float32")
    _write_volume(path, "boundaries", bmap, (10, 10, 10))
    # precomputed responses as their own input datasets (plain-path oracle)
    responses = [("gaussianSmoothing", 1.0), ("laplacianOfGaussian", 1.0)]
    for fn, s in responses:
        resp = np.asarray(apply_filter(jnp.asarray(bmap), fn, s))
        _write_volume(path, f"resp_{fn}", resp.astype("float32"),
                      (10, 10, 10))
    graph_path = str(tmp_path / "graph.n5")

    wf = GraphWorkflow(input_path=path, input_key="labels",
                       graph_path=graph_path, tmp_folder=tmp_folder,
                       config_dir=config_dir, max_jobs=2, target="threads")
    ConfigDir(config_dir).write_task_config(
        "block_edge_features",
        {"filters": [fn for fn, _ in responses], "sigmas": [1.0]})
    fw = EdgeFeaturesWorkflow(
        input_path=path, input_key="boundaries", labels_path=path,
        labels_key="labels", graph_path=graph_path,
        output_path=str(tmp_path / "filtered.n5"),
        tmp_folder=tmp_folder, config_dir=config_dir, max_jobs=2,
        target="threads", dependency=wf)
    assert ctt.build([fw])
    with file_reader(str(tmp_path / "filtered.n5"), "r") as f:
        filtered = f["features"][:]
    assert filtered.shape[1] == 9 * len(responses) + 1

    # plain features on each precomputed response, in clean workdirs
    ConfigDir(config_dir).write_task_config("block_edge_features", {})
    for k, (fn, _) in enumerate(responses):
        sub_tmp = os.path.join(tmp_folder, f"plain_{fn}")
        fw_k = EdgeFeaturesWorkflow(
            input_path=path, input_key=f"resp_{fn}", labels_path=path,
            labels_key="labels", graph_path=graph_path,
            output_path=str(tmp_path / f"plain_{fn}.n5"),
            tmp_folder=sub_tmp, config_dir=config_dir, max_jobs=2,
            target="threads")
        assert ctt.build([fw_k])
        with file_reader(str(tmp_path / f"plain_{fn}.n5"), "r") as f:
            plain = f["features"][:]
        np.testing.assert_allclose(filtered[:, 9 * k:9 * k + 9],
                                   plain[:, :9], rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(filtered[:, -1], plain[:, 9])
