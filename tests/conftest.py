"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding/collective paths are
validated on a virtual CPU mesh (xla_force_host_platform_device_count), the
standard JAX technique for testing pjit/shard_map layouts without TPUs.
Must run before the first jax import anywhere in the test session.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: tests never touch accelerators
_flags = os.environ.get("XLA_FLAGS", "")
# CTT_NO_VIRTUAL_MESH=1 opts out of the virtual mesh (e.g. to mimic a true
# single-device host); tests marked ``mesh`` then self-skip
if "xla_force_host_platform_device_count" not in _flags \
        and os.environ.get("CTT_NO_VIRTUAL_MESH") != "1":
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# keep accelerator-plugin site dirs (axon) out of this process and out of
# worker subprocesses: their device tunnel blocks backend discovery when
# unreachable, and tests must be hermetic either way
sys.path = [p for p in sys.path if ".axon_site" not in p]
os.environ["PYTHONPATH"] = os.pathsep.join(
    p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
    if p and ".axon_site" not in p)

# hermetic executable-cache state: an inherited warm disk tier would turn
# the suite's compile-count assertions (EXEC_CACHE_STATS / exec_cache
# status telemetry) into disk hits; tests opt in per-fixture instead
os.environ.pop("CTT_EXEC_CACHE_DIR", None)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

try:  # if the plugin registered before us (via sitecustomize), unregister it
    from jax._src import xla_bridge as _xb

    _xb._backend_factories.pop("axon", None)
    import jax

    # jax may have been imported (and its platform config latched) by the
    # plugin's sitecustomize before this file ran
    jax.config.update("jax_platforms", "cpu")
except Exception:
    pass

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """Tests marked ``mesh`` need the emulated multi-device mesh: they
    self-skip when ``--xla_force_host_platform_device_count`` is not in
    XLA_FLAGS (a true single-device host, or CTT_NO_VIRTUAL_MESH=1)."""
    if "xla_force_host_platform_device_count" in os.environ.get(
            "XLA_FLAGS", ""):
        return
    skip = pytest.mark.skip(
        reason="needs --xla_force_host_platform_device_count (emulated "
               "multi-device mesh)")
    for item in items:
        if "mesh" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _telemetry_reset():
    """Telemetry state is module-global (like the stage accumulators,
    which tests consume via deltas): restore disabled / default ring /
    real clock after every test so a test that arms the recorder cannot
    leak spans into the next one."""
    yield
    from cluster_tools_tpu.core import telemetry

    telemetry.reset()


@pytest.fixture()
def tmp_workdir(tmp_path):
    """tmp_folder + config_dir pair with a small-block global config."""
    from cluster_tools_tpu.core.config import ConfigDir

    tmp_folder = str(tmp_path / "tmp")
    config_dir = str(tmp_path / "configs")
    cfg = ConfigDir(config_dir)
    cfg.write_global_config({"block_shape": [10, 10, 10], "max_num_retries": 0})
    return tmp_folder, config_dir
