"""BASELINE.json configs 2, 3 and 5: device vs reference-faithful CPU.

Complements bench.py (config 4, the flagship multicut chain; config 1 —
the single-block DT watershed — was measured in round 1).  Each config
runs the SAME workflow classes under ``target='tpu'`` and under
``target='local'`` (subprocess workers pinned to the CPU jax backend,
the reference's LocalTask model), reports voxels/sec for both, the ratio,
and a quality check against the generating ground truth:

* config 2 — ThresholdedComponentsWorkflow: distributed connected
  components with block stitching (offsets -> faces -> union-find).
  Oracle: partition-identical to scipy.ndimage.label.
* config 3 — MwsWorkflow: blockwise mutex watershed on 3D long-range
  affinities.  Quality: adapted Rand error vs the generating labels.
* config 5 — InferenceTask (3D U-Net affinity prediction, uint8
  requant) + MwsWorkflow on the predicted affinities.  The checkpoint is
  an untrained net (no trained weights ship with the repo), so the
  metric is pipeline throughput; segmentation quality is only asserted
  to be defined (the MWS consumes the real prediction output).

Writes one JSON per config: BENCH_config{2,3,5}.json at the repo root.
"""

import json
import os
import shutil
import subprocess
import sys
import time

import numpy as np

ROOT = os.path.dirname(os.path.abspath(__file__))
OFFSETS = [[-1, 0, 0], [0, -1, 0], [0, 0, -1],
           [-4, 0, 0], [0, -4, 0], [0, 0, -4]]


# env-overridable geometry for smoke runs on small hosts; every config
# records the shape it actually measured in its JSON
from bench import _env_shape  # noqa: E402  (same directory)
from cluster_tools_tpu.core.config import write_config  # noqa: E402


def _blob_volume(shape, seed=0):
    """Smoothed random field normalized to [0,1]: thresholding yields
    many multi-block blobs (O(volume) generation — per-blob meshgrids
    take minutes at benchmark scale)."""
    from scipy import ndimage

    rng = np.random.RandomState(seed)
    vol = ndimage.gaussian_filter(rng.rand(*shape).astype("float32"), 4.0)
    lo, hi = float(vol.min()), float(vol.max())
    return (vol - lo) / max(hi - lo, 1e-6)


def _voronoi_gt(shape, n_cells, seed=0):
    from scipy.spatial import cKDTree

    rng = np.random.RandomState(seed)
    pts = (rng.rand(n_cells, 3) * np.array(shape)).astype("float32")
    tree = cKDTree(pts)
    grids = np.meshgrid(*[np.arange(s, dtype="float32") for s in shape],
                        indexing="ij")
    _, idx = tree.query(np.stack([g.ravel() for g in grids], 1), k=1)
    return (idx + 1).reshape(shape).astype("uint64")


def _affs_from_gt(gt, offsets, hi=0.9, lo=0.05, noise=0.05, seed=0):
    rng = np.random.RandomState(seed)
    affs = np.full((len(offsets),) + gt.shape, lo, dtype="float32")
    for c, off in enumerate(offsets):
        sl_a, sl_b = [], []
        for o, s in zip(off, gt.shape):
            sl_a.append(slice(0, s - abs(o)) if o >= 0 else slice(-o, s))
            sl_b.append(slice(o, s) if o >= 0 else slice(0, s + o))
        same = gt[tuple(sl_a)] == gt[tuple(sl_b)]
        affs[c][tuple(sl_a)] = np.where(same, hi, lo)
    affs += (rng.rand(*affs.shape).astype("float32") - 0.5) * 2 * noise
    return np.clip(affs, 0.0, 1.0)


def _run_local_subprocess(fn_name, args, workdir):
    """Run one chain in a subprocess pinned to the CPU jax backend."""
    import pickle

    os.makedirs(workdir, exist_ok=True)
    out_path = os.path.join(workdir, "result.pkl")
    script = os.path.join(workdir, "chain.py")
    with open(script, "w") as f:
        f.write(f"""
import pickle, sys
sys.path.insert(0, {ROOT!r})
import bench_configs
res = bench_configs.{fn_name}(*{args!r}, target="local")
with open({out_path!r}, "wb") as fo:
    pickle.dump(res, fo)
""")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in ([ROOT] + env.get("PYTHONPATH", "").split(os.pathsep))
        if p and ".axon_site" not in p)
    rc = subprocess.call([sys.executable, script], env=env)
    assert rc == 0, f"{fn_name} local chain failed"
    with open(out_path, "rb") as f:
        return pickle.load(f)


def _workdir(name, target):
    base = os.path.join("/tmp/ctt_bench_cfg", f"{name}_{target}")
    shutil.rmtree(base, ignore_errors=True)
    os.makedirs(base)
    return base


# ---------------------------------------------------------------------------
# config 1: single-block distance-transform watershed (BASELINE.json
# config 1: "DT watershed on CREMI sample A boundary map, single block")
# ---------------------------------------------------------------------------

WS_SHAPE = _env_shape("BENCH_CFG_WS_SHAPE", (50, 512, 512))


def run_ws_chain(store, target="tpu"):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.workflows.watershed import WatershedWorkflow

    workdir = _workdir("ws", target)
    cfg = ConfigDir(os.path.join(workdir, "configs"))
    # ONE block covering the volume: the single-block regime of config 1
    cfg.write_global_config({"block_shape": list(WS_SHAPE)})
    if target == "local":
        cfg.write_task_config("watershed", {"threshold": 0.4,
                                            "size_filter": 50,
                                            "impl": "host"})
    else:
        cfg.write_task_config("watershed", {"threshold": 0.4,
                                            "size_filter": 50})
    t0 = time.perf_counter()
    wf = WatershedWorkflow(
        input_path=store, input_key="bmap", output_path=store,
        output_key=f"ws_{target}", tmp_folder=workdir,
        config_dir=os.path.join(workdir, "configs"), max_jobs=1,
        target=target)
    assert ctt.build([wf], raise_on_failure=True)
    elapsed = time.perf_counter() - t0
    with file_reader(store, "r") as f:
        seg = f[f"ws_{target}"][:]
    return elapsed, seg


def config1():
    from scipy.spatial import cKDTree

    from cluster_tools_tpu.core.storage import file_reader

    rng = np.random.RandomState(0)
    n_cells = max(int(np.prod(WS_SHAPE) / 70000), 8)
    pts = (rng.rand(n_cells, 3) * np.array(WS_SHAPE)).astype("float32")
    tree = cKDTree(pts)
    grids = np.meshgrid(*[np.arange(s, dtype="float32")
                          for s in WS_SHAPE], indexing="ij")
    d, _ = tree.query(np.stack([g.ravel() for g in grids], 1), k=2)
    bnd = np.exp(-0.5 * ((d[:, 1] - d[:, 0]) / 2.0) ** 2
                 ).reshape(WS_SHAPE).astype("float32")
    store = "/tmp/ctt_bench_cfg/ws.n5"
    shutil.rmtree(store, ignore_errors=True)
    with file_reader(store) as f:
        f.require_dataset("bmap", shape=WS_SHAPE, chunks=list(WS_SHAPE),
                          dtype="uint8")[:] = np.round(
                              bnd * 255).astype("uint8")

    run_ws_chain(store, "tpu")  # warm compiles
    dev_t, dev_seg = run_ws_chain(store, "tpu")
    cpu_t, cpu_seg = _run_local_subprocess(
        "run_ws_chain", (store,), "/tmp/ctt_bench_cfg/ws_local")

    # watershed fragments OVER-segment by design: quality here is that
    # both paths produce a dense fragment cover of comparable granularity
    # (VOI parity of the final segmentation is config 4's gate)
    n_dev = len(np.unique(dev_seg[dev_seg > 0]))
    n_cpu = len(np.unique(cpu_seg[cpu_seg > 0]))
    assert n_dev > n_cells / 2 and n_cpu > n_cells / 2, (n_dev, n_cpu)
    n = int(np.prod(WS_SHAPE))
    return {
        "config": 1,
        "workflow": "WatershedWorkflow (single-block DT watershed)",
        "volume_mvox": round(n / 1e6, 1), "shape": list(WS_SHAPE),
        "block_shape": list(WS_SHAPE),
        "device_vox_per_sec": round(n / dev_t, 1),
        "cpu_vox_per_sec": round(n / cpu_t, 1),
        "vs_baseline": round(cpu_t / dev_t, 3),
        "n_fragments": {"device": n_dev, "cpu": n_cpu},
        "quality": "dense fragment cover, comparable granularity "
                   "(VOI parity gated in config 4)",
    }


# ---------------------------------------------------------------------------
# config 2: connected components + stitching
# ---------------------------------------------------------------------------

CC_SHAPE = _env_shape("BENCH_CFG_CC_SHAPE", (64, 512, 512))
CC_BLOCK = [32, 256, 256]
#: ~500 components spanning blocks at this threshold of the smoothed field
CC_THRESHOLD = 0.6


def run_cc_chain(store, target="tpu"):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.workflows.thresholded_components import (
        ThresholdedComponentsWorkflow)

    workdir = _workdir("cc", target)
    cfg = ConfigDir(os.path.join(workdir, "configs"))
    cfg.write_global_config({"block_shape": CC_BLOCK})
    t0 = time.perf_counter()
    wf = ThresholdedComponentsWorkflow(
        input_path=store, input_key="vol", output_path=store,
        output_key=f"cc_{target}", threshold=CC_THRESHOLD, tmp_folder=workdir,
        config_dir=os.path.join(workdir, "configs"),
        max_jobs=os.cpu_count() or 1, target=target)
    assert ctt.build([wf], raise_on_failure=True)
    elapsed = time.perf_counter() - t0
    with file_reader(store, "r") as f:
        seg = f[f"cc_{target}"][:]
    return elapsed, seg


def config2():
    from scipy import ndimage

    from cluster_tools_tpu.core.storage import file_reader

    vol = _blob_volume(CC_SHAPE)
    store = "/tmp/ctt_bench_cfg/cc.n5"
    shutil.rmtree(store, ignore_errors=True)
    with file_reader(store) as f:
        f.require_dataset("vol", shape=vol.shape, chunks=CC_BLOCK,
                          dtype="float32")[:] = vol

    run_cc_chain(store, "tpu")  # warm compiles
    dev_t, dev_seg = run_cc_chain(store, "tpu")
    cpu_t, cpu_seg = _run_local_subprocess(
        "run_cc_chain", (store,), "/tmp/ctt_bench_cfg/cc_local")

    expected, _ = ndimage.label(vol > CC_THRESHOLD)
    for name, seg in (("device", dev_seg), ("cpu", cpu_seg)):
        pairs = np.unique(np.stack([seg.ravel(),
                                    expected.ravel().astype("uint64")]),
                          axis=1)
        assert len(np.unique(pairs[0])) == pairs.shape[1] \
            and len(np.unique(pairs[1])) == pairs.shape[1], \
            f"{name} partition differs from scipy.ndimage.label"
    n = int(np.prod(CC_SHAPE))
    return {
        "config": 2,
        "workflow": "ThresholdedComponentsWorkflow (CC + stitching)",
        "volume_mvox": round(n / 1e6, 1), "shape": list(CC_SHAPE),
        "block_shape": CC_BLOCK,
        "device_vox_per_sec": round(n / dev_t, 1),
        "cpu_vox_per_sec": round(n / cpu_t, 1),
        "vs_baseline": round(cpu_t / dev_t, 3),
        "quality": "partition-identical to scipy.ndimage.label (both)",
    }


# ---------------------------------------------------------------------------
# config 3: mutex watershed on long-range affinities
# ---------------------------------------------------------------------------

MWS_SHAPE = _env_shape("BENCH_CFG_MWS_SHAPE", (64, 512, 512))
MWS_BLOCK = [32, 256, 256]


def run_mws_chain(store, target="tpu"):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.workflows.mutex_watershed import (
        TwoPassMwsWorkflow)

    workdir = _workdir("mws", target)
    cfg = ConfigDir(os.path.join(workdir, "configs"))
    cfg.write_global_config({"block_shape": MWS_BLOCK})
    t0 = time.perf_counter()
    # two-pass checkerboard: pass-2 blocks consume the serialized seeds of
    # pass-1 neighbors, then assignments stitch the grid — the
    # cross-block-consistent MWS (single-pass leaves per-block pieces)
    wf = TwoPassMwsWorkflow(
        input_path=store, input_key="affs", output_path=store,
        output_key=f"mws_{target}", offsets=OFFSETS, halo=[4, 16, 16],
        tmp_folder=workdir,
        config_dir=os.path.join(workdir, "configs"),
        max_jobs=os.cpu_count() or 1, target=target)
    assert ctt.build([wf], raise_on_failure=True)
    elapsed = time.perf_counter() - t0
    with file_reader(store, "r") as f:
        seg = f[f"mws_{target}"][:]
    return elapsed, seg


def config3():
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.utils.validation import (ContingencyTable,
                                                    cremi_score_from_table)

    gt = _voronoi_gt(MWS_SHAPE, n_cells=240)
    affs = _affs_from_gt(gt, OFFSETS)
    store = "/tmp/ctt_bench_cfg/mws.n5"
    shutil.rmtree(store, ignore_errors=True)
    with file_reader(store) as f:
        f.require_dataset("affs", shape=affs.shape,
                          chunks=[1] + MWS_BLOCK, dtype="float32")[:] = affs

    run_mws_chain(store, "tpu")  # warm
    dev_t, dev_seg = run_mws_chain(store, "tpu")
    cpu_t, cpu_seg = _run_local_subprocess(
        "run_mws_chain", (store,), "/tmp/ctt_bench_cfg/mws_local")

    metrics = {}
    for name, seg in (("device", dev_seg), ("cpu", cpu_seg)):
        table = ContingencyTable.from_arrays_chunked(gt, seg)
        vs, vm, are, _ = cremi_score_from_table(table)
        metrics[name] = {"voi_split": round(vs, 4),
                         "voi_merge": round(vm, 4),
                         "rand_error": round(are, 4)}
        assert are < 0.1, f"{name} MWS lost parity: {are}"
    n = int(np.prod(MWS_SHAPE))
    return {
        "config": 3,
        "workflow": "TwoPassMwsWorkflow (checkerboard mutex watershed, "
                    f"{len(OFFSETS)} offsets)",
        "volume_mvox": round(n / 1e6, 1), "shape": list(MWS_SHAPE),
        "block_shape": MWS_BLOCK,
        "device_vox_per_sec": round(n / dev_t, 1),
        "cpu_vox_per_sec": round(n / cpu_t, 1),
        "vs_baseline": round(cpu_t / dev_t, 3),
        "device": metrics["device"], "cpu": metrics["cpu"],
    }


# ---------------------------------------------------------------------------
# config 5: U-Net affinity inference + mutex watershed
# ---------------------------------------------------------------------------

INF_SHAPE = _env_shape("BENCH_CFG_INF_SHAPE", (32, 256, 256))
INF_BLOCK = [16, 128, 128]


def _make_checkpoint(path):
    import jax

    from cluster_tools_tpu.models.checkpoint import save_checkpoint
    from cluster_tools_tpu.models.unet import create_unet

    model = create_unet(out_channels=len(OFFSETS), features=(8, 16))
    params = jax.tree_util.tree_map(
        np.asarray, model.init(jax.random.PRNGKey(0),
                               np.zeros((1, 8, 16, 16, 1), "f4")))
    save_checkpoint(path, {"out_channels": len(OFFSETS),
                           "features": [8, 16]}, params)


def run_inference_chain(store, ckpt, target="tpu"):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.workflows.inference import InferenceTask
    from cluster_tools_tpu.workflows.mutex_watershed import MwsWorkflow

    workdir = _workdir("inf", target)
    cfg = ConfigDir(os.path.join(workdir, "configs"))
    cfg.write_global_config({"block_shape": INF_BLOCK})
    t0 = time.perf_counter()
    inf = InferenceTask(
        input_path=store, input_key="raw", output_path=store,
        output_key={f"affs_{target}": [0, len(OFFSETS)]},
        checkpoint_path=ckpt, halo=[4, 16, 16], tmp_folder=workdir,
        config_dir=os.path.join(workdir, "configs"),
        max_jobs=os.cpu_count() or 1, target=target)
    mws = MwsWorkflow(
        input_path=store, input_key=f"affs_{target}", output_path=store,
        output_key=f"seg_{target}", offsets=OFFSETS, tmp_folder=workdir,
        config_dir=os.path.join(workdir, "configs"),
        max_jobs=os.cpu_count() or 1, target=target, dependency=inf)
    assert ctt.build([mws], raise_on_failure=True)
    elapsed = time.perf_counter() - t0
    with file_reader(store, "r") as f:
        seg = f[f"seg_{target}"][:]
    return elapsed, seg


def config5():
    from cluster_tools_tpu.core.storage import file_reader

    rng = np.random.RandomState(0)
    raw = rng.rand(*INF_SHAPE).astype("float32")
    store = "/tmp/ctt_bench_cfg/inf.n5"
    shutil.rmtree(store, ignore_errors=True)
    with file_reader(store) as f:
        f.require_dataset("raw", shape=raw.shape, chunks=INF_BLOCK,
                          dtype="float32")[:] = raw
    ckpt = "/tmp/ctt_bench_cfg/ckpt"
    shutil.rmtree(ckpt, ignore_errors=True)
    _make_checkpoint(ckpt)

    run_inference_chain(store, ckpt, "tpu")  # warm
    dev_t, dev_seg = run_inference_chain(store, ckpt, "tpu")
    cpu_t, cpu_seg = _run_local_subprocess(
        "run_inference_chain", (store, ckpt), "/tmp/ctt_bench_cfg/inf_local")
    assert dev_seg.shape == INF_SHAPE and cpu_seg.shape == INF_SHAPE
    n = int(np.prod(INF_SHAPE))
    return {
        "config": 5,
        "workflow": "InferenceTask (3D U-Net affinities, uint8 requant) "
                    "+ MwsWorkflow",
        "volume_mvox": round(n / 1e6, 1), "shape": list(INF_SHAPE),
        "block_shape": INF_BLOCK,
        "device_vox_per_sec": round(n / dev_t, 1),
        "cpu_vox_per_sec": round(n / cpu_t, 1),
        "vs_baseline": round(cpu_t / dev_t, 3),
        "quality": "untrained weights: throughput benchmark; MWS consumes "
                   "the real prediction output end-to-end",
    }


def main():
    sys.path.insert(0, ROOT)
    os.makedirs("/tmp/ctt_bench_cfg", exist_ok=True)
    only = set(sys.argv[1:])
    todo = (("1", config1), ("2", config2), ("3", config3), ("5", config5))
    for name, fn in todo:
        if only and name not in only:
            continue
        t0 = time.perf_counter()
        res = fn()
        res["bench_seconds"] = round(time.perf_counter() - t0, 1)
        out = os.path.join(ROOT, f"BENCH_config{name}.json")
        write_config(out, res)
        print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()
