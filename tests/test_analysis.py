"""ctt-lint: fixture corpus + repo gate + lock-order witness (ISSUE 18).

Three layers:

* known-BAD fixtures — one tiny file per rule, asserting the exact rule
  id AND line number, so a pass that stops firing (or fires on the
  wrong line) fails loudly;
* known-GOOD corpus — the idioms each pass was explicitly tuned NOT to
  flag (``os.path.join`` under a lock, ``jax.random`` inside jit, the
  tmp+``os.replace`` write, dense-label int32 casts...) must produce
  ZERO findings;
* the repo gate — the full analyzer over the real tree must report zero
  unsuppressed findings (this is the tier-1 lint gate), plus the
  dynamic lock-order witness catching a seeded A->B / B->A inversion.
"""

import os
import textwrap
import threading
import time

import pytest

from cluster_tools_tpu import analysis
from cluster_tools_tpu.analysis import ALL_RULES, run_analysis, sources
from cluster_tools_tpu.core import runtime


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _fixture(tmp_path, relname, src):
    """Write a fixture source file; subdir components ('core/x.py')
    trigger the directory-scoped passes just like in the real tree."""
    path = tmp_path / relname
    path.parent.mkdir(parents=True, exist_ok=True)
    src = textwrap.dedent(src).lstrip("\n")
    path.write_text(src)
    return str(path), src


def _line_of(src, needle, nth=1):
    """1-based line number of the nth line containing ``needle``."""
    hits = [i for i, ln in enumerate(src.splitlines(), start=1)
            if needle in ln]
    assert len(hits) >= nth, "fixture rotted: %r not found" % needle
    return hits[nth - 1]


def _findings(path, rule):
    report = run_analysis(files=[path], rules=[rule])
    return report, [(f.rule, f.line) for f in report["findings"]]


# ---------------------------------------------------------------------------
# known-bad fixtures: exact rule id + line number per pass
# ---------------------------------------------------------------------------

def test_trace_purity_fixture(tmp_path):
    path, src = _fixture(tmp_path, "bad_trace.py", """
        import time

        import jax

        @jax.jit
        def step(x):
            time.sleep(0.01)
            print("step", x)
            return x + 1
    """)
    _report, got = _findings(path, "trace-purity")
    assert ("trace-purity", _line_of(src, "time.sleep")) in got
    assert ("trace-purity", _line_of(src, "print(")) in got
    assert len(got) == 2


def test_trace_purity_transitive_closure(tmp_path):
    """A same-module helper CALLED from a jit'd function is traced too."""
    path, src = _fixture(tmp_path, "bad_trace_helper.py", """
        import time

        import jax

        def _inner(x):
            time.sleep(0.01)
            return x

        @jax.jit
        def outer(x):
            return _inner(x)
    """)
    _report, got = _findings(path, "trace-purity")
    assert got == [("trace-purity", _line_of(src, "time.sleep"))]


def test_blocking_under_lock_fixture(tmp_path):
    path, src = _fixture(tmp_path, "core/bad_locks.py", """
        import json
        import threading

        _lock = threading.Lock()

        def save(path, obj):
            with _lock:
                with open(path, "w") as f:
                    json.dump(obj, f)
    """)
    _report, got = _findings(path, "blocking-under-lock")
    assert ("blocking-under-lock", _line_of(src, "open(path")) in got
    assert ("blocking-under-lock", _line_of(src, "json.dump")) in got


def test_blocking_under_lock_is_core_scoped(tmp_path):
    """The same source OUTSIDE core/ is not in scope for the lock pass."""
    path, _src = _fixture(tmp_path, "elsewhere/bad_locks.py", """
        import json
        import threading

        _lock = threading.Lock()

        def save(path, obj):
            with _lock:
                with open(path, "w") as f:
                    json.dump(obj, f)
    """)
    _report, got = _findings(path, "blocking-under-lock")
    assert got == []


def test_stage_registry_fixture(tmp_path):
    path, src = _fixture(tmp_path, "bad_stage.py", """
        from cluster_tools_tpu.core.telemetry import stage_add

        def work(n):
            stage_add("never-registered-stage", 0.5)
            stage_add(f"stage-{n}", 0.5)
    """)
    _report, got = _findings(path, "stage-registry")
    assert ("stage-registry",
            _line_of(src, "never-registered-stage")) in got
    assert ("stage-registry", _line_of(src, 'f"stage-')) in got


def test_metric_registry_fixture(tmp_path):
    path, src = _fixture(tmp_path, "bad_metric.py", """
        FAMILY = "ctt_bogus_family_total"

        def family_for(op):
            return f"ctt_{op}_seconds"
    """)
    _report, got = _findings(path, "metric-registry")
    assert ("metric-registry",
            _line_of(src, "ctt_bogus_family_total")) in got
    assert ("metric-registry", _line_of(src, 'f"ctt_')) in got


def test_dtype_f64_fixture(tmp_path):
    path, src = _fixture(tmp_path, "ops/bad_f64.py", """
        import jax
        import jax.numpy as jnp

        @jax.jit
        def affinities(x):
            y = x.astype(jnp.float64)
            return jnp.zeros_like(y, dtype="float64")
    """)
    _report, got = _findings(path, "dtype-f64")
    assert ("dtype-f64", _line_of(src, "astype")) in got
    assert ("dtype-f64", _line_of(src, 'dtype="float64"')) in got


def test_dtype_f64_only_in_traced_scope(tmp_path):
    """Host-side f64 staging (NOT jit'd) is deliberately out of scope."""
    path, _src = _fixture(tmp_path, "ops/good_f64_host.py", """
        import numpy as np

        def gaussian_kernel(sigma):
            return np.arange(9).astype(np.float64) * sigma
    """)
    _report, got = _findings(path, "dtype-f64")
    assert got == []


def test_dtype_int32_fixture(tmp_path):
    path, src = _fixture(tmp_path, "ops/bad_i32.py", """
        import jax.numpy as jnp

        def pack(seed_ids, labels):
            small_seeds = seed_ids.astype(jnp.int32)
            dense = labels.astype(jnp.int32)
            return small_seeds, dense
    """)
    _report, got = _findings(path, "dtype-int32")
    # seed receiver flagged; block-local dense labels deliberately NOT
    assert got == [("dtype-int32", _line_of(src, "seed_ids.astype"))]


def test_config_key_fixture(tmp_path):
    path, src = _fixture(tmp_path, "bad_config.py", """
        def resources(job):
            gc = job["global_config"]
            retries = gc.get("max_num_retires", 3)
            shape = job["global_config"]["block_shpae"]
            return retries, shape
    """)
    _report, got = _findings(path, "config-key")
    assert ("config-key", _line_of(src, "max_num_retires")) in got
    assert ("config-key", _line_of(src, "block_shpae")) in got
    assert len(got) == 2


def test_atomic_write_fixture(tmp_path):
    path, src = _fixture(tmp_path, "bad_write.py", """
        import json

        def save(path, obj):
            with open(path, "w") as f:
                json.dump(obj, f)
    """)
    _report, got = _findings(path, "atomic-write")
    assert got == [("atomic-write", _line_of(src, "json.dump"))]


def test_parse_error_is_a_finding(tmp_path):
    path = tmp_path / "broken.py"
    path.write_text("def oops(:\n    pass\n")
    report = run_analysis(files=[str(path)])
    got = [(f.rule, f.line) for f in report["findings"]]
    assert got == [("parse-error", 1)]


# ---------------------------------------------------------------------------
# pragma discipline
# ---------------------------------------------------------------------------

def test_pragma_with_reason_suppresses(tmp_path):
    path, src = _fixture(tmp_path, "suppressed.py", """
        import json

        def save(path, obj):
            with open(path, "w") as f:
                # ctt-lint: disable=atomic-write (test fixture: scratch file, loss is fine)
                json.dump(obj, f)
    """)
    report = run_analysis(files=[path], rules=["atomic-write"])
    assert report["findings"] == []
    assert [(f.rule, f.line) for f in report["suppressed"]] == [
        ("atomic-write", _line_of(src, "json.dump"))]
    assert "scratch file" in report["suppressed"][0].reason


def test_pragma_without_reason_does_not_suppress(tmp_path):
    path, src = _fixture(tmp_path, "reasonless.py", """
        import json

        def save(path, obj):
            with open(path, "w") as f:
                json.dump(obj, f)  # ctt-lint: disable=atomic-write
    """)
    report = run_analysis(files=[path])
    got = {(f.rule, f.line) for f in report["findings"]}
    line = _line_of(src, "json.dump")
    # the original finding survives AND the bare pragma is itself flagged
    assert ("atomic-write", line) in got
    assert ("pragma-reason", line) in got
    assert report["suppressed"] == []


# ---------------------------------------------------------------------------
# known-good corpus: zero false positives on the tuned-out idioms
# ---------------------------------------------------------------------------

def test_known_good_corpus_zero_findings(tmp_path):
    good_core, _ = _fixture(tmp_path, "core/good_locks.py", """
        import os
        import threading

        _lock = threading.Lock()
        _cond = threading.Condition(_lock)

        def summarize(parts, root):
            with _lock:
                label = ", ".join(parts)
                path = os.path.join(root, label)
                _cond.wait(timeout=0.1)
                return path
    """)
    good_jit, _ = _fixture(tmp_path, "ops/good_jit.py", """
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def noisy(x, key):
            n = np.prod(x.shape)
            return x + jax.random.normal(key, x.shape) / n
    """)
    good_write, _ = _fixture(tmp_path, "good_write.py", """
        import json
        import os

        def save(path, obj):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(obj, f)
            os.replace(tmp, path)
    """)
    good_names, _ = _fixture(tmp_path, "good_names.py", """
        from cluster_tools_tpu.core.telemetry import stage_add

        def work():
            stage_add("sync-execute", 0.5)
            return "ctt_slo_burn_rate"
    """)
    report = run_analysis(
        files=[good_core, good_jit, good_write, good_names])
    assert report["findings"] == [], \
        "\n".join(f.format() for f in report["findings"])
    assert report["suppressed"] == []


# ---------------------------------------------------------------------------
# repo gate (tier-1): the real tree must be lint-clean
# ---------------------------------------------------------------------------

def test_repo_is_lint_clean():
    """The whole-package analyzer run — THE lint gate.  Any unsuppressed
    finding fails tier-1; suppressions must carry a reason (enforced by
    the pragma-reason rule, re-asserted here on the live report)."""
    t0 = time.monotonic()
    report = run_analysis()
    elapsed = time.monotonic() - t0
    assert report["findings"] == [], \
        "\n".join(f.format() for f in report["findings"])
    assert all(f.reason for f in report["suppressed"])
    assert report["files_scanned"] > 50
    assert elapsed < 10.0, "analyzer too slow for tier-1: %.1fs" % elapsed


def test_cli_exit_codes_and_json(tmp_path, capsys):
    bad, _ = _fixture(tmp_path, "bad_write.py", """
        import json

        def save(path, obj):
            with open(path, "w") as f:
                json.dump(obj, f)
    """)
    out_json = str(tmp_path / "LINT.json")
    assert analysis.main([bad, "--json", out_json]) == 1
    captured = capsys.readouterr()
    assert "atomic-write" in captured.out
    import json as _json
    with open(out_json) as f:
        payload = _json.load(f)
    assert payload["cmd"] == "lint"
    assert payload["n_findings"] == 1
    assert payload["counts"] == {"atomic-write": 1}
    # the clean tree exits 0 (same check the tier-1 gate makes)
    assert analysis.main(["--quiet"]) == 0


def test_cli_rejects_unknown_rule():
    with pytest.raises(SystemExit):
        analysis.main(["--rules", "not-a-rule"])


def test_all_rules_have_a_pass():
    covered = {r for p in analysis.load_passes() for r in p.rules}
    covered |= {"pragma-reason", "parse-error"}   # runner-level rules
    assert covered == set(ALL_RULES)


# ---------------------------------------------------------------------------
# dynamic lock-order witness
# ---------------------------------------------------------------------------

@pytest.fixture
def witness():
    runtime.lock_witness_configure(enabled=True, ring=64)
    try:
        yield
    finally:
        runtime.lock_witness_configure(enabled=False)


def test_witness_detects_seeded_inversion(witness):
    """A->B in one thread, B->A in another: the classic deadlock seed.
    The witness flags it from the acquisition graph WITHOUT needing the
    unlucky interleaving to actually wedge."""
    lock_a = runtime.named_lock("A")
    lock_b = runtime.named_lock("B")

    def a_then_b():
        with lock_a:
            with lock_b:
                pass

    t = threading.Thread(target=a_then_b)
    t.start()
    t.join()

    with lock_b:
        with lock_a:
            pass

    report = runtime.lock_witness_report()
    inversions = [v for v in report["violations"]
                  if v["kind"] == "lock-order-inversion"]
    assert inversions, report
    v = inversions[0]
    assert v["edge"] == ["B", "A"]
    assert v["cycle"][0] == v["cycle"][-1] == "A"
    assert ("A", "B") in [tuple(e) for e in report["edges"]]
    assert set(report["locks"]) == {"A", "B"}


def test_witness_consistent_order_is_clean(witness):
    lock_a = runtime.named_lock("A")
    lock_b = runtime.named_lock("B")
    for _ in range(3):
        with lock_a:
            with lock_b:
                pass
    assert runtime.lock_witness_report()["violations"] == []


def test_witness_blocking_under_lock(witness):
    lock = runtime.named_lock("L")
    with runtime.witness_blocking("free-io"):
        pass                               # not held: no violation
    with lock:
        with runtime.witness_blocking("status-write"):
            pass
    report = runtime.lock_witness_report()
    blocked = [v for v in report["violations"]
               if v["kind"] == "blocking-under-lock"]
    assert len(blocked) == 1
    assert blocked[0]["blocking"] == "status-write"
    assert blocked[0]["held"] == ["L"]


def test_witness_reentrant_rlock_not_an_inversion(witness):
    rlock = runtime.named_lock("R", rlock=True)
    with rlock:
        with rlock:
            pass
    assert runtime.lock_witness_report()["violations"] == []


def test_witness_dump_artifact(witness, tmp_path):
    lock = runtime.named_lock("D")
    with lock:
        pass
    out = str(tmp_path / "WITNESS.json")
    runtime.lock_witness_dump(out)
    import json as _json
    with open(out) as f:
        payload = _json.load(f)
    assert payload["enabled"] is True
    assert payload["locks"] == ["D"]


def test_witness_disabled_is_off_path():
    """Disabled (the production default): named_lock returns PLAIN
    threading primitives and witness_blocking returns one shared no-op
    object — the hot path pays a single module-global read."""
    runtime.lock_witness_configure(enabled=False)
    assert not runtime.witness_enabled()
    lock = runtime.named_lock("prod")
    assert isinstance(lock, type(threading.Lock()))
    cm1 = runtime.witness_blocking("a")
    cm2 = runtime.witness_blocking("b")
    assert cm1 is cm2                      # the shared null singleton
    t0 = time.monotonic()
    for _ in range(100_000):
        with runtime.witness_blocking("hot"):
            pass
    assert time.monotonic() - t0 < 1.0
    # nothing was recorded
    report = runtime.lock_witness_report()
    assert report["violations"] == [] and report["locks"] == []


def test_witness_condition_compat(witness):
    """threading.Condition must accept a witnessed lock (server._work
    wraps server._lock) — acquire/release/context protocol."""
    lock = runtime.named_lock("cond-lock")
    cond = threading.Condition(lock)
    with cond:
        cond.wait(timeout=0.01)
        cond.notify_all()
    report = runtime.lock_witness_report()
    assert "cond-lock" in report["locks"]
    assert report["violations"] == []
