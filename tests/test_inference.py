"""Blockwise inference workflow tests (BASELINE config 5).

Oracle style: re-run the exact per-block computation (halo load -> jitted
forward -> crop -> requant) directly against the model and compare with the
workflow's output datasets — validating halo geometry, channel mapping and
requantization wiring (reference test analog: the reference has no inference
test; this follows the recompute-oracle style of SURVEY §4).
"""

import numpy as np
import pytest

from cluster_tools_tpu.core.storage import file_reader


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    from cluster_tools_tpu.models.checkpoint import save_checkpoint
    from cluster_tools_tpu.models.unet import create_unet

    import jax

    path = str(tmp_path_factory.mktemp("ckpt") / "model")
    cfg = {"out_channels": 3, "features": [8, 16], "anisotropic": False}
    model = create_unet(**{**cfg, "features": tuple(cfg["features"])})
    params = model.init(jax.random.PRNGKey(0),
                        np.zeros((1, 8, 8, 8, 1), "float32"))
    params = jax.tree_util.tree_map(np.asarray, params)
    save_checkpoint(path, cfg, params)
    return path


def _make_input(tmp_path, shape=(16, 32, 32)):
    raw = (np.random.RandomState(0).rand(*shape) * 255).astype("float32")
    path = str(tmp_path / "data.n5")
    with file_reader(path) as f:
        f.create_dataset("raw", data=raw, chunks=[8, 16, 16])
    return path, raw


def test_checkpoint_roundtrip(checkpoint):
    import jax

    from cluster_tools_tpu.models.checkpoint import load_checkpoint

    model, params = load_checkpoint(checkpoint)
    x = np.random.RandomState(1).rand(1, 8, 16, 16, 1).astype("float32")
    out = model.apply(params, x)
    assert out.shape == (1, 8, 16, 16, 3)
    leaves = jax.tree_util.tree_leaves(params)
    assert all(isinstance(l, np.ndarray) for l in leaves)


def test_inference_task_channel_mapping(tmp_path, checkpoint, tmp_workdir):
    from cluster_tools_tpu.workflows.inference import (
        InferenceTask, load_with_halo, make_predictor, to_uint8)
    import cluster_tools_tpu as ctt

    tmp_folder, config_dir = tmp_workdir
    shape = (16, 32, 32)
    in_path, raw = _make_input(tmp_path, shape)
    out_path = str(tmp_path / "out.n5")
    halo = [2, 4, 4]

    # block_shape from tmp_workdir global config is [10,10,10]
    task = InferenceTask(
        input_path=in_path, input_key="raw", output_path=out_path,
        output_key={"affs": [0, 3], "boundary": [0, 1]},
        checkpoint_path=checkpoint, halo=halo,
        tmp_folder=tmp_folder, config_dir=config_dir, max_jobs=2,
        target="threads")
    assert ctt.build([task])

    with file_reader(out_path, "r") as f:
        affs = f["affs"][:]
        boundary = f["boundary"][:]
    assert affs.shape == (3, *shape)
    assert boundary.shape == shape
    assert affs.dtype == np.uint8
    # sigmoid outputs in (0,1) -> requantized bytes span a real range
    assert affs.max() > 0
    # channel 0 of affs is the boundary dataset
    np.testing.assert_array_equal(affs[0], boundary)

    # recompute one interior block directly
    with file_reader(in_path, "r") as f:
        ds_in = f["raw"]
        block_shape = [10, 10, 10]
        offset = [0, 10, 10]
        outer_shape = tuple(bs + 2 * h for bs, h in zip(block_shape, halo))
        data = load_with_halo(ds_in, offset, block_shape, halo)
        assert data.shape == outer_shape
        predict = make_predictor(checkpoint, outer_shape, halo)
        pred = to_uint8(predict(data))
    np.testing.assert_array_equal(
        affs[:, 0:10, 10:20, 10:20], pred)


def test_inference_mask_skips_blocks(tmp_path, checkpoint, tmp_workdir):
    from cluster_tools_tpu.workflows.inference import InferenceTask
    import cluster_tools_tpu as ctt

    tmp_folder, config_dir = tmp_workdir
    shape = (16, 32, 32)
    in_path, _ = _make_input(tmp_path, shape)
    out_path = str(tmp_path / "out.n5")
    mask = np.zeros(shape, "uint8")
    mask[:, :16, :] = 1  # right half masked out
    mask_path = str(tmp_path / "mask.n5")
    with file_reader(mask_path) as f:
        f.create_dataset("mask", data=mask, chunks=[8, 16, 16])

    task = InferenceTask(
        input_path=in_path, input_key="raw", output_path=out_path,
        output_key={"pred": [0, 1]}, checkpoint_path=checkpoint,
        halo=[2, 4, 4], mask_path=mask_path, mask_key="mask",
        tmp_folder=tmp_folder, config_dir=config_dir, max_jobs=1,
        target="threads")
    assert ctt.build([task])

    with file_reader(out_path, "r") as f:
        pred = f["pred"][:]
    # masked-out blocks never written -> stay zero; sigmoid output in the
    # written half requantizes to nonzero bytes
    assert pred[:, 24:, :].max() == 0
    assert pred[:, :8, :].min() > 0


def test_load_with_halo_reflect_padding(tmp_path):
    from cluster_tools_tpu.workflows.inference import load_with_halo

    shape = (8, 8, 8)
    raw = np.arange(np.prod(shape), dtype="float32").reshape(shape)
    path = str(tmp_path / "d.n5")
    with file_reader(path) as f:
        f.create_dataset("raw", data=raw, chunks=[4, 4, 4])
        ds = f["raw"]
        out = load_with_halo(ds, (0, 0, 0), (4, 4, 4), (2, 2, 2))
    assert out.shape == (8, 8, 8)
    expected = np.pad(raw[:6, :6, :6], ((2, 0), (2, 0), (2, 0)),
                      mode="reflect")
    np.testing.assert_array_equal(out, expected)


def test_load_with_halo_channel_slice(tmp_path):
    from cluster_tools_tpu.workflows.inference import load_with_halo

    shape = (3, 8, 8, 8)
    raw = np.arange(np.prod(shape), dtype="float32").reshape(shape)
    path = str(tmp_path / "d4.n5")
    with file_reader(path) as f:
        f.create_dataset("raw", data=raw, chunks=[1, 4, 4, 4])
        ds = f["raw"]
        out = load_with_halo(ds, (4, 4, 4), (4, 4, 4), (1, 1, 1),
                             channel_slice=slice(1, 3))
    assert out.shape == (2, 6, 6, 6)
    expected = np.pad(raw[1:3, 3:, 3:, 3:], ((0, 0),) + 3 * ((0, 1),),
                      mode="reflect")
    np.testing.assert_array_equal(out, expected)


def test_predict_sharded_matches_single(tmp_path, checkpoint):
    """Multi-chip batch prediction over the virtual 8-device mesh equals the
    per-block jitted forward."""
    from cluster_tools_tpu.workflows.inference import (make_predictor,
                                                       predict_sharded)

    outer = (8, 16, 16)
    blocks = np.random.RandomState(2).rand(3, *outer).astype("float32")
    out = predict_sharded(checkpoint, blocks, n_devices=8)
    assert out.shape == (3, 3, *outer)

    predict = make_predictor(checkpoint, outer, (0, 0, 0))
    single = predict(blocks[1])
    np.testing.assert_allclose(out[1], single, atol=2e-2)


def test_inference_pytorch_framework(tmp_path, tmp_workdir):
    """Torch-checkpoint predictor (framework registry, reference
    inference/frameworks.py parity): a fixed 1x1x1 conv model run through
    the blockwise task matches the direct per-block recompute."""
    torch = pytest.importorskip("torch")
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.blocking import Blocking
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.models.frameworks import make_torch_predictor
    from cluster_tools_tpu.workflows.inference import (InferenceTask,
                                                       load_with_halo)

    tmp_folder, config_dir = tmp_workdir
    shape = (16, 32, 32)
    in_path, raw = _make_input(tmp_path, shape)
    out_path = str(tmp_path / "torch_out.n5")
    halo = [2, 4, 4]

    model = torch.nn.Conv3d(1, 2, 1, bias=False)
    with torch.no_grad():
        model.weight[:] = torch.tensor([2.0, -1.0]).view(2, 1, 1, 1, 1)
    ckpt = str(tmp_path / "model.pt")
    torch.save(model, ckpt)

    ConfigDir(config_dir).write_task_config(
        "inference", {"framework": "pytorch", "dtype": "float32"})
    task = InferenceTask(
        input_path=in_path, input_key="raw", output_path=out_path,
        output_key={"pos": [0, 1], "both": [0, 2]},
        checkpoint_path=ckpt, halo=halo,
        tmp_folder=tmp_folder, config_dir=config_dir, max_jobs=2,
        target="threads")
    assert ctt.build([task])

    with file_reader(out_path, "r") as f:
        pos = f["pos"][:]
        both = f["both"][:]
    assert pos.shape == shape and both.shape == (2, *shape)
    np.testing.assert_allclose(both[0], pos, rtol=1e-5)
    # scaled channels of a linear model: ch1 = -ch0/2
    np.testing.assert_allclose(both[1], -0.5 * both[0], rtol=1e-4, atol=1e-5)

    # oracle: recompute one interior block directly through the registry
    block_shape = [10, 10, 10]
    blocking = Blocking(shape, block_shape)
    predict = make_torch_predictor(
        ckpt, tuple(b + 2 * h for b, h in zip(block_shape, halo)), halo)
    with file_reader(in_path, "r") as f:
        ds = f["raw"]
        block = blocking.get_block(4)
        data = load_with_halo(ds, block.begin, block_shape, halo)
    expected = predict(data)
    actual = pos[block.bb]
    inner = tuple(slice(0, e - b) for b, e in zip(block.begin, block.end))
    np.testing.assert_allclose(actual, expected[(0,) + inner], rtol=1e-5)


def test_get_predictor_unknown_framework():
    from cluster_tools_tpu.models.frameworks import get_predictor

    with pytest.raises(KeyError):
        get_predictor("tensorflow", "x", (8, 8, 8), (0, 0, 0))


def test_tta_mirror_wrapper():
    """wrap_tta averages the 8 mirror variants with correct inversion."""
    from cluster_tools_tpu.models.frameworks import wrap_tta

    rng = np.random.RandomState(0)

    # a predictor equivariant under flips (elementwise): TTA == plain
    def equivariant(block):
        return (block * 2.0)[None].astype("float32")

    x = rng.rand(6, 8, 8).astype("float32")
    plain = equivariant(x)
    tta = wrap_tta(equivariant, "mirror")(x)
    np.testing.assert_allclose(tta, plain, rtol=1e-6)

    # a non-equivariant predictor: TTA equals the hand-computed average
    def shifted(block):
        out = np.zeros_like(block)
        out[1:] = block[:-1]  # shift along z
        return out[None].astype("float32")

    tta = wrap_tta(shifted, "mirror")(x)
    import itertools

    acc = np.zeros((1,) + x.shape, "float64")
    for flips in itertools.product([False, True], repeat=3):
        axes = tuple(d for d, f in enumerate(flips) if f)
        xb = np.flip(x, axes) if axes else x
        y = shifted(np.ascontiguousarray(xb))
        oaxes = tuple(1 + d for d, f in enumerate(flips) if f)
        acc += np.flip(y, oaxes) if oaxes else y
    np.testing.assert_allclose(tta, (acc / 8).astype("float32"), rtol=1e-6)

    # unknown mode raises
    import pytest

    with pytest.raises(ValueError, match="unknown tta mode"):
        wrap_tta(shifted, "rotate")
