"""dtype-f64 / dtype-int32: dtype discipline on ``ops/`` paths.

* ``dtype-f64`` — float64 introduced inside a DIRECTLY traced scope
  (jit/pjit/shard_map decorated or passed to one).  JAX defaults to
  f32 and the x64 flag is off; an f64 literal/astype/dtype= in a
  traced program either silently downcasts or doubles device memory
  if x64 is ever enabled.  Host-side f64 staging helpers (e.g. the
  gaussian-kernel constant builder) are fine and out of scope.
* ``dtype-int32`` — ``.astype(int32)`` on names that look like packed
  keys / seed ids / label offsets, anywhere in ``ops/``.  Global seed
  ids exceed 2**31 on real volumes (the PR-10 corruption class); the
  sanctioned route is ``ops.mws.compact_seeds_int32`` which
  range-checks after compaction.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Set

from .base import Finding, Pass, SourceFile, dotted_name
from .trace_purity import traced_functions

_F64 = frozenset({"float64", "f8", "double"})
_I32 = frozenset({"int32", "i4"})
#: receiver-name tokens that mark a global-id/packed-key value.
#: Deliberately does NOT include "label": post-relabel dense labels are
#: block-local by construction; the >2**31 corruption class is global
#: SEED/packed-edge ids.
_KEY_TOKENS = ("seed", "packed", "key", "offset")


def _dtype_token(node: ast.AST) -> Optional[str]:
    """'float64' / 'int32' / ... for a dtype-valued expression."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    name = dotted_name(node)
    if name:
        return name.rsplit(".", 1)[-1]
    return None


def _names_in(node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id.lower())
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr.lower())
    return out


def _is_keyish(node: ast.AST) -> bool:
    names = _names_in(node)
    return any(tok in n for n in names for tok in _KEY_TOKENS)


def run(sf: SourceFile) -> List[Finding]:
    if not sf.in_dir("ops"):
        return []
    traced_functions(sf)               # populates traced_fns_direct
    direct = sf.cache.get("traced_fns_direct", set())
    in_traced: Set[int] = set()
    for fn in direct:
        for node in ast.walk(fn):
            if hasattr(node, "lineno"):
                in_traced.add(id(node))

    out: List[Finding] = []
    for node in ast.walk(sf.tree):
        if not isinstance(node, ast.Call):
            continue
        # .astype(<dtype>)
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "astype" and node.args:
            tok = _dtype_token(node.args[0])
            if tok in _F64 and id(node) in in_traced:
                out.append(Finding(
                    sf.rel, node.lineno, "dtype-f64",
                    "astype(%s) inside a traced program — JAX x64 is "
                    "off; keep device math in f32" % tok))
            elif tok in _I32 and _is_keyish(node.func.value):
                out.append(Finding(
                    sf.rel, node.lineno, "dtype-int32",
                    "bare int32 cast on a packed-key/seed-id value — "
                    "global ids exceed 2**31; use "
                    "ops.mws.compact_seeds_int32"))
            continue
        if id(node) not in in_traced:
            continue
        # np.float64(x) / jnp.float64(x) constructor
        fn_name = dotted_name(node.func)
        if fn_name and fn_name.rsplit(".", 1)[-1] in _F64:
            out.append(Finding(
                sf.rel, node.lineno, "dtype-f64",
                "%s(...) inside a traced program — JAX x64 is off; "
                "keep device math in f32" % fn_name))
            continue
        # dtype="float64" keyword in a traced scope
        for kw in node.keywords:
            if kw.arg == "dtype" and _dtype_token(kw.value) in _F64:
                out.append(Finding(
                    sf.rel, kw.value.lineno, "dtype-f64",
                    "dtype=float64 inside a traced program — JAX x64 "
                    "is off; keep device math in f32"))
    return out


PASS = Pass(name="dtype-discipline",
            rules=("dtype-f64", "dtype-int32"), run=run)
