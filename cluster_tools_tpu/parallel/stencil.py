"""Sharded-stencil primitive: halo exchange over a mesh axis via ppermute.

The reference's universal spatial pattern is "read outerBlock (with halo),
write innerBlock" through the filesystem (watershed/watershed.py:252-264,
inference/inference.py:202-232).  On TPU the volume lives sharded across
chips, and the halo read becomes a ring exchange over ICI — structurally
identical to ring/context-parallel sequence sharding (SURVEY §5.7), so it is
built once here and reused by every stencil-shaped workload (filters, EDT
seams, inference, two-pass watershed).

``halo_exchange`` runs *inside* a ``shard_map``-decorated function: each shard
sends its boundary slabs to its +1/-1 neighbors along the mesh axis and
concatenates the received slabs, growing the local array by ``halo`` on both
sides of ``axis``.  Non-periodic edges are padded with ``fill`` (the analog of
reflect/constant padding at volume borders).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _take(x: jnp.ndarray, axis: int, sl: slice) -> jnp.ndarray:
    idx = [slice(None)] * x.ndim
    idx[axis] = sl
    return x[tuple(idx)]


def axis_size(mesh_axis: str) -> int:
    """STATIC size of a named mesh axis inside shard_map (the ppermute
    ring and the edge-shard handling below need it as a Python int).
    ``jax.lax.axis_size`` only exists in newer jax; fall back to the
    tracing axis env."""
    try:
        return int(jax.lax.axis_size(mesh_axis))
    except AttributeError:
        from jax._src import core as _core

        return int(_core.get_axis_env().axis_size(mesh_axis))


def device_varying(a: jnp.ndarray, mesh_axis: str) -> jnp.ndarray:
    """Mark ``a`` device-varying over ``mesh_axis`` inside shard_map —
    the jax-version shim (pcast on current jax, pvary on the vma
    transition releases, no-op on pre-vma jax where unmarked values are
    already varying) shared by the ring collectives."""
    if hasattr(jax.lax, "pcast"):
        return jax.lax.pcast(a, (mesh_axis,), to="varying")
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(a, (mesh_axis,))
    return a


def halo_exchange(x: jnp.ndarray, halo: int, axis: int, mesh_axis: str,
                  fill: Any = 0, mode: str = "constant") -> jnp.ndarray:
    """Grow ``x`` by ``halo`` on both ends of ``axis`` with neighbor data.

    Must be called inside shard_map with ``mesh_axis`` a named mesh axis.
    ``mode``: 'constant' (pad with fill) or 'reflect' at the outer volume
    borders (reference: inference reflect-padding, inference.py:202-232).
    'reflect' mirrors excluding the border plane (numpy/jnp.pad 'reflect'
    semantics) and therefore needs ``halo <= x.shape[axis] - 1``.
    """
    if halo <= 0:
        return x
    n = axis_size(mesh_axis)
    idx = jax.lax.axis_index(mesh_axis)

    lo_slab = _take(x, axis, slice(0, halo))           # my low boundary
    hi_slab = _take(x, axis, slice(x.shape[axis] - halo, None))

    if n > 1:
        # send my high slab to the next shard (it becomes their low halo)
        recv_lo = jax.lax.ppermute(
            hi_slab, mesh_axis, [(i, (i + 1) % n) for i in range(n)])
        # send my low slab to the previous shard (their high halo)
        recv_hi = jax.lax.ppermute(
            lo_slab, mesh_axis, [(i, (i - 1) % n) for i in range(n)])
    else:
        recv_lo = lo_slab
        recv_hi = hi_slab

    if mode == "reflect":
        # numpy-style reflect: mirror EXCLUDING the border plane, the
        # same fold as jnp.pad(mode='reflect') and the blockwise chain's
        # volume-level reflect_indices (period 2n-2) — including it
        # would duplicate the border plane and silently diverge from
        # the per-block readers.  Requires halo <= size-1 on this axis
        # (same constraint jnp.pad imposes; callers clamp)
        size = x.shape[axis]
        pad_lo = jnp.flip(_take(x, axis, slice(1, halo + 1)), axis=axis)
        pad_hi = jnp.flip(_take(x, axis, slice(size - halo - 1, size - 1)),
                          axis=axis)
    else:
        pad_lo = jnp.full_like(lo_slab, fill)
        pad_hi = jnp.full_like(hi_slab, fill)

    # first/last shards have no ring neighbor on that side: use border padding
    lo = jnp.where(idx == 0, pad_lo, recv_lo) if n > 1 else pad_lo
    hi = jnp.where(idx == n - 1, pad_hi, recv_hi) if n > 1 else pad_hi
    return jnp.concatenate([lo, x, hi], axis=axis)


def crop_halo(x: jnp.ndarray, halo: int, axis: int) -> jnp.ndarray:
    """Drop ``halo`` from both ends of ``axis`` (write the innerBlock)."""
    if halo <= 0:
        return x
    return _take(x, axis, slice(halo, x.shape[axis] - halo))


def sharded_stencil(fn, mesh: Mesh, halo: int, axis: int = 0,
                    mesh_axis: str = "space", fill: Any = 0,
                    mode: str = "constant"):
    """Wrap a local stencil ``fn(block) -> block`` into a mesh-sharded op.

    The returned function takes a global array sharded over ``mesh_axis`` on
    ``axis``, performs the halo exchange, applies ``fn`` to the haloed local
    shard, and crops the halo back off — the single reusable primitive
    replacing the reference's outer/inner block machinery.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    def local(x):
        grown = halo_exchange(x, halo, axis, mesh_axis, fill=fill, mode=mode)
        out = fn(grown)
        return crop_halo(out, halo, axis)

    def specs(ndim):
        spec = [None] * ndim
        spec[axis] = mesh_axis
        return P(*spec)

    def apply(x):
        sp = specs(x.ndim)
        return shard_map(local, mesh=mesh, in_specs=(sp,), out_specs=sp)(x)

    return apply
