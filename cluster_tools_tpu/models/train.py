"""Sharded training step for the flagship U-Net.

The reference consumes externally-trained torch checkpoints (SURVEY §5.4: "no
model/optimizer checkpointing exists"); the TPU framework closes that gap with
an in-framework training loop.  Design:

* one jitted ``train_step`` over the full mesh (data x space x model):
  batch sharded over ``data``, the volume z-axis sharded over ``space``
  (GSPMD partitions the convolutions and inserts halo collectives over ICI),
  wide conv kernels sharded over ``model`` (tensor parallelism);
* loss = Dice + balanced BCE on affinities — the standard EM boundary loss;
* optimizer = optax adamw; gradients are averaged across ``data``/``space``
  implicitly by GSPMD when the params are replicated over those axes;
* checkpointing via orbax (models/checkpoint helpers in the inference
  workflow read the same format).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib
from .unet import UNet3D, create_unet


def affinity_loss(pred: jnp.ndarray, target: jnp.ndarray,
                  mask: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Balanced BCE + soft-Dice on affinity channels (float32 math)."""
    pred = pred.astype(jnp.float32)
    target = target.astype(jnp.float32)
    eps = 1e-6
    pred = jnp.clip(pred, eps, 1.0 - eps)
    bce = -(target * jnp.log(pred) + (1.0 - target) * jnp.log(1.0 - pred))
    if mask is not None:
        bce = bce * mask
        denom = jnp.maximum(mask.sum(), 1.0)
    else:
        denom = float(np.prod(bce.shape))
    bce = bce.sum() / denom
    inter = (pred * target).sum()
    dice = 1.0 - (2.0 * inter + 1.0) / ((pred ** 2).sum() + (target ** 2).sum() + 1.0)
    return bce + dice


class TrainState:
    """Minimal train state (params + opt state); a plain pytree container."""

    def __init__(self, params, opt_state, step):
        self.params = params
        self.opt_state = opt_state
        self.step = step

    def tree_flatten(self):
        return (self.params, self.opt_state, self.step), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def make_optimizer(lr: float = 1e-3, weight_decay: float = 1e-5):
    return optax.adamw(lr, weight_decay=weight_decay)


def init_state(model: UNet3D, input_shape: Tuple[int, ...],
               rng: Optional[jax.Array] = None,
               lr: float = 1e-3) -> TrainState:
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    params = model.init(rng, jnp.zeros(input_shape, jnp.float32))
    opt = make_optimizer(lr)
    return TrainState(params, opt.init(params), jnp.zeros((), jnp.int32))


def make_train_step(model: UNet3D, lr: float = 1e-3):
    """The pure train-step function (state, x, y) -> (state, loss)."""
    opt = make_optimizer(lr)

    def step(state: TrainState, x, y):
        def loss_fn(params):
            pred = model.apply(params, x)
            return affinity_loss(pred, y)

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        updates, opt_state = opt.update(grads, state.opt_state, state.params)
        params = optax.apply_updates(state.params, updates)
        return TrainState(params, opt_state, state.step + 1), loss

    return step


def shard_train_step(model: UNet3D, state: TrainState, mesh: Mesh,
                     lr: float = 1e-3):
    """jit the train step over the mesh with dp/sp/tp shardings.

    Returns (jitted_step, sharded_state, batch_shardings).  Params carry
    tensor-parallel annotations from :func:`mesh_lib.param_sharding`; inputs
    are sharded (batch over 'data', z over 'space'); GSPMD lowers the
    convolutions to spatially-partitioned kernels with ICI halo collectives
    and inserts the gradient reductions.
    """
    step = make_train_step(model, lr)

    p_shard = mesh_lib.param_sharding(mesh, state.params)
    o_shard = mesh_lib.param_sharding(mesh, state.opt_state)  # mu/nu follow params
    rep = mesh_lib.replicated(mesh)
    x_shard = NamedSharding(mesh, P("data", "space", None, None, None))

    placed = TrainState(jax.device_put(state.params, p_shard),
                        jax.device_put(state.opt_state, o_shard),
                        jax.device_put(state.step, rep))
    # shardings flow from the arguments; GSPMD propagates them through the
    # step and inserts the ICI collectives (halo exchange for spatially
    # partitioned convs, all-reduce for the data/space-summed gradients)
    jitted = jax.jit(step)
    return jitted, placed, x_shard


def train_step_for_mesh(n_devices: int = 8,
                        features=(8, 16, 32),
                        shape=(2, 8, 32, 32)):
    """Build (jitted_step, state, example_batch) for an n-device mesh —
    used by ``__graft_entry__.dryrun_multichip`` and the tests."""
    mesh = mesh_lib.make_mesh(n_devices)
    dp = mesh.shape["data"]
    sp = mesh.shape["space"]
    model = create_unet(out_channels=3, features=features, anisotropic=False)
    div = model.min_divisor()

    def _round_up(v, m):  # round every dim so mesh axes and U-Net scales divide
        return int(-(-v // m) * m)

    b = _round_up(max(shape[0], dp), dp)
    d = _round_up(max(shape[1], sp * div[0]), sp * div[0])
    h = _round_up(max(shape[2], div[1]), div[1])
    w = _round_up(max(shape[3], div[2]), div[2])
    x = np.random.RandomState(0).rand(b, d, h, w, 1).astype(np.float32)
    y = (np.random.RandomState(1).rand(b, d, h, w, 3) > 0.5).astype(np.float32)
    state = init_state(model, (1, d, h, w, 1))
    jitted, state, x_shard = shard_train_step(model, state, mesh)
    xj = jax.device_put(jnp.asarray(x), x_shard)
    yj = jax.device_put(jnp.asarray(y), x_shard)
    return jitted, state, (xj, yj)
