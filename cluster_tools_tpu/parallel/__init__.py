from .mesh import make_mesh, volume_sharding, param_sharding, replicated
from .stencil import halo_exchange, crop_halo, sharded_stencil
