"""Hierarchical blockwise LIFTED multicut.

Re-specification of the reference's ``lifted_multicut/`` package
(solve_lifted_subproblems.py:27-325, reduce_lifted_problem.py:26,
solve_lifted_global.py:21, lifted_multicut_workflow.py): the multicut
solve->reduce ladder with long-range lifted edges carried along — per-block
subproblems pick up the lifted pairs entirely inside the block and solve the
lifted objective (native lmc_gaec + lmc_kl_refine); the reduce step maps
lifted pairs through the scale's node labeling and re-accumulates their
costs.

Container layout extends the multicut problem:

    s<i>/lifted_nh_<prefix>      (L, 2) uint64 lifted pairs
    s<i>/lifted_costs_<prefix>   (L,) float64
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..core import graph as g
from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import Task
from .multicut import (ReduceProblem, SolveSubproblems, _load_costs,
                       _load_scale_graph, compose_to_s0,
                       save_assignment_table)


def _lifted_keys(scale: int, prefix: str):
    return (f"s{scale}/lifted_nh_{prefix}",
            f"s{scale}/lifted_costs_{prefix}")


def _load_lifted(problem_path: str, scale: int, prefix: str):
    from .lifted_features import load_edge_list

    nh_key, costs_key = _lifted_keys(scale, prefix)
    with file_reader(problem_path, "r") as f:
        if nh_key not in f:
            return np.zeros((0, 2), "uint64"), np.zeros(0, "float64")
    lifted_uv = load_edge_list(problem_path, nh_key)
    with file_reader(problem_path, "r") as f:
        lifted_costs = f[costs_key][:][:len(lifted_uv)]
    return lifted_uv, lifted_costs.astype("float64")


def _save_lifted(problem_path: str, scale: int, prefix: str,
                 lifted_uv: np.ndarray, lifted_costs: np.ndarray) -> None:
    from .lifted_features import save_edge_list

    nh_key, costs_key = _lifted_keys(scale, prefix)
    save_edge_list(problem_path, nh_key, lifted_uv)
    # zero-size datasets are not representable; pad to one row, the true
    # count travels in the nh dataset's n_edges attr
    costs = (lifted_costs.astype("float64") if len(lifted_costs)
             else np.zeros(1, "float64"))
    with file_reader(problem_path) as f:
        f.require_dataset(costs_key, data=costs, shape=costs.shape,
                          chunks=(min(int(1e6), len(costs)),))


def find_inner_lifted(lifted_uv: np.ndarray, nodes: np.ndarray) -> np.ndarray:
    """Indices of lifted pairs with BOTH endpoints in ``nodes`` (reference:
    solve_lifted_subproblems.py:131 ``_find_lifted_edges``)."""
    if len(lifted_uv) == 0:
        return np.zeros(0, "int64")
    lookup = np.sort(nodes)

    def _in(col):
        idx = np.searchsorted(lookup, col)
        return (idx < len(lookup)) & (
            lookup[np.minimum(idx, len(lookup) - 1)] == col)

    return np.flatnonzero(_in(lifted_uv[:, 0]) & _in(lifted_uv[:, 1]))


def _lifted_dense_pairs(lifted_uv: np.ndarray, scale: int, s0_nodes):
    """Lifted pairs are stored in original node labels at s0; map them to
    the dense node indexing used by the solver layer."""
    if scale == 0 and len(lifted_uv):
        graph0 = g.Graph(s0_nodes, np.zeros((0, 2), "uint64"))
        return np.stack([graph0.node_index(lifted_uv[:, 0]),
                         graph0.node_index(lifted_uv[:, 1])], axis=1)
    return lifted_uv.astype("int64")


class SolveLiftedSubproblems(SolveSubproblems):
    """Per-block lifted multicut (reference: SolveLiftedSubproblems,
    solve_lifted_subproblems.py:27-241).  Reuses the base block loop; only
    the per-block solve differs (lifted solver when the block holds lifted
    pairs)."""

    task_name = "solve_lifted_subproblems"

    def __init__(self, lifted_prefix: str, **kw):
        self.lifted_prefix = lifted_prefix
        super().__init__(**kw)

    def _extra_job_config(self):
        return {"lifted_prefix": self.lifted_prefix}

    @classmethod
    def _job_context(cls, cfg, s0_nodes):
        lifted_uv, lifted_costs = _load_lifted(
            cfg["problem_path"], int(cfg["scale"]), cfg["lifted_prefix"])
        return {"lifted_dense": _lifted_dense_pairs(
                    lifted_uv, int(cfg["scale"]), s0_nodes),
                "lifted_costs": lifted_costs}

    @classmethod
    def _solve_block(cls, cfg, ctx, nodes_dense, inner, uv_dense, costs):
        from .. import native

        inner_lifted = find_inner_lifted(ctx["lifted_dense"], nodes_dense)
        if len(inner_lifted) == 0:
            return SolveSubproblems._solve_block(cfg, ctx, nodes_dense,
                                                 inner, uv_dense, costs)
        sub_uv = uv_dense[inner]
        all_pairs = np.concatenate([sub_uv, ctx["lifted_dense"][inner_lifted]])
        sub_nodes, local_flat = np.unique(all_pairs, return_inverse=True)
        local_all = local_flat.reshape(-1, 2).astype("int64")
        local_uv = local_all[:len(sub_uv)]
        local_lifted = local_all[len(sub_uv):]
        sub_res = native.lifted_multicut_kernighan_lin(
            len(sub_nodes), local_uv, costs[inner], local_lifted,
            ctx["lifted_costs"][inner_lifted])
        cut_mask = sub_res[local_uv[:, 0]] != sub_res[local_uv[:, 1]]
        return inner[cut_mask]


class ReduceLiftedProblem(ReduceProblem):
    """ReduceProblem + map the lifted pairs through the scale labeling and
    re-accumulate their costs (reference: reduce_lifted_problem.py:26)."""

    task_name = "reduce_lifted_problem"

    def __init__(self, lifted_prefix: str, **kw):
        self.lifted_prefix = lifted_prefix
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.problem_path, "r") as f:
            shape = list(f["s0/graph"].attrs["shape"])
        base_bs = self.global_block_shape()
        scale_bs = [b * 2 ** self.scale for b in base_bs]
        self.run_jobs(None, {
            "problem_path": self.problem_path, "scale": self.scale,
            "shape": shape, "block_shape": base_bs,
            "expected_blocks": self.blocks_in_volume(shape, scale_bs),
            "lifted_prefix": self.lifted_prefix,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        ReduceProblem.process_job(job_id, job_config, log_fn)

        cfg = job_config["config"]
        problem_path = cfg["problem_path"]
        scale = int(cfg["scale"])
        prefix = cfg["lifted_prefix"]
        next_scale = scale + 1

        lifted_uv, lifted_costs = _load_lifted(problem_path, scale, prefix)
        if len(lifted_uv) == 0:
            _save_lifted(problem_path, next_scale, prefix,
                         np.zeros((0, 2), "uint64"), np.zeros(0, "float64"))
            return
        with file_reader(problem_path, "r") as f:
            scale_labeling = f[f"s{next_scale}/scale_node_labeling"][:]
        if scale == 0:
            # lifted pairs carry original s0 labels; the scale labeling is
            # indexed by dense node index
            _, _, s0_nodes = _load_scale_graph(problem_path, 0)
            graph0 = g.Graph(s0_nodes, np.zeros((0, 2), "uint64"))
            dense = np.stack([graph0.node_index(lifted_uv[:, 0]),
                              graph0.node_index(lifted_uv[:, 1])], axis=1)
        else:
            dense = lifted_uv.astype("int64")
        mapped = scale_labeling[dense]
        keep = mapped[:, 0] != mapped[:, 1]
        mu = np.minimum(mapped[keep][:, 0], mapped[keep][:, 1])
        mv = np.maximum(mapped[keep][:, 0], mapped[keep][:, 1])
        pairs = np.stack([mu, mv], axis=1)
        new_lifted, inverse = (np.unique(pairs, axis=0, return_inverse=True)
                               if len(pairs) else
                               (np.zeros((0, 2), "uint64"),
                                np.zeros(0, "int64")))
        new_costs = np.zeros(len(new_lifted), "float64")
        np.add.at(new_costs, inverse, lifted_costs[keep])
        _save_lifted(problem_path, next_scale, prefix, new_lifted, new_costs)
        log_fn(f"reduced lifted edges {len(lifted_uv)} -> {len(new_lifted)}")


class SolveLiftedGlobal(BlockTask):
    """Single global lifted solve -> final assignment table (reference:
    SolveLiftedGlobal, solve_lifted_global.py:21)."""

    task_name = "solve_lifted_global"
    global_task = True
    allow_retry = False

    def __init__(self, problem_path: str, scale: int, assignment_path: str,
                 lifted_prefix: str = "", **kw):
        self.problem_path = problem_path
        self.scale = scale
        self.assignment_path = assignment_path
        self.lifted_prefix = lifted_prefix
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "problem_path": self.problem_path, "scale": self.scale,
            "assignment_path": self.assignment_path,
            "lifted_prefix": self.lifted_prefix,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from .. import native

        cfg = job_config["config"]
        problem_path = cfg["problem_path"]
        scale = int(cfg["scale"])
        prefix = cfg["lifted_prefix"]

        uv_dense, n_nodes, s0_nodes = _load_scale_graph(problem_path, scale)
        costs = _load_costs(problem_path, scale)
        lifted_uv, lifted_costs = _load_lifted(problem_path, scale, prefix)
        lifted_dense = _lifted_dense_pairs(lifted_uv, scale, s0_nodes)
        labels = native.lifted_multicut_kernighan_lin(
            n_nodes, uv_dense.astype("int64"), costs, lifted_dense,
            lifted_costs)
        log_fn(f"global lifted solve: {n_nodes} nodes -> "
               f"{len(np.unique(labels))} segments")

        final = compose_to_s0(problem_path, scale, labels)
        nodes0, _, _ = g.load_graph(problem_path, "s0/graph")
        table = save_assignment_table(nodes0, final, cfg["assignment_path"])
        log_fn(f"assignments saved: {len(table)} fragment ids")


class LiftedMulticutWorkflow(Task):
    """for scale: SolveLiftedSubproblems -> ReduceLiftedProblem; then
    SolveLiftedGlobal (reference: lifted_multicut_workflow.py)."""

    def __init__(self, problem_path: str, assignment_path: str,
                 lifted_prefix: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local", n_scales: int = 1,
                 dependency: Optional[Task] = None):
        self.problem_path = problem_path
        self.assignment_path = assignment_path
        self.lifted_prefix = lifted_prefix
        self.n_scales = n_scales
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        dep = self.dependency
        for scale in range(self.n_scales):
            dep = SolveLiftedSubproblems(
                problem_path=self.problem_path, scale=scale,
                lifted_prefix=self.lifted_prefix, dependency=dep,
                **self._common())
            dep = ReduceLiftedProblem(
                problem_path=self.problem_path, scale=scale,
                lifted_prefix=self.lifted_prefix, dependency=dep,
                **self._common())
        return SolveLiftedGlobal(
            problem_path=self.problem_path, scale=self.n_scales,
            assignment_path=self.assignment_path,
            lifted_prefix=self.lifted_prefix, dependency=dep,
            **self._common())

    def output(self):
        from ..core.workflow import FileTarget

        return FileTarget(os.path.join(self.tmp_folder,
                                       "solve_lifted_global.status"))
