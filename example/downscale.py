"""Multiscale pyramid example (reference: example/downscale.py).

    python example/downscale.py /tmp/ctt_downscale
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(workdir):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.storage import file_reader

    os.makedirs(workdir, exist_ok=True)
    data = os.path.join(workdir, "data.n5")
    config_dir = os.path.join(workdir, "configs")
    ConfigDir(config_dir).write_global_config({"block_shape": [16, 64, 64]})

    raw = np.random.RandomState(0).rand(32, 256, 256).astype("float32")
    with file_reader(data) as f:
        f.create_dataset("raw/s0", data=raw, chunks=[16, 64, 64])

    wf = ctt.DownscalingWorkflow(
        input_path=data, input_key="raw/s0",
        scale_factors=[[1, 2, 2], [2, 2, 2], [2, 2, 2]],
        output_key_prefix="raw",
        metadata_dict={"resolution": [40.0, 4.0, 4.0]},
        tmp_folder=os.path.join(workdir, "tmp"), config_dir=config_dir,
        max_jobs=4, target="local")
    assert ctt.build([wf])

    with file_reader(data, "r") as f:
        for s in range(4):
            print(f"raw/s{s}:", f[f"raw/s{s}"].shape)


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/ctt_downscale")
