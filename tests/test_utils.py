"""Utils tail tests: transformations, knossos adapter, mesh extraction."""

import os

import numpy as np


def test_affine_matrices_roundtrip():
    from cluster_tools_tpu.utils.transformations import (
        matrix_2d, matrix_3d, parameters_from_matrix, transform_roi)

    m = matrix_2d(scale=(2.0, 3.0), rotation=30.0, translation=(5.0, -2.0))
    scale, rot, trans = parameters_from_matrix(m)
    np.testing.assert_allclose(scale, (2.0, 3.0), rtol=1e-6)
    np.testing.assert_allclose(rot, 30.0, rtol=1e-6)
    np.testing.assert_allclose(trans, (5.0, -2.0), rtol=1e-6)

    m = matrix_3d(scale=(1.5, 2.0, 0.5), rotation=(10.0, -20.0, 30.0),
                  translation=(1.0, 2.0, 3.0))
    scale, rot, trans = parameters_from_matrix(m)
    np.testing.assert_allclose(scale, (1.5, 2.0, 0.5), rtol=1e-6)
    np.testing.assert_allclose(rot, (10.0, -20.0, 30.0), rtol=1e-5)
    np.testing.assert_allclose(trans, (1.0, 2.0, 3.0), rtol=1e-6)

    # roi envelope: pure translation shifts the box exactly
    m = matrix_3d(translation=(10.0, 0.0, 0.0))
    lo, hi = transform_roi((0, 0, 0), (4, 4, 4), m)
    np.testing.assert_allclose(lo, (10, 0, 0))
    np.testing.assert_allclose(hi, (14, 4, 4))


def test_knossos_dataset(tmp_path):
    from cluster_tools_tpu.utils.knossos import KnossosDataset, KnossosFile

    # build a tiny 2x1x1-cube pyramid level with raw cubes
    bs = KnossosDataset.block_size
    root = tmp_path / "mag1"
    rng = np.random.RandomState(0)
    cubes = {}
    for gx in range(2):
        d = root / f"x{gx:04d}" / "y0000" / "z0000"
        os.makedirs(d)
        cube = rng.randint(0, 255, size=(bs, bs, bs), dtype=np.uint8)
        cubes[gx] = cube
        cube.tofile(str(d / f"x{gx:04d}_y0000_z0000.raw"))

    ds = KnossosFile(str(tmp_path))["mag1"]
    assert ds.shape == (bs, bs, 2 * bs)
    assert ds.dtype == np.uint8
    # full read stitches the cubes along x
    np.testing.assert_array_equal(ds[:, :, :bs], cubes[0])
    np.testing.assert_array_equal(ds[:, :, bs:], cubes[1])
    # partial read across the cube boundary
    sub = ds[10:20, 0:5, bs - 4:bs + 4]
    np.testing.assert_array_equal(sub[..., :4], cubes[0][10:20, 0:5, -4:])
    np.testing.assert_array_equal(sub[..., 4:], cubes[1][10:20, 0:5, :4])


def test_mesh_extraction_watertight():
    from cluster_tools_tpu.utils.mesh import object_mesh, smooth_mesh

    zz, yy, xx = np.meshgrid(*[np.arange(20)] * 3, indexing="ij")
    seg = ((zz - 10) ** 2 + (yy - 10) ** 2 + (xx - 10) ** 2 < 49
           ).astype("uint64") * 3
    verts, faces = object_mesh(seg, 3)
    assert len(verts) > 100 and len(faces) > 100
    # vertices sit near the radius-7 sphere surface
    r = np.linalg.norm(verts - 10, axis=1)
    assert 5.5 < r.min() and r.max() < 8.5
    # watertight: every edge shared by exactly two faces
    edges = np.sort(np.concatenate(
        [faces[:, [0, 1]], faces[:, [1, 2]], faces[:, [2, 0]]]), axis=1)
    _, counts = np.unique(edges, axis=0, return_counts=True)
    assert (counts == 2).all()
    # smoothing reduces surface roughness
    smoothed = smooth_mesh(verts, faces, iterations=10)
    r2 = np.linalg.norm(smoothed - 10, axis=1)
    assert r2.std() < r.std()


def test_knossos_prefix_discovery_and_file_reader(tmp_path):
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.utils.knossos import KnossosDataset

    bs = KnossosDataset.block_size
    root = tmp_path / "vol.knossos" / "mag1"
    d = root / "x0000" / "y0000" / "z0000"
    os.makedirs(d)
    cube = np.random.RandomState(0).randint(0, 255, size=(bs, bs, bs),
                                            dtype=np.uint8)
    # real Knossos naming: experiment prefix in front of the coordinates
    cube.tofile(str(d / "experiment_mag1_x0000_y0000_z0000.raw"))

    with file_reader(str(tmp_path / "vol.knossos"), "r") as f:
        ds = f["mag1"]
        assert ds.file_prefix == "experiment_mag1"
        np.testing.assert_array_equal(ds[:, :, :], cube)


def test_gimbal_lock_parameters():
    from cluster_tools_tpu.utils.transformations import (
        matrix_3d, parameters_from_matrix)

    m = matrix_3d(rotation=(0.0, 90.0, 0.0))
    scale, rot, trans = parameters_from_matrix(m)
    assert np.isfinite(rot).all()
    # the recovered angles reproduce the same rotation matrix
    m2 = matrix_3d(scale=scale, rotation=rot, translation=trans)
    np.testing.assert_allclose(m2, m, atol=1e-9)
