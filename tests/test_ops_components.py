"""Device connected-components kernel vs scipy oracle."""

import numpy as np
import pytest
from scipy import ndimage

import jax.numpy as jnp

from cluster_tools_tpu.ops.components import (
    connected_components, connected_components_batched, relabel_consecutive,
    threshold_volume,
)


def _same_partition(ours, ref):
    assert ((ours == 0) == (ref == 0)).all()
    fg = ref != 0
    pairs = np.unique(np.stack([ours[fg], ref[fg]]), axis=1)
    assert len(np.unique(pairs[0])) == pairs.shape[1]
    assert len(np.unique(pairs[1])) == pairs.shape[1]


@pytest.mark.parametrize("shape,connectivity", [
    ((32, 32), 1), ((32, 32), 2),
    ((16, 16, 16), 1), ((16, 16, 16), 3),
])
def test_cc_matches_scipy(shape, connectivity):
    rng = np.random.RandomState(42)
    mask = rng.rand(*shape) > 0.6
    ours = np.asarray(connected_components(jnp.asarray(mask),
                                           connectivity=connectivity))
    struct = ndimage.generate_binary_structure(len(shape), connectivity)
    ref, _ = ndimage.label(mask, structure=struct)
    _same_partition(ours, ref)


def test_cc_worst_case_snake():
    # serpentine path: single component with very long graph diameter,
    # stresses the pointer-jumping convergence bound
    mask = np.zeros((16, 16), dtype=bool)
    for i in range(16):
        mask[i, :] = True
        if i + 1 < 16:
            mask[i, -1 if i % 2 == 0 else 0] = True
    mask[1::2, 0] = False
    mask[0::2, 15] = True
    for i in range(0, 15):
        mask[i, 15 if i % 2 == 0 else 0] = True
    ours = np.asarray(connected_components(jnp.asarray(mask)))
    ref, _ = ndimage.label(mask)
    _same_partition(ours, ref)


def test_cc_batched_equals_single():
    rng = np.random.RandomState(0)
    masks = rng.rand(4, 12, 12, 12) > 0.5
    batched = np.asarray(connected_components_batched(jnp.asarray(masks)))
    for i in range(4):
        single = np.asarray(connected_components(jnp.asarray(masks[i])))
        np.testing.assert_array_equal(batched[i], single)


def test_relabel_consecutive():
    labels = np.array([[0, 5, 5], [9, 0, 2]], dtype="uint64")
    out, max_id = relabel_consecutive(labels)
    assert max_id == 3
    assert set(np.unique(out)) == {0, 1, 2, 3}
    assert ((labels == 0) == (out == 0)).all()


def test_threshold_modes():
    x = jnp.asarray(np.array([0.1, 0.5, 0.9]))
    assert np.asarray(threshold_volume(x, 0.5, "greater")).tolist() == [False, False, True]
    assert np.asarray(threshold_volume(x, 0.5, "less")).tolist() == [True, False, False]
    assert np.asarray(threshold_volume(x, 0.5, "equal")).tolist() == [False, True, False]
    with pytest.raises(ValueError):
        threshold_volume(x, 0.5, "bogus")
