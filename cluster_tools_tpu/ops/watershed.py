"""Seeded watershed on device.

TPU-native replacement for vigra's ``watershedsNew`` (reference:
utils/volume_utils.py:123-139 ``watershed`` + size filter;
watershed/watershed.py:211-249 per-block 2d/3d watershed).

Sequential priority-flood is inherently serial, so the device algorithm is the
**steepest-descent forest**: every voxel points to its lowest neighbor (itself
if it is a local minimum), seeds are forced to point to themselves, and
pointer jumping (O(log n) gathers) resolves every voxel to a root.  Voxels
whose root is a seed inherit its label; plateau/non-seed-minimum leftovers are
filled by monotone label propagation in height order (bounded while_loop that
at each step adopts the label of the lowest already-labeled neighbor).  The
result has vigra-compatible *structure* (every masked voxel labeled, seeds
preserved, boundaries on ridges); exact voxel assignments on plateaus differ
between implementations, as they already do between vigra and scipy.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .components import _neighbor_offsets, _shifted


def _flat_offsets(shape: Tuple[int, ...], connectivity: int) -> Tuple[Tuple[int, ...], ...]:
    return _neighbor_offsets(len(shape), connectivity)


def extent_valid_mask(local_shape, extent=None, origin=None, vol_shape=None):
    """Jit-composable validity mask over a block/shard-local window.

    Two conventions, one mask: pass ``extent`` (per-axis REAL size of a
    clipped border block — the blockwise resident program's convention),
    or a SHARD-LOCAL ``origin`` (traced per-shard int vector, e.g.
    ``axis_index * slab_z``) plus the static global ``vol_shape`` — the
    mesh-resident convention, where a shard's local window may overrun the
    volume end by the shard-equalizing pad.  Positions at or past the
    volume end are invalid (their reflect/zero-padded content must never
    enter label ranks, id counts or pair sets)."""
    if extent is None:
        if origin is None or vol_shape is None:
            raise ValueError("pass extent, or origin + vol_shape")
        extent = [jnp.asarray(vol_shape[d], jnp.int32) - origin[d]
                  for d in range(len(local_shape))]
    valid = jnp.ones(tuple(local_shape), bool)
    for d, n in enumerate(local_shape):
        coord = jnp.arange(n)
        shape_d = [1] * len(local_shape)
        shape_d[d] = n
        valid &= (coord < extent[d]).reshape(shape_d)
    return valid


def dense_relabel(inner, n_bound: int, valid=None):
    """Dense per-window relabel (device-side np.unique/searchsorted:
    presence flags + cumsum rank) of the nonzero labels in ``inner``,
    whose values are bounded by ``n_bound``.  ``valid`` masks voxels out
    of the relabel entirely (phantom padding).  Returns
    ``(dense_grid int32, k)`` with dense ids consecutive in [1, k] — the
    shared tail of every resident segmentation program (blockwise and
    mesh-resident alike), so the id convention lives in one place."""
    if valid is not None:
        inner = jnp.where(valid, inner, 0)
    flat = inner.reshape(-1)
    pres = jnp.zeros((n_bound + 2,), jnp.int32).at[flat].set(1, mode="drop")
    pres = pres.at[0].set(0)
    rank = jnp.cumsum(pres)
    dense = jnp.where(flat > 0, rank[flat], 0).astype(jnp.int32)
    return dense.reshape(inner.shape), rank[-1]


def seeded_watershed(
    height: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    max_iter: int = 0,
    method: Optional[str] = None,
) -> jnp.ndarray:
    """Grow ``seeds`` (int labels, 0 = unlabeled) over ``height`` (flooded in
    increasing order) restricted to ``mask``.  Returns int32 labels; 0 only
    outside the mask.

    ``method``: ``'basins'`` (default — watershed cuts via descent forest +
    Boruvka saddle merging, ~50x faster than the flood at [50,512,512] with
    equivalent segmentation quality) or ``'flood'`` (quantized priority
    flood, the reference-ordering formulation kept for comparison).  Env
    ``CTT_WS_METHOD`` overrides the default."""
    import os

    # ctt-lint: disable=trace-purity (dead under trace: _batched_impl always passes method explicitly, so the env read only runs on direct host calls)
    method = method or os.environ.get("CTT_WS_METHOD", "basins")
    if method == "basins":
        return seeded_watershed_basins(height, seeds, mask, connectivity)
    if method == "flood":
        return seeded_watershed_flood(height, seeds, mask, connectivity,
                                      max_iter)
    raise ValueError(f"unknown watershed method {method!r} "
                     "(expected 'basins' or 'flood')")


@partial(jax.jit, static_argnames=("connectivity", "max_iter"))
def seeded_watershed_flood(
    height: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    max_iter: int = 0,
) -> jnp.ndarray:
    """Level-ordered (quantized priority flood) seeded watershed."""
    shape = height.shape
    n = int(np.prod(shape))
    height = height.astype(jnp.float32)
    if mask is None:
        mask = jnp.ones(shape, bool)
    else:
        mask = mask.astype(bool)
    if max_iter == 0:
        # the fill loop advances labels one voxel per iteration along geodesic
        # paths, so the only safe data-independent bound is the voxel count
        # (serpentine corridors realize it); both loops exit early on
        # convergence, so the generous bound costs nothing in practice
        max_iter = max(n, 32)
    offsets = _flat_offsets(shape, connectivity)

    big = jnp.float32(np.finfo(np.float32).max)
    h = jnp.where(mask, height, big)
    seeded = (seeds > 0) & mask
    # seeds are below everything: they are the only attractors
    h = jnp.where(seeded, -big, h)

    flat_idx = jnp.arange(n, dtype=jnp.int32).reshape(shape)

    # steepest-descent pointer: index of the strictly-lowest neighbor
    # (ties broken toward lower linear index for determinism)
    best_h = h
    best_i = flat_idx
    for off in offsets:
        nh = _shifted(h, off, big)
        ni = _shifted(flat_idx, off, jnp.int32(n))
        better = (nh < best_h) | ((nh == best_h) & (ni < best_i) & (nh < h))
        best_h = jnp.where(better, nh, best_h)
        best_i = jnp.where(better, ni, best_i)
    parent = jnp.where(mask, best_i, flat_idx).reshape(-1)
    parent = jnp.where(seeded.reshape(-1), jnp.arange(n, dtype=jnp.int32), parent)

    # pointer jumping to roots (bounded: depth halves per step)
    def jump_body(state):
        p, _, it = state
        p2 = p[p]
        return p2, jnp.any(p2 != p), it + 1

    parent, _, _ = jax.lax.while_loop(
        lambda s: s[1] & (s[2] < max_iter), jump_body,
        (parent, jnp.bool_(True), jnp.int32(0)))

    # ctt-lint: disable=dtype-int32 (caller contract: seeds are block-local compacted ids — sweep.sweep_watershed / mws.compact_seeds_int32 rank-compact before calling)
    seed_flat = seeds.astype(jnp.int32).reshape(-1)
    labels = seed_flat[parent]
    labels = jnp.where(mask.reshape(-1), labels, 0)

    # fill voxels the descent stage left unlabeled (plateaus, spurious
    # non-seed minima) with a QUANTIZED PRIORITY FLOOD — the vigra
    # watershedsNew ordering: heights are binned into L levels processed in
    # ascending order; at each level, only voxels at-or-below the water
    # level may adopt (from their lowest labeled neighbor), iterated to
    # stability before the level rises.  A label can therefore only cross a
    # saddle once the flood REACHES the saddle's level, by which time every
    # basin below it has been claimed by its own seed — the unordered
    # step-count race freely leaked labels across ridges into late-claimed
    # pockets (fragment purity ~0.7 on CREMI-like geometry).
    n_levels = 256
    hg = jnp.where(mask, height, big)
    finite = jnp.where(mask, height, -big)
    h_lo = jnp.where(mask, height, big).min()
    h_hi = finite.max()
    hq = jnp.clip(((hg - h_lo) / jnp.maximum(h_hi - h_lo, 1e-6)
                   * (n_levels - 1)).astype(jnp.int32), 0, n_levels - 1)
    hq = jnp.where(mask, hq, n_levels)

    def lowest_labeled_neighbor(lab_g):
        nbr_h = jnp.full(shape, big)
        nbr_l = jnp.zeros(shape, jnp.int32)
        for off in offsets:
            oh = _shifted(hg, off, big)
            ol = _shifted(lab_g, off, jnp.int32(0))
            cand = (ol > 0) & (oh < nbr_h)
            nbr_h = jnp.where(cand, oh, nbr_h)
            nbr_l = jnp.where(cand, ol, nbr_l)
        return nbr_l

    def flood_body(state):
        lab, level, it = state
        lab_g = lab.reshape(shape)
        nbr_l = lowest_labeled_neighbor(lab_g)
        adopt = (lab_g == 0) & mask & (nbr_l > 0) & (hq <= level)
        new = jnp.where(adopt, nbr_l, lab_g).reshape(-1)
        changed = jnp.any(new != lab)
        # stable at this water level -> jump straight to the lowest level
        # present on the frontier (skipping empty levels costs nothing and
        # saves hundreds of no-op sweeps)
        frontier = (lab_g == 0) & mask & (nbr_l > 0)
        next_level = jnp.min(jnp.where(frontier, hq, n_levels))
        level = jnp.where(changed, level,
                          jnp.maximum(level + 1, next_level))
        return new, level, it + 1

    def flood_cond(state):
        lab, level, it = state
        return (level < n_levels) & (it < max_iter + n_levels)

    labels, _, _ = jax.lax.while_loop(
        flood_cond, flood_body, (labels, jnp.int32(0), jnp.int32(0)))

    # backstop ONLY: the flood converges exactly (its frontier empties), so
    # this unordered sweep does work solely if the flood's iteration bound
    # (max_iter + n_levels) was hit early on a pathological instance —
    # labelable voxels then still get claimed, arbitrary-side like any tie
    def fill_body(state):
        lab, _, it = state
        lab_g = lab.reshape(shape)
        nbr_l = lowest_labeled_neighbor(lab_g)
        adopt = (lab_g == 0) & mask & (nbr_l > 0)
        new = jnp.where(adopt, nbr_l, lab_g).reshape(-1)
        return new, jnp.any(new != lab), it + 1

    labels, _, _ = jax.lax.while_loop(
        lambda s: s[1] & (s[2] < max_iter), fill_body,
        (labels, jnp.bool_(True), jnp.int32(0)))
    return labels.reshape(shape)


def seeded_watershed_basins(
    height: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    connectivity: int = 1,
    max_rounds: int = 64,
    min_size: int = 0,
) -> jnp.ndarray:
    """Seeded watershed via BASIN MERGING (watershed cuts, Cousty et al.):
    the parallel-native formulation that replaces the level-ordered flood.

    1. Steepest-descent forest with lexicographic (height, index) plateau
       tie-breaking; pointer jumping resolves every voxel to a root in
       O(log depth) gathers.  Plateau pockets simply become extra basins.
    2. Seeds are forced below everything, so seed clusters self-root and
       label their basins.  Basin ids are made DENSE with a scatter-free
       rank (a root is ``root[v] == v``, so presence + cumsum suffice) —
       all per-round state then lives in small basin-space arrays.
    3. Boruvka rounds: every UNLABELED basin group attaches to the
       neighbor group across its LOWEST SADDLE (min over boundary voxel
       pairs of max(h[u], h[v]) — the height at which rising water first
       overflows), 2-cycles broken toward the lower group id, pointer
       jumping over the BASIN forest (thousands of entries, not millions);
       repeat until no unlabeled group has a neighbor.  The only
       voxel-space work per round is the 6-neighbor stencil + a
       collision-free compaction of the boundary candidates.
    4. ``min_size`` fuses the size filter: after convergence, fragments
       below the threshold are stripped of their labels and the merge
       rounds continue, re-attaching them across their lowest saddles —
       replacing the full regrow pass (another watershed) with ~2 extra
       cheap rounds.

    Capacity handling: the basin/candidate tables are sized for natural
    volumes (n/64 basins, n/8 boundary candidates); the program counts the
    actual demand, and the host wrapper transparently re-runs with exact
    worst-case capacities (n/2 basins, n candidates) when a check trips —
    correctness never depends on the tight caps (adversarial random
    heights exceed them; smoothed EM boundary maps never do).

    Labels cross saddles in flood order like a priority flood; exact voxel
    assignments on plateaus and at equidistant fronts differ from the
    sequential flood — the same class of divergence vigra and scipy
    already show against each other.
    """
    n = int(np.prod(height.shape))
    if isinstance(height, jax.core.Tracer) or isinstance(seeds,
                                                         jax.core.Tracer):
        # inside another trace (vmap/jit callers) the overflow re-run
        # cannot branch on the flag — use the always-correct capacities;
        # hot paths that need the tight caps call _basins_impl directly
        # and handle the flag themselves (workflows/watershed.py pipeline)
        labels, _ = _basins_impl(height, seeds, mask, connectivity,
                                 max_rounds, min_size, n // 2 + 2, n)
        return labels
    labels, ok = _basins_impl(height, seeds, mask, connectivity, max_rounds,
                              min_size, max(n // 64, 1024),
                              max(n // 8, 4096))
    if bool(ok):
        return labels
    labels, _ = _basins_impl(height, seeds, mask, connectivity, max_rounds,
                             min_size, n // 2 + 2, n)
    return labels


@partial(jax.jit, static_argnames=("connectivity", "max_rounds", "min_size",
                                   "b_cap", "k_cap"))
def _basins_impl(height, seeds, mask, connectivity: int, max_rounds: int,
                 min_size: int, b_cap: int, k_cap: int):
    shape = height.shape
    n = int(np.prod(shape))
    height = height.astype(jnp.float32)
    if mask is None:
        mask = jnp.ones(shape, bool)
    else:
        mask = mask.astype(bool)
    offsets = _flat_offsets(shape, connectivity)
    big = jnp.float32(np.finfo(np.float32).max)

    h = jnp.where(mask, height, big)
    seeded = (seeds > 0) & mask
    h = jnp.where(seeded, -big, h)
    flat_idx = jnp.arange(n, dtype=jnp.int32).reshape(shape)

    # steepest-descent pointer: lexicographic min over (h, idx) of self+nbrs.
    # A seeded voxel may only point within its own seed cluster — without
    # this, ADJACENT clusters with different ids (dense seeds, e.g. the
    # size-filter regrow) would chain into one root and merge labels.
    # ctt-lint: disable=dtype-int32 (caller contract: seeds are block-local compacted ids, see seeded_watershed_flood)
    sv = seeds.astype(jnp.int32)
    best_h, best_i = h, flat_idx
    for off in offsets:
        nh = _shifted(h, off, big)
        ni = _shifted(flat_idx, off, jnp.int32(n))
        ns = _shifted(sv, off, jnp.int32(0))
        allowed = ~(seeded & (ns != sv))
        better = allowed & ((nh < best_h) | ((nh == best_h) & (ni < best_i)))
        best_h = jnp.where(better, nh, best_h)
        best_i = jnp.where(better, ni, best_i)
    parent = jnp.where(mask, best_i, flat_idx).reshape(-1)

    def jump(p, bound=64):
        def body(state):
            p, _, it = state
            p2 = p[p]
            return p2, jnp.any(p2 != p), it + 1

        p, _, _ = jax.lax.while_loop(
            lambda s: s[1] & (s[2] < bound), body,
            (p, jnp.bool_(True), jnp.int32(0)))
        return p

    root = jump(parent)

    # ctt-lint: disable=dtype-int32 (caller contract: seeds are block-local compacted ids, see seeded_watershed_flood)
    seed_flat = seeds.astype(jnp.int32).reshape(-1)
    mask_flat = mask.reshape(-1)
    h_flat = jnp.where(mask, height, big).reshape(-1)
    idx = jnp.arange(n, dtype=jnp.int32)

    # dense basin ids WITHOUT scatters: a root is root[v] == v
    is_root = (root == idx) & mask_flat
    rank = jnp.cumsum(is_root.astype(jnp.int32)) - 1
    n_basins = jnp.where(n > 0, rank[-1] + 1, 0)
    basin_of = jnp.where(rank[root] < b_cap, rank[root], b_cap)  # (n,)
    # per-basin label: scatter at root voxels only; non-roots go OUT OF
    # BOUNDS (mode='drop') — an in-bounds dump slot would serialize
    # millions of colliding writes on TPU
    basin_label0 = jnp.zeros((b_cap + 1,), jnp.int32).at[
        jnp.where(is_root, basin_of, b_cap + 2)].set(
        jnp.where(is_root, seed_flat, 0), mode="drop")

    basin_grid = basin_of.reshape(shape)
    h_grid = h_flat.reshape(shape)

    def boruvka_round(state):
        bparent, blabel, _, it, ok = state
        # group resolution in BASIN space (tiny)
        group = jump(bparent)
        glab = blabel[group]
        # ONE voxel-space gather for (group, labeled?): the 19M random
        # gathers dominate the round cost on TPU (~80 ms each), so group
        # and label-state ride one packed code
        code = group * 2 + (glab > 0).astype(jnp.int32)
        vcode = code[basin_of]
        vg = vcode >> 1
        vlab = vcode & 1
        vg_grid = vg.reshape(shape)

        # voxel-space stencil: best (saddle, neighbor group) per voxel
        sad = jnp.full((n,), big)
        nbr = jnp.full((n,), jnp.int32(b_cap))
        for off in offsets:
            oh = _shifted(h_grid, off, big).reshape(-1)
            og = _shifted(vg_grid, off, jnp.int32(b_cap)).reshape(-1)
            s = jnp.maximum(h_flat, oh)
            valid = (og != vg) & (og < b_cap) & (s < big) & mask_flat
            bet = valid & ((s < sad) | ((s == sad) & (og < nbr)))
            sad = jnp.where(bet, s, sad)
            nbr = jnp.where(bet, og, nbr)
        cand = (vlab == 0) & mask_flat & (nbr < b_cap)
        # collision-free compaction of candidates to k_cap slots; demand
        # beyond the cap trips the ok flag and the host wrapper re-runs
        # with exact worst-case capacities
        ctgt = jnp.cumsum(cand.astype(jnp.int32)) - 1
        ok = ok & (jnp.where(n > 0, ctgt[-1] + 1, 0) <= k_cap)
        # invalid entries scatter OUT OF BOUNDS (mode='drop'): an in-bounds
        # dump slot would serialize millions of colliding writes on TPU
        ctgt = jnp.where(cand & (ctgt < k_cap), ctgt, k_cap + 2)
        cg = jnp.full((k_cap + 1,), b_cap, jnp.int32).at[ctgt].set(
            vg, mode="drop")[:k_cap]
        cs = jnp.full((k_cap + 1,), big).at[ctgt].set(sad,
                                                     mode="drop")[:k_cap]
        cn = jnp.full((k_cap + 1,), b_cap, jnp.int32).at[ctgt].set(
            nbr, mode="drop")[:k_cap]
        # basin-space segment mins over the compacted candidates
        smin = jax.ops.segment_min(cs, cg, num_segments=b_cap + 1)
        at_min = (cs == smin[cg]) & (cs < big)
        attach = jax.ops.segment_min(
            jnp.where(at_min, cn, jnp.int32(b_cap)), cg,
            num_segments=b_cap + 1)[:b_cap + 1]
        gidx = jnp.arange(b_cap + 1, dtype=jnp.int32)
        attach = jnp.where(attach < b_cap, attach, gidx)
        attach = jnp.where(blabel > 0, gidx, attach)   # labeled absorb
        # break 2-cycles toward the lower group id
        attach2 = attach[attach]
        attach = jnp.where((attach2 == gidx) & (attach > gidx), gidx,
                           attach)
        # every basin points at its root's attach target: one step of
        # Boruvka + full path compression in one gather
        new_parent = attach[group]
        changed = jnp.any(new_parent != bparent)
        return new_parent, blabel, changed, it + 1, ok

    ok0 = n_basins <= b_cap
    bparent0 = jnp.arange(b_cap + 1, dtype=jnp.int32)
    bparent, blabel, _, _, ok = jax.lax.while_loop(
        lambda s: s[2] & (s[3] < max_rounds), boruvka_round,
        (bparent0, basin_label0, jnp.bool_(True), jnp.int32(0), ok0))

    if min_size:
        # fused size filter: strip labels of too-small fragments, keep
        # merging — small fragments re-attach across their lowest saddles
        group = jump(bparent)
        sizes = jax.ops.segment_sum(
            jnp.where(mask_flat, 1, 0), group[basin_of],
            num_segments=b_cap + 1)
        small = (sizes < min_size) & (sizes > 0)
        # every basin takes its group root's label, then small fragments
        # are stripped back to unlabeled and keep merging
        blabel = jnp.where(small[group], 0, blabel[group])
        bparent, blabel, _, _, ok = jax.lax.while_loop(
            lambda s: s[2] & (s[3] < max_rounds), boruvka_round,
            (bparent, blabel, jnp.bool_(True), jnp.int32(0), ok))

    group = jump(bparent)
    labels = blabel[group][basin_of]
    labels = jnp.where(mask_flat, labels, 0)
    return labels.reshape(shape), ok


def _coarse_impl(height, seeds, min_size: int, refine_rounds: int,
                 factor: int = 2, dense_ids: bool = False):
    """Jit-composable ``factor``x-coarse basin watershed: mean-pool the
    height, max-pool the seeds, run the descent-forest + saddle-merge
    solve (`_basins_impl`) on the factor^3-smaller grid — every
    gather/scatter/cumsum primitive shrinks with it (measured 5.9 s at
    full res -> 0.82 s at 2x -> 0.19 s at 4x per [58,576,576] block) —
    then upsample and snap boundaries back at full resolution with
    ``refine_rounds`` steepest-descent adoption sweeps (pure stencils,
    ~0.11 s regardless of round count).  Stays in the flood's divergence
    class (VI ~0.15 vs the bucket-queue flood; scan-only formulations
    measured ~0.6, ops/sweep.py).  Short dims are edge-padded to a
    multiple of ``factor`` for the pooling and cropped back.
    ``min_size`` is in FULL-resolution voxels."""
    from .components import _shifted

    shape = height.shape
    f = int(factor)
    pads = tuple((0, (f - s % f) % f) for s in shape)
    if any(p[1] for p in pads):
        height_p = jnp.pad(height, pads, mode="edge")
        seeds_p = jnp.pad(seeds, pads)
    else:
        height_p, seeds_p = height, seeds
    cshape = tuple(s // f for s in height_p.shape)
    cn = int(np.prod(cshape))
    hc = height_p.reshape(cshape[0], f, cshape[1], f,
                          cshape[2], f).mean((1, 3, 5))
    sc = seeds_p.reshape(cshape[0], f, cshape[1], f,
                         cshape[2], f).max((1, 3, 5))
    wsc, ok = _basins_impl(hc, sc, None, 1, 64,
                           max(min_size // (f ** 3), 1),
                           min(max(cn // 8, 4096), cn // 2 + 2),
                           min(max(cn // 2, 16384), cn))
    if dense_ids:
        # dense-rank the label VALUES on the coarse grid (labels out of
        # the basin solve are full-res seed root indices, bounded only by
        # the voxel count): sort + binary search at coarse scale is ~free
        # and shrinks every downstream id table from n_outer to cn
        # entries (the fused program's per-block relabel cumsum was 18%
        # of its device time at the full-res bound).  Ids stay
        # partition-equivalent (first-occurrence-in-sorted-order rank).
        flatc = wsc.reshape(-1)
        s = jnp.sort(flatc)
        is_new = jnp.concatenate([(s[:1] > 0),
                                  (s[1:] != s[:-1]) & (s[1:] > 0)])
        rank = jnp.cumsum(is_new.astype(jnp.int32))
        pos = jnp.searchsorted(s, flatc)
        wsc = jnp.where(flatc > 0, rank[pos], 0).reshape(wsc.shape)
    ws = jnp.repeat(jnp.repeat(jnp.repeat(wsc, f, 0), f, 1), f, 2)
    ws = ws[tuple(slice(0, s) for s in shape)]

    big = jnp.float32(3.4e38)

    def refine(w, _):
        best_h, best_l = height, w
        for off in ((1, 0, 0), (-1, 0, 0), (0, 1, 0),
                    (0, -1, 0), (0, 0, 1), (0, 0, -1)):
            nh = _shifted(height, off, big)
            nl = _shifted(w, off, jnp.int32(0))
            better = (nh < best_h) & (nl > 0)
            best_h = jnp.where(better, nh, best_h)
            best_l = jnp.where(better, nl, best_l)
        return best_l, 0

    ws, _ = jax.lax.scan(refine, ws, None, length=refine_rounds)
    return ws, ok


def seeded_watershed_coarse(height, seeds, mask=None, connectivity: int = 1,
                            min_size: int = 0, refine_rounds: int = 3,
                            factor: int = 2):
    """Host-facing wrapper around :func:`_coarse_impl` (3d, maskless —
    masked callers use the full-resolution methods)."""
    if mask is not None:
        raise ValueError("coarse watershed does not support masks; use "
                         "method='basins'")
    if connectivity != 1:
        raise ValueError("coarse watershed refines along faces "
                         "(connectivity=1)")
    height = jnp.asarray(height).astype(jnp.float32)
    labels, ok = _coarse_jit(height, jnp.asarray(seeds), int(min_size),
                             int(refine_rounds), int(factor))
    return labels, bool(ok)


@partial(jax.jit, static_argnames=("min_size", "refine_rounds", "factor"))
def _coarse_jit(height, seeds, min_size: int, refine_rounds: int,
                factor: int = 2):
    return _coarse_impl(height, seeds, min_size, refine_rounds, factor)


@partial(jax.jit, static_argnames=("connectivity", "method"))
def _batched_impl(heights, seeds, masks, connectivity: int, method: str):
    def one(h, s, m):
        return seeded_watershed(h, s, m, connectivity, method=method)

    if masks is None:
        return jax.vmap(lambda h, s: one(h, s, None))(heights, seeds)
    return jax.vmap(one)(heights, seeds, masks)


def seeded_watershed_batched(
    heights: jnp.ndarray, seeds: jnp.ndarray, masks: Optional[jnp.ndarray] = None,
    connectivity: int = 1, method: Optional[str] = None,
) -> jnp.ndarray:
    """Per-slice (vmapped) seeded watershed.  The method is resolved OUTSIDE
    the jit (env override takes effect per call, not per trace)."""
    import os

    method = method or os.environ.get("CTT_WS_METHOD", "basins")
    return _batched_impl(heights, seeds, masks, connectivity, method)


def size_filter(
    labels: np.ndarray, height: np.ndarray, size_threshold: int,
    mask: Optional[np.ndarray] = None, connectivity: int = 1,
    per_slice: bool = False,
) -> np.ndarray:
    """Remove fragments smaller than ``size_threshold`` and regrow the
    remaining seeds over the height map (reference:
    utils/volume_utils.py:123-139 watershed-and-size-filter).  Host-side
    counting + one device watershed pass.  ``per_slice`` regrows each z-slice
    independently (2d watershed mode)."""
    labels = np.asarray(labels)
    flat = labels.ravel()
    uniques, inverse, counts = np.unique(flat, return_inverse=True,
                                         return_counts=True)
    small = (counts < size_threshold) & (uniques != 0)
    if not small.any():
        return labels
    keep = np.where(small[inverse], 0, flat).reshape(labels.shape)
    # regrown labels must fit the watershed's int32 seed ids: compact first,
    # restore original ids after
    nz = uniques[(uniques != 0) & ~small]
    seed_ids = np.searchsorted(nz, keep).astype("int32") + 1
    seed_ids[keep == 0] = 0
    if per_slice:
        out = seeded_watershed_batched(
            jnp.asarray(height), jnp.asarray(seed_ids),
            None if mask is None else jnp.asarray(mask),
            connectivity=connectivity)
    else:
        out = seeded_watershed(
            jnp.asarray(height), jnp.asarray(seed_ids),
            None if mask is None else jnp.asarray(mask),
            connectivity=connectivity)
    out = np.asarray(out)
    restored = np.zeros(out.shape, dtype=labels.dtype)
    fg = out > 0
    restored[fg] = nz[out[fg] - 1]
    return restored
