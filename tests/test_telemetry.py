"""Structured span tracing (core/telemetry.py) — ISSUE 15.

Tier-1 coverage for the span recorder (thread safety, ring bound,
parent/child nesting, off-by-default zero-recording), the Chrome
trace-event exporter (schema, nesting, fixed-clock determinism), the
span-derived rollups (device-busy, bubble fraction, queue-wait
histograms), the Prometheus writer, the stage-name registry lint, the
runtime instrumentation (stage_add span emission with bit-identical
accumulators, BoundedPool queue-wait spans, attempt spans + correlation
ids across retries), and the telemetry-off overhead gate.  No XLA
compiles anywhere (PR 13 conftest pattern).
"""

import json
import os
import re
import threading
import time

import pytest

from cluster_tools_tpu.core import runtime, telemetry
from cluster_tools_tpu.core.config import ConfigDir

from test_runtime import FailingTask, FillTask


class FakeClock:
    """Deterministic fixed-step clock for byte-identical trace exports."""

    def __init__(self, step=0.001):
        self.t = 0.0
        self.step = step

    def __call__(self):
        self.t += self.step
        return self.t


@pytest.fixture()
def fake_clock():
    clk = FakeClock()
    telemetry.configure(enabled=True, clock=clk)
    return clk


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------

def test_disabled_records_nothing():
    """Off by default: spans, stage hooks and context managers are all
    no-ops, and the disabled span context is a shared singleton (the
    off-path allocates nothing)."""
    assert not telemetry.enabled()
    telemetry.record("x", 0.0, 1.0)
    telemetry.record_stage("sync-execute", 1.0)
    ctx = telemetry.span("x")
    with ctx:
        runtime.stage_add("host-map", 1.0)
    assert ctx is telemetry.span("y")        # shared null span
    assert telemetry.spans_snapshot() == []


def test_span_nesting_and_parents(fake_clock):
    """task -> job -> block -> stage: children link to the innermost
    enclosing span on the same thread, both for `span` contexts and for
    post-hoc `record`/`record_stage` calls."""
    with telemetry.span("t", cat="task") as t:
        with telemetry.span("j", cat="job") as j:
            with telemetry.span("b", cat="block") as b:
                telemetry.record_stage("sync-execute", 0.5)
            telemetry.record("d2h-dense", 1.0, 2.0)
    spans = {s.name: s for s in telemetry.spans_snapshot()}
    assert spans["t"].parent is None
    assert spans["j"].parent == t.sid
    assert spans["b"].parent == j.sid
    assert spans["sync-execute"].parent == b.sid
    assert spans["d2h-dense"].parent == j.sid     # block already closed
    # durations are monotone and nested
    assert spans["t"].t0 < spans["j"].t0 < spans["b"].t0
    assert spans["b"].t1 < spans["j"].t1 < spans["t"].t1


def test_ring_bound_and_dropped_count(fake_clock):
    telemetry.configure(ring_size=8)
    for i in range(20):
        telemetry.record("host-map", float(i), float(i) + 0.5)
    spans = telemetry.spans_snapshot()
    assert len(spans) == 8
    # newest survive, oldest dropped
    assert [s.t0 for s in spans] == [float(i) for i in range(12, 20)]
    assert telemetry.dropped_count() == 12


def test_recorder_thread_safety(fake_clock):
    """8 threads recording concurrently: no lost spans, unique sids."""
    n_threads, n_iter = 8, 200
    barrier = threading.Barrier(n_threads)

    def hammer():
        barrier.wait()
        for _ in range(n_iter):
            with telemetry.span("host-map", cat="stage"):
                pass

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    spans = telemetry.spans_snapshot()
    assert len(spans) == n_threads * n_iter
    assert len({s.sid for s in spans}) == len(spans)


# ---------------------------------------------------------------------------
# Chrome trace exporter
# ---------------------------------------------------------------------------

def _record_fixture_trace():
    with telemetry.span("fill_j0", cat="job", job_id=0):
        with telemetry.span("block:0", cat="block", block=0):
            telemetry.record_stage("sync-execute", 0.002)
        with telemetry.span("block:1", cat="block", block=1):
            telemetry.record_stage("d2h-dense", 0.001)


def test_chrome_trace_schema(fake_clock, tmp_path):
    """Exported JSON is the trace-event object format Perfetto accepts:
    a traceEvents list of complete 'X' events with name/ph/ts/dur/pid/
    tid, plus 'M' process/thread metadata."""
    _record_fixture_trace()
    path = str(tmp_path / "trace.json")
    n = telemetry.export_chrome_trace(path)
    with open(path) as f:
        doc = json.load(f)
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    events = doc["traceEvents"]
    assert len(events) == n
    xs = [e for e in events if e["ph"] == "X"]
    ms = [e for e in events if e["ph"] == "M"]
    assert len(xs) == 5 and ms, events
    assert any(e["name"] == "process_name" for e in ms)
    assert any(e["name"] == "thread_name" for e in ms)
    for e in xs:
        for key in ("name", "cat", "ph", "ts", "dur", "pid", "tid"):
            assert key in e, (key, e)
        assert e["ts"] >= 0 and e["dur"] >= 0
        assert isinstance(e["tid"], int) and e["tid"] >= 1


def test_chrome_trace_nesting(fake_clock, tmp_path):
    """Block events sit time-nested inside their job event and carry the
    parent sid in args (the hierarchy survives the flat event list)."""
    _record_fixture_trace()
    path = str(tmp_path / "trace.json")
    telemetry.export_chrome_trace(path)
    with open(path) as f:
        xs = [e for e in json.load(f)["traceEvents"] if e["ph"] == "X"]
    by_name = {e["name"]: e for e in xs}
    job = by_name["fill_j0"]
    for bname in ("block:0", "block:1"):
        blk = by_name[bname]
        assert blk["args"]["parent"] == job["args"]["sid"]
        assert blk["ts"] >= job["ts"]
        assert blk["ts"] + blk["dur"] <= job["ts"] + job["dur"]
    stg = by_name["sync-execute"]
    assert stg["args"]["parent"] == by_name["block:0"]["args"]["sid"]


def test_chrome_trace_deterministic_under_fixed_clock(tmp_path):
    """Identical recordings under an injected fixed clock export
    byte-identical files (dense tid remap, pinned pid, sorted keys)."""
    outs = []
    for i in range(2):
        telemetry.reset()
        telemetry.configure(enabled=True, clock=FakeClock())
        _record_fixture_trace()
        path = str(tmp_path / f"trace_{i}.json")
        telemetry.export_chrome_trace(path)
        with open(path, "rb") as f:
            outs.append(f.read())
    assert outs[0] == outs[1]


# ---------------------------------------------------------------------------
# rollups
# ---------------------------------------------------------------------------

def test_rollups_exact_on_known_intervals(fake_clock):
    """Device-busy (sum AND merged-timeline), bubble fraction and the
    queue-wait histogram against hand-checkable interval arithmetic."""
    telemetry.record("sync-execute", 0.0, 1.0)
    telemetry.record("d2h-dense", 0.5, 1.5)       # overlaps the first
    telemetry.record("host-map", 0.0, 3.0)        # host: never busy time
    telemetry.record("wait-a", 0.0, 0.005, cat="queue-wait")
    telemetry.record("wait-b", 0.0, 0.05, cat="queue-wait")
    spans = telemetry.spans_snapshot()
    assert telemetry.device_busy_seconds(spans) == pytest.approx(2.0)
    assert telemetry.busy_timeline(spans) == [(0.0, 1.5)]
    # SUM semantics (matches the device_busy_frac accumulator)
    assert telemetry.device_busy_fraction(4.0, spans) == \
        pytest.approx(0.5)
    # merged-timeline semantics: 1 - 1.5/3 of the window has no device
    # stage active
    assert telemetry.pipeline_bubble_fraction(spans, wall=3.0) == \
        pytest.approx(0.5)
    hist = telemetry.queue_wait_histogram(
        bins=(0.01, 0.1), spans=spans)
    assert hist["count"] == 2
    assert hist["sum"] == pytest.approx(0.055)
    assert hist["buckets"]["0.01"] == 1
    assert hist["buckets"]["0.1"] == 2
    assert hist["buckets"]["+Inf"] == 2
    summ = telemetry.summary(wall=4.0)
    assert summ["device_busy_s"] == pytest.approx(2.0)
    assert summ["device_busy_frac"] == pytest.approx(0.5)
    assert summ["by_cat"]["queue-wait"] == 2


def test_device_busy_crosschecks_accumulator(fake_clock):
    """The span view and the flat accumulator are fed by the SAME
    stage_add calls — their device-busy sums must agree (the acceptance
    bound is 5%; in-process they agree to float precision)."""
    st0 = runtime.stages_snapshot()
    for sec in (0.25, 0.5, 0.125):
        runtime.stage_add("sync-execute", sec)
    runtime.stage_add("h2d-upload", 0.1)
    runtime.stage_add("host-map", 9.0)            # must NOT count
    acc_busy = sum(v for k, v in runtime.stages_delta(st0).items()
                   if k.startswith(telemetry.DEVICE_STAGE_PREFIXES))
    span_busy = telemetry.device_busy_seconds()
    assert span_busy == pytest.approx(acc_busy, rel=1e-6)


# ---------------------------------------------------------------------------
# runtime instrumentation
# ---------------------------------------------------------------------------

def test_stage_add_emits_spans_and_preserves_counts(fake_clock):
    """Every stage accumulation doubles as a span WITHOUT touching the
    accumulators: deltas are identical to a telemetry-off run of the
    same calls."""
    cn0 = runtime.counts_snapshot()
    st0 = runtime.stages_snapshot()
    runtime.stage_add("sync-execute", 0.5, 3)
    with runtime.stage("host-map"):
        pass
    on_counts = runtime.counts_delta(cn0)
    on_stages = runtime.stages_delta(st0)
    spans = telemetry.spans_snapshot()
    assert [s.name for s in spans] == ["sync-execute", "host-map"]
    assert spans[0].t1 - spans[0].t0 == pytest.approx(0.5)
    assert spans[0].attrs["count"] == 3

    telemetry.configure(enabled=False)
    cn1 = runtime.counts_snapshot()
    runtime.stage_add("sync-execute", 0.5, 3)
    with runtime.stage("host-map"):
        pass
    assert runtime.counts_delta(cn1) == on_counts == \
        {"sync-execute": 3, "host-map": 1}
    assert len(telemetry.spans_snapshot()) == 2   # nothing new recorded
    assert on_stages["sync-execute"] == pytest.approx(0.5)


def test_timed_stage_alias():
    assert runtime.timed_stage is runtime.stage


def test_bounded_pool_spans(fake_clock):
    """Pool submissions record a submit->start queue-wait span and a
    worker-side execution span; inline mode (max_workers=0) records
    nothing extra."""
    done = []
    with runtime.BoundedPool(2) as pool:
        for i in range(4):
            pool.submit(done.append, i)
    spans = telemetry.spans_snapshot()
    waits = [s for s in spans if s.cat == "queue-wait"]
    execs = [s for s in spans if s.cat == "pool"]
    assert sorted(done) == [0, 1, 2, 3]
    assert len(waits) == 4 and len(execs) == 4
    assert all(s.name == "pool-queue-wait" for s in waits)
    assert all(s.name == "pool:append" for s in execs)
    assert telemetry.queue_wait_histogram()["count"] == 4

    n0 = len(telemetry.spans_snapshot())
    with runtime.BoundedPool(0) as pool:          # inline reference mode
        pool.submit(done.append, 99)
    assert len(telemetry.spans_snapshot()) == n0


def test_global_config_arms_telemetry(tmp_path):
    """telemetry_enabled/telemetry_ring_size in the global config arm the
    recorder at task construction (the workflow-level opt-in, mirroring
    exec_cache_dir)."""
    config_dir = str(tmp_path / "configs")
    ConfigDir(config_dir).write_global_config(
        {"block_shape": [10, 10, 10], "telemetry_enabled": True,
         "telemetry_ring_size": 128})
    assert not telemetry.enabled()
    FillTask(output_path=str(tmp_path / "o.n5"), output_key="d",
             shape=(10, 10, 10), tmp_folder=str(tmp_path / "tmp"),
             config_dir=config_dir, max_jobs=1, target="inline")
    assert telemetry.enabled()
    telemetry.record("host-map", 0.0, 1.0)
    assert len(telemetry.spans_snapshot()) == 1


def test_attempt_spans_and_correlation_id_across_retries(tmp_path):
    """Block-granular retry: every attempt emits a span carrying the
    SAME correlation id and its attempt number, and the status JSON
    carries the id too (trace <-> status join key)."""
    config_dir = str(tmp_path / "configs")
    ConfigDir(config_dir).write_global_config(
        {"block_shape": [10, 10, 10], "max_num_retries": 2,
         "telemetry_enabled": True})
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir)
    out = str(tmp_path / "out.n5")
    task = FailingTask(output_path=out, output_key="data",
                       shape=(20, 20, 20), tmp_folder=str(tmp_path / "t"),
                       config_dir=config_dir, max_jobs=4,
                       target="threads")
    orig = task.run_jobs

    def run_jobs(block_list, cfg, **kw):
        return orig(block_list, {**cfg, "marker_dir": marker_dir}, **kw)

    task.run_jobs = run_jobs
    task.run()
    attempts = [s for s in telemetry.spans_snapshot()
                if s.cat == "attempt"]
    # first run + at least one retry (odd blocks queued BEHIND a failing
    # block only get their marker on the next attempt, so the cascade
    # may take 2 retries); attempt numbers are contiguous from 0
    assert len(attempts) >= 2
    assert sorted(s.attrs["attempt"] for s in attempts) == \
        list(range(len(attempts)))
    corr = {s.attrs["correlation_id"] for s in attempts}
    assert len(corr) == 1 and corr != {""}
    with open(task.output().path) as f:
        status = json.load(f)
    assert status["correlation_id"] == corr.pop()
    assert status["retries"] == len(attempts) - 1
    # job spans run on executor WORKER threads (parenting is per-thread,
    # so they have no parent sid) but are time-nested within an attempt
    jobs = [s for s in telemetry.spans_snapshot() if s.cat == "job"]
    assert jobs
    for j in jobs:
        assert any(a.t0 <= j.t0 and j.t1 <= a.t1 for a in attempts), j


def test_metrics_path_writes_prometheus_snapshot(tmp_path):
    """The metrics_path global-config key makes every status write drop a
    Prometheus snapshot of the runtime counters."""
    mp = str(tmp_path / "task_metrics.prom")
    config_dir = str(tmp_path / "configs")
    ConfigDir(config_dir).write_global_config(
        {"block_shape": [10, 10, 10], "metrics_path": mp})
    task = FillTask(output_path=str(tmp_path / "o.n5"), output_key="d",
                    shape=(10, 10, 10), tmp_folder=str(tmp_path / "tmp"),
                    config_dir=config_dir, max_jobs=1, target="inline")
    task.run()
    assert os.path.exists(mp)
    text = open(mp).read()
    assert "# TYPE ctt_stage_seconds_total counter" in text
    assert "# TYPE ctt_exec_cache_hit_ratio gauge" in text


# ---------------------------------------------------------------------------
# stage-name registry lint (satellite: typo'd stage buckets currently
# vanish silently into stage_counts)
# ---------------------------------------------------------------------------

def test_stage_literals_are_registered():
    """Thin shim (ISSUE 18): the PR-15 grep lint now lives in the
    unified ctt-lint runner as a real AST pass (analysis.registry),
    which additionally catches f-string/concatenated stage names the
    grep structurally could not.  Same test id, same guarantee."""
    from cluster_tools_tpu import analysis
    from cluster_tools_tpu.analysis import registry as areg

    report = analysis.run_analysis(passes=[areg.STAGE_PASS])
    bad = [f.format() for f in report["findings"]
           if f.rule == "stage-registry"]
    assert not bad, "\n".join(bad)
    # the canonical buckets the bench/docs rely on must actually be used
    src = "\n".join(open(p).read()
                    for p in analysis.sources.source_files())
    for name in ("sync-execute", "sync-compile", "store-write"):
        assert f'"{name}"' in src


def test_register_stage_extension():
    assert not telemetry.is_registered("ext-custom")
    try:
        telemetry.register_stage("ext-custom")
        assert telemetry.is_registered("ext-custom")
    finally:
        telemetry.STAGE_REGISTRY.discard("ext-custom")


# ---------------------------------------------------------------------------
# Prometheus writer
# ---------------------------------------------------------------------------

def test_prometheus_writer_format(tmp_path):
    path = str(tmp_path / "m.prom")
    telemetry.write_prometheus(path, [
        ("ctt_queue_depth", "gauge", "Requests waiting", [(None, 3)]),
        ("ctt_in_flight", "gauge", "Per-tenant in flight",
         [({"tenant": "alice"}, 2), ({"tenant": 'bo"b'}, 1)]),
    ])
    lines = open(path).read().splitlines()
    assert lines[0] == "# HELP ctt_queue_depth Requests waiting"
    assert lines[1] == "# TYPE ctt_queue_depth gauge"
    assert lines[2] == "ctt_queue_depth 3"
    assert 'ctt_in_flight{tenant="alice"} 2' in lines
    assert 'ctt_in_flight{tenant="bo\\"b"} 1' in lines     # escaped


# ---------------------------------------------------------------------------
# cumulative-bucket histograms (ISSUE 16 tentpole 2)
# ---------------------------------------------------------------------------

def test_histogram_cumulative_buckets_and_quantiles():
    h = telemetry.Histogram((0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.5, 0.5, 5.0):
        h.observe(v)
    assert h.count == 5
    assert h.sum == pytest.approx(6.055)
    cum = h.cumulative()
    # Prometheus semantics: each le bucket counts ALL observations <= le
    assert cum == {"0.01": 1, "0.1": 2, "1.0": 4, "+Inf": 5}
    assert list(cum)[-1] == "+Inf"
    # monotone non-decreasing in le order
    vals = list(cum.values())
    assert vals == sorted(vals)
    assert h.quantile(0.0) <= h.quantile(0.5) <= h.quantile(0.99)
    # p50 falls in the (0.1, 1.0] bucket
    assert 0.1 <= h.quantile(0.5) <= 1.0
    # quantiles beyond the finite buckets clamp to the highest bound
    assert h.quantile(1.0) == pytest.approx(1.0)


def test_histogram_boundary_observation_is_inclusive():
    h = telemetry.Histogram((1.0,))
    h.observe(1.0)                  # le="1.0" must include exactly 1.0
    assert h.cumulative() == {"1.0": 1, "+Inf": 1}


def test_histogram_merge_and_copy():
    a = telemetry.Histogram((0.1, 1.0))
    b = telemetry.Histogram((0.1, 1.0))
    a.observe(0.05)
    b.observe(0.5)
    c = a.copy()
    c.merge(b)
    assert a.count == 1             # copy is independent
    assert c.count == 2
    assert c.cumulative() == {"0.1": 1, "1.0": 2, "+Inf": 2}
    with pytest.raises(ValueError):
        a.merge(telemetry.Histogram((0.5,)))


def test_histogram_to_samples_prometheus_invariants(tmp_path):
    h = telemetry.Histogram((0.1, 1.0))
    for v in (0.05, 0.5, 2.0):
        h.observe(v)
    samples = h.to_samples({"lane": "edit"})
    suffixes = [s[0] for s in samples]
    assert suffixes == ["_bucket", "_bucket", "_bucket", "_sum",
                        "_count"]
    les = [s[1]["le"] for s in samples[:3]]
    assert les == ["0.1", "1.0", "+Inf"]
    assert samples[2][2] == samples[4][2] == 3    # +Inf == _count
    # round-trip through the writer and the lint
    path = str(tmp_path / "h.prom")
    telemetry.write_prometheus(path, [telemetry.histogram_family(
        "ctt_server_request_latency_seconds", "Request latency",
        [({"lane": "edit"}, h)])])
    text = open(path).read()
    assert telemetry.lint_prometheus(text) == []
    assert 'ctt_server_request_latency_seconds_bucket' \
        '{lane="edit",le="+Inf"} 3' in text


# ---------------------------------------------------------------------------
# Prometheus text-format lint (promtool-style, satellite)
# ---------------------------------------------------------------------------

def _lint(text):
    return telemetry.lint_prometheus(text)


def test_lint_accepts_generated_snapshot(tmp_path):
    path = str(tmp_path / "ok.prom")
    h = telemetry.Histogram((0.5,))
    h.observe(0.1)
    telemetry.write_prometheus(path, [
        ("ctt_server_queue_depth", "gauge", "Depth", [(None, 2)]),
        ("ctt_server_in_flight", "gauge", "In flight",
         [({"tenant": 'a\\b"c'}, 1)]),            # escaping round-trips
        telemetry.histogram_family("ctt_server_queue_wait_seconds",
                                   "Wait", [(None, h)]),
    ] + telemetry.metrics_families())
    assert _lint(open(path).read()) == []


def test_lint_rejects_malformed_exposition():
    # sample with no TYPE
    assert _lint("ctt_x 1\n")
    # invalid metric name
    assert _lint("# TYPE 0bad gauge\n0bad 1\n")
    # invalid label syntax (unquoted value)
    assert _lint('# TYPE ctt_x gauge\nctt_x{l=a} 1\n')
    # bad escape in a label value
    assert _lint('# TYPE ctt_x gauge\nctt_x{l="a\\q"} 1\n')
    # non-float value
    assert _lint("# TYPE ctt_x gauge\nctt_x abc\n")
    # duplicate series
    assert _lint("# TYPE ctt_x gauge\nctt_x 1\nctt_x 2\n")
    # unknown TYPE
    assert _lint("# TYPE ctt_x wibble\nctt_x 1\n")


def test_lint_enforces_histogram_invariants():
    head = "# TYPE ctt_h histogram\n"
    # non-monotone cumulative buckets
    bad_mono = head + ('ctt_h_bucket{le="0.1"} 5\n'
                       'ctt_h_bucket{le="1.0"} 3\n'
                       'ctt_h_bucket{le="+Inf"} 5\n'
                       'ctt_h_sum 1\nctt_h_count 5\n')
    assert any("monoton" in e for e in _lint(bad_mono))
    # missing +Inf bucket
    bad_inf = head + ('ctt_h_bucket{le="0.1"} 1\n'
                      'ctt_h_sum 1\nctt_h_count 1\n')
    assert any("+Inf" in e for e in _lint(bad_inf))
    # +Inf disagrees with _count
    bad_count = head + ('ctt_h_bucket{le="+Inf"} 4\n'
                        'ctt_h_sum 1\nctt_h_count 5\n')
    assert any("_count" in e for e in _lint(bad_count))
    # missing _sum
    bad_sum = head + ('ctt_h_bucket{le="+Inf"} 1\nctt_h_count 1\n')
    assert any("_sum" in e for e in _lint(bad_sum))
    # a correct family passes
    good = head + ('ctt_h_bucket{le="0.1"} 1\n'
                   'ctt_h_bucket{le="+Inf"} 2\n'
                   'ctt_h_sum 0.3\nctt_h_count 2\n')
    assert _lint(good) == []


# ---------------------------------------------------------------------------
# metric-name registry lint (satellite: the stage-lint pattern extended
# to Prometheus family names)
# ---------------------------------------------------------------------------

def test_metric_literals_are_registered():
    """Thin shim (ISSUE 18): the PR-16 metric-name grep lint now lives
    in the unified ctt-lint runner as a real AST pass
    (analysis.registry), which additionally flags dynamic ``ctt_*``
    family names.  Same test id, same guarantee."""
    from cluster_tools_tpu import analysis
    from cluster_tools_tpu.analysis import registry as areg

    report = analysis.run_analysis(passes=[areg.METRIC_PASS])
    bad = [f.format() for f in report["findings"]
           if f.rule == "metric-registry"]
    assert not bad, "\n".join(bad)
    # the serve-path families PR 16 added must actually be emitted
    src = "\n".join(open(p).read()
                    for p in analysis.sources.source_files())
    for name in ("ctt_server_request_latency_seconds",
                 "ctt_slo_burn_rate",
                 "ctt_telemetry_dropped_spans_total"):
        assert f'"{name}"' in src


def test_dropped_span_counter_exported(fake_clock, tmp_path):
    """The ring's dropped-span count surfaces as a Prometheus counter
    (satellite: silent drops were invisible before)."""
    telemetry.configure(ring_size=4)
    for i in range(10):
        telemetry.record("host-map", float(i), float(i) + 0.5)
    path = str(tmp_path / "m.prom")
    telemetry.write_prometheus(path, telemetry.metrics_families())
    text = open(path).read()
    assert "# TYPE ctt_telemetry_dropped_spans_total counter" in text
    assert "ctt_telemetry_dropped_spans_total 6" in text
    assert "ctt_telemetry_ring_spans 4" in text
    assert _lint(text) == []


# ---------------------------------------------------------------------------
# trace-diff regression gate (ISSUE 16 tentpole 3)
# ---------------------------------------------------------------------------

_BASE_ROLLUPS = {
    "stage_seconds": {"sync-execute": 8.0, "h2d-upload": 0.6,
                      "host-solve": 2.0},
    "device_busy_s": 8.6,
    "pipeline_bubble_frac": 0.02,
}


def test_diff_rollups_pass_path():
    """Candidate within thresholds (including small improvements): no
    regressions, exit-0 path."""
    cand = {
        "stage_seconds": {"sync-execute": 8.2, "h2d-upload": 0.5,
                          "host-solve": 2.2},   # host +10%: warning only
        "device_busy_s": 8.7,
        "pipeline_bubble_frac": 0.03,
    }
    diff = telemetry.diff_rollups(_BASE_ROLLUPS, cand)
    assert diff["regressed"] is False
    assert diff["regressions"] == []
    assert diff["stages"]["sync-execute"]["regressed"] is False


def test_diff_rollups_fail_path_device_busy():
    """A device stage past threshold regresses AND the device-busy total
    regresses — the acceptance criterion's nonzero-exit condition."""
    cand = {
        "stage_seconds": {"sync-execute": 12.0, "h2d-upload": 0.6,
                          "host-solve": 2.0},
        "device_busy_s": 12.6,
        "pipeline_bubble_frac": 0.02,
    }
    diff = telemetry.diff_rollups(_BASE_ROLLUPS, cand)
    assert diff["regressed"] is True
    assert "stage:sync-execute" in diff["regressions"]
    assert "device_busy_s" in diff["regressions"]
    assert diff["device_busy"]["delta_s"] == pytest.approx(4.0)


def test_diff_rollups_host_regression_warns_not_gates():
    cand = dict(_BASE_ROLLUPS,
                stage_seconds={"sync-execute": 8.0, "h2d-upload": 0.6,
                               "host-solve": 9.0})
    diff = telemetry.diff_rollups(_BASE_ROLLUPS, cand)
    assert diff["regressed"] is False
    assert "stage:host-solve" in diff["warnings"]


def test_diff_rollups_abs_floor_ignores_micro_stages():
    base = {"stage_seconds": {"sync-execute": 0.001},
            "device_busy_s": 0.001}
    cand = {"stage_seconds": {"sync-execute": 0.01},
            "device_busy_s": 0.01}   # 10x relative but under the floor
    diff = telemetry.diff_rollups(base, cand)
    assert diff["regressed"] is False


def test_diff_rollups_bubble_gate():
    cand = dict(_BASE_ROLLUPS, pipeline_bubble_frac=0.2)
    diff = telemetry.diff_rollups(_BASE_ROLLUPS, cand)
    assert diff["regressed"] is True
    assert "pipeline_bubble_frac" in diff["regressions"]
    # configurable threshold: widen it and the gate opens
    ok = telemetry.diff_rollups(_BASE_ROLLUPS, cand, bubble_abs=0.5)
    assert ok["regressed"] is False


def test_diff_rollups_new_stage_in_candidate_gates():
    """A device stage absent from the baseline is pure regression."""
    cand = dict(_BASE_ROLLUPS)
    cand = {**_BASE_ROLLUPS,
            "stage_seconds": {**_BASE_ROLLUPS["stage_seconds"],
                              "sync-meta": 1.0}}
    diff = telemetry.diff_rollups(_BASE_ROLLUPS, cand)
    assert "stage:sync-meta" in diff["regressions"]


def test_bench_trace_diff_cli_pass_and_fail(tmp_path):
    """End-to-end CLI: exit 0 on self-compare, nonzero on a synthetic
    device-busy regression (both paths of the acceptance criterion)."""
    import subprocess
    import sys as _sys

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    base = str(tmp_path / "base.json")
    regr = str(tmp_path / "regr.json")
    with open(base, "w") as f:
        json.dump({"rollups": _BASE_ROLLUPS}, f)
    cand = {**_BASE_ROLLUPS, "device_busy_s": 12.6,
            "stage_seconds": {**_BASE_ROLLUPS["stage_seconds"],
                              "sync-execute": 12.0}}
    with open(regr, "w") as f:
        json.dump({"rollups": cand}, f)
    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def run(a, b):
        return subprocess.run(
            [_sys.executable, os.path.join(here, "bench.py"),
             "trace-diff", a, b],
            cwd=here, env=env, capture_output=True, text=True,
            timeout=120)

    ok = run(base, base)
    assert ok.returncode == 0, ok.stdout + ok.stderr
    assert json.loads(ok.stdout)["regressed"] is False
    bad = run(base, regr)
    assert bad.returncode == 1, bad.stdout + bad.stderr
    out = json.loads(bad.stdout)
    assert out["regressed"] is True
    assert "device_busy_s" in out["regressions"]


# ---------------------------------------------------------------------------
# correlation propagation (satellite: exemplar-style linking)
# ---------------------------------------------------------------------------

def test_correlation_scope_attaches_to_spans_and_records(fake_clock):
    with telemetry.correlation("aaaabbbbcccc"):
        assert telemetry.current_correlation() == "aaaabbbbcccc"
        with telemetry.span("work", cat="stage"):
            pass
        telemetry.record("host-map", 0.0, 1.0)
        with telemetry.correlation("ddddeeeeffff"):   # nesting: inner wins
            telemetry.record("host-map", 1.0, 2.0)
    telemetry.record("host-map", 2.0, 3.0)            # outside: no corr
    spans = telemetry.spans_snapshot()
    corr = [s.attrs.get("corr") for s in spans]
    assert corr == ["aaaabbbbcccc", "aaaabbbbcccc", "ddddeeeeffff",
                    None]
    assert telemetry.current_correlation() is None


def test_correlation_explicit_attr_not_overwritten(fake_clock):
    with telemetry.correlation("aaaabbbbcccc"):
        telemetry.record("host-map", 0.0, 1.0, corr="explicit")
    (s,) = telemetry.spans_snapshot()
    assert s.attrs["corr"] == "explicit"


def test_correlation_in_chrome_trace_args(fake_clock, tmp_path):
    """The join key lands in the exported Chrome-trace args, so a
    histogram outlier joins back to its Perfetto spans."""
    with telemetry.correlation("abc123def456"):
        with telemetry.span("attempt", cat="attempt"):
            telemetry.record_stage("sync-execute", 0.5)
    path = str(tmp_path / "t.json")
    telemetry.export_chrome_trace(path)
    with open(path) as f:
        xs = [e for e in json.load(f)["traceEvents"] if e["ph"] == "X"]
    assert xs
    for e in xs:
        assert e["args"]["corr"] == "abc123def456"


def test_retry_attempt_children_inherit_correlation(tmp_path):
    """End-to-end: worker-thread job/stage spans recorded inside a
    retried task's attempts carry the attempt's 12-hex id in attrs —
    the correlation stack is process-global on purpose."""
    config_dir = str(tmp_path / "configs")
    ConfigDir(config_dir).write_global_config(
        {"block_shape": [10, 10, 10], "max_num_retries": 2,
         "telemetry_enabled": True})
    marker_dir = str(tmp_path / "markers")
    os.makedirs(marker_dir)
    task = FailingTask(output_path=str(tmp_path / "out.n5"),
                       output_key="data", shape=(20, 20, 20),
                       tmp_folder=str(tmp_path / "t"),
                       config_dir=config_dir, max_jobs=4,
                       target="threads")
    orig = task.run_jobs

    def run_jobs(block_list, cfg, **kw):
        return orig(block_list, {**cfg, "marker_dir": marker_dir}, **kw)

    task.run_jobs = run_jobs
    task.run()
    spans = telemetry.spans_snapshot()
    attempts = [s for s in spans if s.cat == "attempt"]
    (corr,) = {s.attrs["correlation_id"] for s in attempts}
    assert re.fullmatch(r"[0-9a-f]{12}", corr)
    jobs = [s for s in spans if s.cat == "job"]
    assert jobs
    for j in jobs:
        assert j.attrs.get("corr") == corr, j


# ---------------------------------------------------------------------------
# telemetry-off overhead gate (CI satellite: wired into tier-1)
# ---------------------------------------------------------------------------

def test_telemetry_off_overhead_under_one_percent():
    """The <1% wall gate as a projection: measured per-call cost of a
    DISABLED stage_add (the only thing a telemetry-off run pays), times
    the flagship's total stage entries, against 1% of the recorded
    telemetry-off wall.  Reads the committed TRACE_r07.json when present
    so the gate tracks the real artifact; nominal fallback otherwise."""
    assert not telemetry.enabled()
    n_entries, wall_off = 101, 9.0                # TRACE_r07 nominal
    trace = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "TRACE_r07.json")
    if os.path.exists(trace):
        with open(trace) as f:
            doc = json.load(f)
        n_entries = doc["stage_entries"]
        wall_off = doc["wall_off_s"]
    n_cal = 50_000
    t0 = time.perf_counter()
    for _ in range(n_cal):
        runtime.stage_add("host-map", 0.0)
    per_call = (time.perf_counter() - t0) / n_cal
    projected = per_call * n_entries
    assert projected < 0.01 * wall_off, (
        f"telemetry-off overhead projection {projected:.6f}s exceeds 1% "
        f"of the {wall_off}s flagship wall ({per_call * 1e9:.0f} ns/call "
        f"x {n_entries} entries)")

# ---------------------------------------------------------------------------
# memory observability (ISSUE 17 tentpole a: probe, counter tracks,
# per-span watermarks, memory rollup)
# ---------------------------------------------------------------------------

def test_host_memory_probe_reads_proc_status():
    """The probe reads real, positive RSS/HWM bytes and the shared
    peak-RSS helper uses the 1024-based conversion (the old ad-hoc
    ``ru_maxrss / 1e6`` it replaces OVERSTATES GiB, so the bench's
    ``< 7 GB`` bound only got safer)."""
    import resource

    mem = telemetry.host_memory_bytes()
    assert mem["rss"] > 0 and mem["hwm"] > 0
    gib = telemetry.host_peak_rss_gb()
    assert gib == pytest.approx(mem["hwm"] / 1024.0 ** 3)
    old_style = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6
    assert gib < old_style + 1e-9


def test_device_memory_probe_graceful_without_allocator_stats():
    """CPU jaxlib exposes no allocator stats: the device probe returns
    None (never raises) and NEVER imports jax as a side effect."""
    dev = telemetry.device_memory_bytes()
    assert dev is None or (dev["in_use"] >= 0 and
                           dev["peak"] >= dev["in_use"])


def test_sample_memory_exports_counter_tracks(fake_clock, tmp_path):
    """Counter samples export as Chrome 'C' events (one Perfetto counter
    track per series) and stay OFF the thread-metadata tracks."""
    telemetry.sample_memory()
    with telemetry.span("block:0", cat="block", block=0):
        pass
    path = str(tmp_path / "trace.json")
    telemetry.export_chrome_trace(path, telemetry.spans_snapshot())
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    counters = [e for e in events if e["ph"] == "C"]
    assert {e["name"] for e in counters} >= {"host_rss_gb",
                                            "host_hwm_gb"}
    for e in counters:
        assert set(e["args"]) == {"value"}
        assert e["args"]["value"] > 0
    # the counter pseudo-track claims no thread-name metadata
    thread_meta_tids = {e["tid"] for e in events
                       if e["ph"] == "M" and e["name"] == "thread_name"}
    assert not any(e["tid"] in thread_meta_tids for e in counters)
    # the block span still exports as a normal 'X' slice
    assert any(e["ph"] == "X" and e["name"] == "block:0"
               for e in events)


def test_sample_memory_disabled_is_noop():
    assert not telemetry.enabled()
    assert telemetry.sample_memory() is None
    telemetry.annotate_memory(telemetry.span("x"))   # null span: no-op
    assert telemetry.spans_snapshot() == []


def test_annotate_memory_stamps_span_watermarks(fake_clock):
    """Drain-point hook: mem_* attrs land on the open span and the
    rollup folds them into per-span-name watermarks + the peak scalars
    the trace-diff gate compares."""
    with telemetry.span("block:3", cat="block", block=3) as sp:
        telemetry.annotate_memory(sp)
    roll = telemetry.memory_rollup()
    wm = roll["span_watermarks"]["block:3"]
    assert wm["mem_host_rss_gb"] > 0
    assert wm["mem_host_hwm_gb"] > 0
    assert roll["peak_host_rss_gb"] >= wm["mem_host_rss_gb"]
    assert roll["counters"]["host_rss_gb"]["n"] == 1
    # summary() embeds the same rollup (bench artifacts record it)
    assert telemetry.summary()["memory"]["peak_host_rss_gb"] \
        == roll["peak_host_rss_gb"]


def test_memory_rollup_empty_trace_has_null_peaks():
    """A trace with no memory samples yields None peaks — the
    degrade-to-skip contract diff_rollups depends on."""
    roll = telemetry.memory_rollup([])
    assert roll["peak_host_rss_gb"] is None
    assert roll["peak_device_gb"] is None
    assert roll["counters"] == {} and roll["span_watermarks"] == {}


def test_memory_sampler_background_thread(fake_clock):
    """The optional background probe records counter samples while
    running and stops cleanly."""
    with telemetry.MemorySampler(interval_s=0.005):
        deadline = time.time() + 2.0
        while telemetry.memory_rollup()["counters"].get(
                "host_rss_gb", {}).get("n", 0) < 2:
            assert time.time() < deadline, "sampler recorded nothing"
            time.sleep(0.005)
    n = telemetry.memory_rollup()["counters"]["host_rss_gb"]["n"]
    time.sleep(0.02)      # stopped: no further samples
    assert telemetry.memory_rollup()["counters"]["host_rss_gb"]["n"] == n


# ---------------------------------------------------------------------------
# trace-diff memory gate + malformed/partial artifacts (satellite 3)
# ---------------------------------------------------------------------------

_MEM_ROLLUPS = {**_BASE_ROLLUPS,
                "memory": {"peak_host_rss_gb": 4.0,
                           "peak_device_gb": 2.0}}


def test_diff_rollups_memory_regression_gates():
    """A synthetic peak-HBM regression fails the gate exactly like a
    device-busy regression (acceptance criterion)."""
    cand = {**_MEM_ROLLUPS,
            "memory": {"peak_host_rss_gb": 4.0, "peak_device_gb": 3.5}}
    diff = telemetry.diff_rollups(_MEM_ROLLUPS, cand)
    assert diff["regressed"] is True
    assert diff["regressions"] == ["memory:peak_device_gb"]
    assert diff["memory"]["peak_device_gb"]["delta_gb"] \
        == pytest.approx(1.5)
    # self-compare passes
    ok = telemetry.diff_rollups(_MEM_ROLLUPS, _MEM_ROLLUPS)
    assert ok["regressed"] is False


def test_diff_rollups_memory_abs_floor_and_threshold():
    """Small absolute growth under the GiB floor never regresses; the
    floor is configurable like the seconds floor."""
    cand = {**_MEM_ROLLUPS,
            "memory": {"peak_host_rss_gb": 4.2, "peak_device_gb": 2.0}}
    assert telemetry.diff_rollups(
        _MEM_ROLLUPS, cand)["regressed"] is False      # +0.2 < 1.0 rel floor
    tight = telemetry.diff_rollups(_MEM_ROLLUPS, cand,
                                   mem_abs_floor_gb=0.05,
                                   rel_threshold=0.01)
    assert "memory:peak_host_rss_gb" in tight["regressions"]


def test_diff_rollups_baseline_without_memory_skips():
    """Pre-memory baselines (e.g. the committed TRACE_r07) degrade to
    skipping the memory checks — never a crash or false regression."""
    diff = telemetry.diff_rollups(_BASE_ROLLUPS, _MEM_ROLLUPS)
    assert diff["regressed"] is False
    assert diff["memory"]["peak_host_rss_gb"]["skipped"] is True
    rev = telemetry.diff_rollups(_MEM_ROLLUPS, _BASE_ROLLUPS)
    assert rev["regressed"] is False


def test_diff_rollups_malformed_artifacts_never_crash():
    """Satellite 3: missing rollup keys, empty span lists, wrong-typed
    sections and junk values all degrade to skip/zero, keeping the
    trace-diff gate alive."""
    cases = [
        {}, {"stage_seconds": None}, {"stage_seconds": "junk"},
        {"memory": "junk"}, {"memory": {"peak_host_rss_gb": "junk"}},
        {"stage_seconds": {"sync-execute": "junk"},
         "device_busy_s": None, "pipeline_bubble_frac": "junk",
         "memory": {"peak_host_rss_gb": None}},
        telemetry.rollup_spans([]),      # empty trace, real shape
    ]
    for a in cases:
        for b in cases:
            diff = telemetry.diff_rollups(a, b)
            assert diff["regressed"] is False, (a, b, diff)


# ---------------------------------------------------------------------------
# cross-process trace shards + merge (ISSUE 17 tentpole c)
# ---------------------------------------------------------------------------

def test_trace_shard_roundtrip(fake_clock, tmp_path):
    with telemetry.span("block:0", cat="block", block=0) as sp:
        telemetry.annotate_memory(sp)
    path = str(tmp_path / "trace_shard_p0.json")
    n = telemetry.export_trace_shard(path, process_index=0,
                                     process_count=2,
                                     wall_anchor=100.0, perf_anchor=1.0)
    sh = telemetry.load_trace_shard(path)
    assert sh["process_index"] == 0 and sh["process_count"] == 2
    assert sh["wall_anchor"] == 100.0 and sh["perf_anchor"] == 1.0
    assert len(sh["spans"]) == n >= 2          # block span + counter


def _synthetic_shard(path, pidx, wall_anchor, perf_anchor, spans):
    doc = {"process_index": pidx, "process_count": 2,
           "wall_anchor": wall_anchor, "perf_anchor": perf_anchor,
           "dropped": 0,
           "spans": [{"sid": i + 1, "parent": None, "name": n,
                      "cat": c, "t0": t0, "t1": t1, "tid": 1,
                      "tname": "MainThread", "attrs": a}
                     for i, (n, c, t0, t1, a) in enumerate(spans)]}
    with open(path, "w") as f:
        json.dump(doc, f)


def test_merge_chrome_traces_rebases_and_remaps(tmp_path):
    """Two shards with different clock origins merge into ONE trace:
    pids remapped per process, timestamps rebased through the
    barrier-aligned anchors, and the merged rollups aggregate
    device_busy_s across the mesh (cross-checked per process)."""
    p0 = str(tmp_path / "trace_shard_p0.json")
    p1 = str(tmp_path / "trace_shard_p1.json")
    # process 0: perf clock starts at 1000; process 1: at 5; their wall
    # anchors differ by 0.5 s (process 1 reached the barrier later)
    _synthetic_shard(p0, 0, 100.0, 1000.0, [
        ("sync-execute", "stage", 1000.0, 1000.5,
         {"mem_dev_peak_gb": 1.0}),
        ("host-map", "stage", 1000.5, 1000.6, {})])
    _synthetic_shard(p1, 1, 100.5, 5.0, [
        ("sync-execute", "stage", 5.0, 5.25, {"mem_dev_peak_gb": 2.0})])
    out = str(tmp_path / "merged.json")
    m = telemetry.merge_chrome_traces([p1, p0], out)   # order-insensitive
    assert m["n_processes"] == 2
    assert [p["pid"] for p in m["processes"]] == [1, 2]
    assert [p["clock_offset_s"] for p in m["processes"]] == [0.0, 0.5]
    busy = {p["process_index"]: p["device_busy_s"]
            for p in m["processes"]}
    assert busy == {0: 0.5, 1: 0.25}
    assert m["rollups"]["device_busy_s"] == pytest.approx(0.75)
    assert m["rollups"]["memory"]["peak_device_gb"] == pytest.approx(2.0)
    with open(out) as f:
        events = json.load(f)["traceEvents"]
    assert {e["pid"] for e in events} == {1, 2}
    xs = {e["name"]: e for e in events if e["ph"] == "X"}
    # p1's span started 0.5 s into p0's timeline after the wall rebase:
    # (5.0 - 5.0) + (100.5 - 100.0) -> +0.5 s from the trace base
    assert xs["sync-execute"]["ts"] in (0, 500_000)
    assert all(e["ts"] >= 0 for e in events if "ts" in e)
    # merged trace is a loadable Chrome trace: every event well-formed
    assert all({"ph", "pid", "name"} <= set(e) for e in events)


def test_merge_chrome_traces_empty_raises(tmp_path):
    with pytest.raises(ValueError):
        telemetry.merge_chrome_traces([], str(tmp_path / "out.json"))


# ---------------------------------------------------------------------------
# crash flight recorder (ISSUE 17 tentpole d)
# ---------------------------------------------------------------------------

def test_flight_record_dump_contents(fake_clock, tmp_path):
    """The dump carries the span ring, a live memory probe + rollup, the
    process identity and caller-supplied correlation state — written
    atomically (no .tmp litter)."""
    with telemetry.correlation("req_42"):
        with telemetry.span("block:0", cat="block", block=0) as sp:
            telemetry.annotate_memory(sp)
    path = telemetry.flight_record(
        str(tmp_path), "tenant-fault:req_42",
        extra={"request": "req_42", "tenant": "alice"})
    assert os.path.basename(path).startswith("flightrec_tenant-fault")
    assert not [p for p in os.listdir(str(tmp_path)) if ".tmp" in p]
    with open(path) as f:
        doc = json.load(f)
    assert doc["reason"] == "tenant-fault:req_42"
    assert doc["extra"] == {"request": "req_42", "tenant": "alice"}
    assert doc["n_spans"] == len(doc["spans"]) >= 2
    assert any(s["attrs"].get("corr") == "req_42" for s in doc["spans"])
    assert doc["memory"]["probe"]["host"]["rss"] > 0
    assert doc["memory"]["rollup"]["peak_host_rss_gb"] > 0
    assert doc["process_count"] >= 1
    assert telemetry.flight_record_count() == 1
    # the counter surfaces in the Prometheus families
    fams = {f[0]: f for f in telemetry.metrics_families()}
    assert fams["ctt_telemetry_flight_records_total"][3] == [(None, 1)]


def test_flight_record_works_with_telemetry_disabled(tmp_path):
    assert not telemetry.enabled()
    path = telemetry.flight_record(str(tmp_path), "sigterm")
    with open(path) as f:
        doc = json.load(f)
    assert doc["n_spans"] == 0 and doc["spans"] == []
    assert doc["memory"]["probe"]["host"]["hwm"] > 0


def test_install_flight_recorder_chains_and_uninstalls(tmp_path):
    """The excepthook wrapper dumps a record, then CHAINS the previous
    hook; uninstall restores it exactly."""
    import sys as _sys

    seen = []
    prev = _sys.excepthook
    _sys.excepthook = lambda *a: seen.append(a)
    try:
        uninstall = telemetry.install_flight_recorder(
            str(tmp_path), extra_fn=lambda: {"stage": "serve"})
        try:
            err = ValueError("boom")
            _sys.excepthook(ValueError, err, None)
        finally:
            uninstall()
        assert _sys.excepthook is not prev
        assert len(seen) == 1 and seen[0][1] is err
        recs = [p for p in os.listdir(str(tmp_path))
                if p.startswith("flightrec_")]
        assert len(recs) == 1
        with open(os.path.join(str(tmp_path), recs[0])) as f:
            doc = json.load(f)
        assert doc["reason"] == "exception"
        assert doc["extra"]["exc_type"] == "ValueError"
        assert doc["extra"]["stage"] == "serve"
    finally:
        _sys.excepthook = prev
