"""Ring attention: exact attention over sequences sharded across chips.

The reference has no attention (SURVEY §5.7 — its long-context analog is
the spatial halo machinery), but a TPU framework that claims long-context
as first-class needs the real thing: sequences too long for one chip's HBM,
sharded over a mesh axis, attended exactly.  This is the standard ring
schedule: queries stay put, key/value chunks rotate around the ring via
``lax.ppermute`` (ICI neighbor traffic only — no all_gather of the full
sequence), and each hop folds its partial attention into a numerically
stable online softmax (the flash-attention recurrence: running max,
running normalizer, running weighted sum).  After ``n_shards`` hops every
query has seen every key exactly once; the result is bit-for-bit a
softmax-attention up to float associativity.

Causal masking works across shards by comparing global positions (each
chunk carries its shard offset around the ring).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                   mesh: Mesh, axis: str = "seq",
                   causal: bool = False) -> jnp.ndarray:
    """Exact (optionally causal) attention with the sequence axis sharded
    over ``axis``.

    ``q, k, v``: ``(T, H, D)`` GLOBAL arrays, sharded over the leading
    (sequence) axis by shard_map; T must divide by the axis size.  Returns
    ``(T, H, D)`` — ``softmax(q k^T / sqrt(D)) v`` computed without any
    device ever holding more than its ``T / n_shards`` slice of k/v.
    """
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    n_shards = mesh.shape[axis]
    t_local = q.shape[0] // n_shards
    scale = 1.0 / float(np.sqrt(q.shape[-1]))
    # rotate chunks backwards so shard i sees chunks i, i+1, ... in turn
    perm = [(j, (j - 1) % n_shards) for j in range(n_shards)]

    def body(ql, kl, vl):
        my = jax.lax.axis_index(axis)
        q_pos = my * t_local + jnp.arange(t_local)          # global rows

        def attend(step, kc, vc, m, l, o):
            # bf16 operands at full MXU rate, f32 accumulation
            s = jnp.einsum("thd,shd->hts", ql, kc,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                # the resident chunk at hop `step` originated at shard
                # (my + step) % n_shards — no collective needed to track it
                src = (my + step) % n_shards
                k_pos = src * t_local + jnp.arange(t_local)
                mask = q_pos[:, None] >= k_pos[None, :]
                s = jnp.where(mask[None], s, -jnp.inf)
            m_new = jnp.maximum(m, s.max(axis=2))
            # rows with no visible key yet (causal, all -inf) must not
            # poison exp(): substitute a finite max; exp(m - m_safe) is
            # then already 0 for the -inf prior state
            m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
            corr = jnp.exp(m - m_safe)
            p = jnp.exp(s - m_safe[:, :, None])
            l_new = l * corr + p.sum(axis=2)
            o_new = (o * corr[..., None]
                     + jnp.einsum("hts,shd->thd", p, vc,
                                  preferred_element_type=jnp.float32
                                  ).transpose(1, 0, 2))
            return m_new, l_new, o_new

        def hop(step, carry):
            kc, vc, m, l, o = carry
            m, l, o = attend(step, kc, vc, m, l, o)
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            return kc, vc, m, l, o

        # initial accumulators must be marked device-varying over the ring
        # axis (the loop makes them varying via the per-shard partials)
        from .stencil import device_varying

        h, d = ql.shape[1], ql.shape[2]
        m0 = device_varying(jnp.full((h, t_local), -jnp.inf, jnp.float32),
                            axis)
        l0 = device_varying(jnp.zeros((h, t_local), jnp.float32), axis)
        o0 = device_varying(jnp.zeros((h, t_local, d), jnp.float32), axis)
        carry = (kl, vl, m0, l0, o0)
        # the final hop attends without rotating (its permuted chunk would
        # be discarded — one full K+V ICI transfer saved per call)
        kc, vc, m, l, o = jax.lax.fori_loop(0, n_shards - 1, hop, carry)
        m, l, o = attend(n_shards - 1, kc, vc, m, l, o)
        out = o / jnp.maximum(l[..., None], 1e-30)
        return out.transpose(1, 0, 2).astype(ql.dtype)      # (t, H, D)

    spec = P(axis)
    return shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                     out_specs=spec)(q, k, v)


def make_seq_mesh(n_shards: int, n_devices: Optional[int] = None) -> Mesh:
    """Mesh with a single ``seq`` axis for sequence/context parallelism."""
    from .mesh import single_axis_mesh

    return single_axis_mesh("seq", n_shards, n_devices)
