"""Lazy volume views: interpolated (multi-resolution) and affine-transformed.

Re-specification of the reference's volume classes
(utils/volume_classes.py:31-232): views expose ``__getitem__`` over the
*virtual* full-resolution/transformed shape so tasks can treat a low-res mask
as if it were full-res (utils/volume_utils.py:208-218 ``load_mask``).
Interpolation runs on host via scipy (mask resampling is control-plane, not a
TPU hot path).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import numpy as np
from scipy.ndimage import affine_transform, zoom


def normalize_index(index, shape) -> Tuple[Tuple[slice, ...], Tuple[int, ...]]:
    """Normalize an index to a tuple of non-negative slices over ``shape``
    (reference: utils/volume_classes.py:12)."""
    if not isinstance(index, tuple):
        index = (index,)
    squeeze_axes = []
    slices = []
    for d, ind in enumerate(index):
        if isinstance(ind, (int, np.integer)):
            i = int(ind)
            if i < 0:
                i += shape[d]
            slices.append(slice(i, i + 1))
            squeeze_axes.append(d)
        elif isinstance(ind, slice):
            start = 0 if ind.start is None else int(ind.start)
            stop = shape[d] if ind.stop is None else int(ind.stop)
            if start < 0:
                start += shape[d]
            if stop < 0:
                stop += shape[d]
            slices.append(slice(start, stop))
        else:
            raise TypeError(f"unsupported index {ind!r}")
    for d in range(len(slices), len(shape)):
        slices.append(slice(0, shape[d]))
    return tuple(slices), tuple(squeeze_axes)


class InterpolatedVolume:
    """Present a low-resolution volume at a virtual full-resolution ``shape``
    (reference: utils/volume_classes.py:155-232), with empty/uniform-block
    shortcuts (:223-228)."""

    def __init__(self, volume, shape: Sequence[int], spline_order: int = 0):
        self.volume = volume
        self.shape = tuple(int(s) for s in shape)
        self.ndim = len(self.shape)
        vshape = volume.shape
        if len(vshape) != self.ndim:
            raise ValueError("dim mismatch")
        self.scale = tuple(s / v for s, v in zip(self.shape, vshape))
        self.spline_order = spline_order
        self.dtype = np.dtype(getattr(volume, "dtype", np.float32))

    def __getitem__(self, index) -> np.ndarray:
        slices, squeeze_axes = normalize_index(index, self.shape)
        out_shape = tuple(s.stop - s.start for s in slices)
        # matching low-res bounding box (expanded by 1 voxel for interpolation)
        lo = [max(int(np.floor(s.start / sc)), 0) for s, sc in zip(slices, self.scale)]
        hi = [
            min(int(np.ceil(s.stop / sc)) + 1, vs)
            for s, sc, vs in zip(slices, self.scale, self.volume.shape)
        ]
        sub = np.asarray(self.volume[tuple(slice(l, h) for l, h in zip(lo, hi))])
        if sub.size == 0:
            return np.zeros(out_shape, dtype=self.dtype)
        # uniform-block shortcut
        first = sub.flat[0]
        if (sub == first).all():
            return np.full(out_shape, first, dtype=self.dtype)
        zoomed = zoom(sub, self.scale, order=self.spline_order,
                      mode="nearest", grid_mode=True)
        # crop the requested window out of the zoomed expanded box
        off = [s.start - int(l * sc) for s, l, sc in zip(slices, lo, self.scale)]
        bb = tuple(
            slice(max(o, 0), max(o, 0) + osz)
            for o, osz in zip(off, out_shape)
        )
        out = zoomed[bb]
        # pad if rounding left us short at the upper border
        if out.shape != out_shape:
            pad = [(0, osz - cs) for osz, cs in zip(out_shape, out.shape)]
            out = np.pad(out, pad, mode="edge")
        if squeeze_axes:
            out = np.squeeze(out, axis=tuple(squeeze_axes))
        return out.astype(self.dtype, copy=False)


class TransformedVolume:
    """Affine-resampled view of a volume (reference:
    utils/volume_classes.py:31-152): ``view[bb]`` returns the transformed
    data for that output bounding box."""

    def __init__(self, volume, matrix: np.ndarray, shape: Sequence[int] = None,
                 order: int = 0, fill_value: float = 0):
        self.volume = volume
        matrix = np.asarray(matrix, dtype="float64")
        ndim = volume.ndim if hasattr(volume, "ndim") else len(volume.shape)
        if matrix.shape != (ndim + 1, ndim + 1):
            raise ValueError(
                f"expected homogeneous {(ndim + 1, ndim + 1)} matrix, got {matrix.shape}")
        self.matrix = matrix
        self.shape = tuple(int(s) for s in (shape or volume.shape))
        self.ndim = len(self.shape)
        self.order = order
        self.fill_value = fill_value
        self.dtype = np.dtype(getattr(volume, "dtype", np.float32))

    def __getitem__(self, index) -> np.ndarray:
        slices, squeeze_axes = normalize_index(index, self.shape)
        out_shape = tuple(s.stop - s.start for s in slices)
        offset_vec = np.array([s.start for s in slices], dtype="float64")

        # output voxel o (+ window offset) -> input voxel: x = A^-1 @ o
        inv = np.linalg.inv(self.matrix)
        lin, trans = inv[:-1, :-1], inv[:-1, -1]
        trans = trans + lin @ offset_vec

        # conservative input bounding box for the window
        corners = np.array(np.meshgrid(
            *[[0, s] for s in out_shape], indexing="ij")).reshape(self.ndim, -1).T
        src = corners @ lin.T + trans
        lo = np.maximum(np.floor(src.min(axis=0)).astype(int) - 1, 0)
        hi = np.minimum(np.ceil(src.max(axis=0)).astype(int) + 2,
                        np.asarray(self.volume.shape))
        if (hi <= lo).any():
            out = np.full(out_shape, self.fill_value, dtype=self.dtype)
        else:
            sub = np.asarray(self.volume[tuple(slice(l, h) for l, h in zip(lo, hi))])
            out = affine_transform(
                sub, lin, offset=trans - lo,
                output_shape=out_shape, order=self.order,
                mode="constant", cval=self.fill_value,
            ).astype(self.dtype, copy=False)
        if squeeze_axes:
            out = np.squeeze(out, axis=tuple(squeeze_axes))
        return out


def load_mask(mask_path: str, mask_key: str, shape: Sequence[int]):
    """Open a (possibly low-res) mask as a full-res interpolated view
    (reference: utils/volume_utils.py:208-218)."""
    from .storage import file_reader

    f = file_reader(mask_path, "r")
    ds = f[mask_key]
    if tuple(ds.shape) == tuple(shape):
        return ds
    return InterpolatedVolume(ds, shape, spline_order=0)
