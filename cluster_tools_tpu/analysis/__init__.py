"""ctt-lint: static-analysis passes over the whole package.

One CLI (``python -m cluster_tools_tpu.analysis``), ~7 AST passes, one
pragma.  See :mod:`.base` for the framework, the sibling modules for
the individual rules, and ``core.runtime`` for the dynamic half (the
lock-order witness).
"""

from .base import (ALL_RULES, Finding, Pass, SourceFile, load_passes,
                   report_as_json, run_analysis)
from . import sources

__all__ = [
    "ALL_RULES", "Finding", "Pass", "SourceFile", "load_passes",
    "report_as_json", "run_analysis", "sources", "main",
]


def main(argv=None) -> int:
    from .__main__ import main as _main
    return _main(argv)
