"""Distributed edge-feature accumulation.

Re-specification of the reference's ``features/`` package: per-block edge
statistics from boundary or affinity maps, then a count-weighted hierarchical
merge (reference: block_edge_features.py:113-141 typed ndist C++ paths,
merge_edge_features.py ndist.mergeFeatureBlocks).  TPU-first split: the
O(volume) work — sampling map values at label faces — is a jitted device
kernel (ops/rag.py boundary_pair_values / affinity_pair_values); the
O(edges) segmented statistics are vectorized host numpy.

Feature columns (ops/rag.py FEATURE_NAMES):
    [mean, variance, min, q10, q25, q50, q75, q90, max, count]
Costs consume column 0 (mean probability) and column 9 (edge size), matching
the reference's features[:, 0] / features[:, -1] convention
(costs/probs_to_costs.py).
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import numpy as np

from ..core import graph as g
from ..core.blocking import Blocking
from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import Task

_BLOCK_FEAT_DIR = "block_features"


def _block_feature_path(features_path: str, block_id: int) -> str:
    return os.path.join(features_path, _BLOCK_FEAT_DIR, f"block_{block_id}.npz")


class BlockEdgeFeatures(BlockTask):
    """Per-block accumulation (reference: BlockEdgeFeatures).  Boundary maps
    (3d input) sample both face voxels per edge; affinity maps (4d input)
    sample the offset channel at the face (reference convention)."""

    task_name = "block_edge_features"

    @staticmethod
    def default_task_config():
        from ..core.runtime import BlockTask

        conf = BlockTask.default_task_config()
        # filters + sigmas: optional filter-bank features (reference:
        # block_edge_features.py:165-230 _accumulate_block) — each
        # (filter, sigma) response contributes a 9-column stat group;
        # the sample-count column is shared and written once at the end
        conf.update({"e_max": 65536, "filters": None, "sigmas": None})
        return conf

    def __init__(self, input_path: str, input_key: str, labels_path: str,
                 labels_key: str, graph_path: str, output_path: str,
                 offsets: Optional[List[List[int]]] = None,
                 graph_key: str = "graph", **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.graph_path = graph_path
        self.graph_key = graph_key
        self.output_path = output_path
        self.offsets = offsets
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.labels_path, "r") as f:
            shape = list(f[self.labels_key].shape)
        block_shape = self.global_block_shape()
        block_list = self.blocks_in_volume(shape, block_shape)
        os.makedirs(os.path.join(self.output_path, _BLOCK_FEAT_DIR),
                    exist_ok=True)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "labels_path": self.labels_path, "labels_key": self.labels_key,
            "graph_path": self.graph_path, "graph_key": self.graph_key,
            "output_path": self.output_path, "offsets": self.offsets,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        import jax.numpy as jnp

        from ..ops.rag import (affinity_pair_values, boundary_pair_values,
                               densify_labels, device_edge_stats_finalize,
                               device_edge_stats_submit)

        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        offsets = cfg.get("offsets")
        f_in = file_reader(cfg["input_path"], "r")
        f_lab = file_reader(cfg["labels_path"], "r")
        ds_in, ds_lab = f_in[cfg["input_key"]], f_lab[cfg["labels_key"]]
        # integer inputs are quantized probabilities scaled by the dtype's
        # full range (uint8 -> /255, uint16 -> /65535, ...)
        if np.issubdtype(ds_in.dtype, np.signedinteger):
            raise ValueError(
                f"signed integer probability maps are not supported "
                f"(got {ds_in.dtype})")
        scale = (float(np.iinfo(ds_in.dtype).max)
                 if np.issubdtype(ds_in.dtype, np.integer) else 1.0)
        global_edges = None
        if offsets is not None:
            # affinity anchors are owned per-voxel, so an anchor's edge may
            # live in a neighboring block's sub-graph; map samples straight
            # to GLOBAL edge ids to keep seam faces (graph loaded once/job)
            _, global_edges, _ = g.load_graph(cfg["graph_path"],
                                              cfg.get("graph_key", "graph"))
        responses = [(fn, s) for fn in (cfg.get("filters") or [])
                     for s in (cfg.get("sigmas") or [])]
        if responses and offsets is not None:
            raise ValueError("filter-bank features are defined for boundary "
                             "maps only (reference: _accumulate_block)")
        n_feats = 9 * len(responses) + 1 if responses else 10

        e_max = int(cfg.get("e_max", 65536))

        # two-stage pipeline: submit enqueues the device programs without
        # synchronizing, drain materializes and writes — block i+1's
        # transfers/compute overlap block i's readback + IO (per-block
        # device latency dominates on tunnel-attached chips)
        def load(block_id: int):
            """Host IO only (runs on the prefetch threads): geometry, label
            + map reads, sub-graph load."""
            block = blocking.get_block(block_id)
            if offsets is None:
                begin = list(block.begin)
                end = [min(e + 1, s) for e, s in zip(block.end, cfg["shape"])]
            else:
                # two-sided halo covering the longest offset (negative
                # offsets reach backwards from anchors in the inner block)
                reach = np.abs(np.asarray(offsets)).max(axis=0)
                begin = [max(b - int(r), 0)
                         for b, r in zip(block.begin, reach)]
                end = [min(e + int(r), s)
                       for e, r, s in zip(block.end, reach, cfg["shape"])]
            bb = tuple(slice(b, e) for b, e in zip(begin, end))
            data = g.load_sub_graph(cfg["graph_path"], 0, block_id)
            if len(data["edges"]) == 0 and offsets is None:
                # empty local sub-graph: no map/label read needed (affinity
                # mode still proceeds — the block may own seam anchors)
                return block_id, None, None, None, None, None, None, data
            labels = np.asarray(ds_lab[bb])
            if responses:
                halo_v = int(4.0 * max(cfg["sigmas"]) + 0.5) + 1
                obegin = [max(b - halo_v, 0) for b in begin]
                oend = [min(e + halo_v, s)
                        for e, s in zip(end, cfg["shape"])]
                obb = tuple(slice(b, e) for b, e in zip(obegin, oend))
                raw = np.asarray(ds_in[obb])
            elif offsets is None:
                obegin = begin
                raw = np.asarray(ds_in[bb])
            else:
                obegin = begin
                raw = np.asarray(ds_in[(slice(0, len(offsets)),) + bb])
            return block_id, block, begin, end, obegin, labels, raw, data

        host_impl = cfg.get("impl") == "host"

        def submit(entry):
            block_id, block, begin, end, obegin, labels, raw, data = entry
            edges, edge_ids = data["edges"], data["edge_ids"]
            if len(edges) == 0 and offsets is None:
                return block_id, None, None, None, None
            if host_impl:
                # reference-faithful CPU path: numpy pair extraction +
                # sort-based segmented stats, no device involvement
                if responses or offsets is not None:
                    raise ValueError("impl='host' supports plain boundary "
                                     "features only")
                from ..ops.rag import host_boundary_edge_features

                uv, feats = host_boundary_edge_features(
                    labels, raw.astype("float32") / scale,
                    inner_shape=tuple(block.shape))
                return block_id, ("host", uv, feats), edges, edge_ids, "host"
            lut, dense = densify_labels(labels)
            if responses:
                # filter-bank features: one device filter response per
                # (filter, sigma), each accumulated with the same boundary
                # sampling; support halo must cover the full kernel radius
                # (truncate=4.0 in ops/filters._gaussian_kernel) so
                # blockwise responses equal the global ones up to the
                # volume border
                from ..ops.filters import apply_filter

                import jax

                raw_dev = jnp.asarray(raw.astype("float32") / scale)
                local = tuple(slice(b - ob, e - ob)
                              for b, ob, e in zip(begin, obegin, end))
                dense_dev = jnp.asarray(dense)
                resp_stack = jnp.stack([apply_filter(raw_dev, fn, s)[local]
                                        for fn, s in responses])
                # u/v/ok derive from the labels only, so under vmap they
                # stay unbatched and the O(volume) pair extraction runs
                # once; only the value gather is per-response
                u, v, vals, ok = jax.vmap(
                    lambda m: boundary_pair_values(
                        dense_dev, m, inner_shape=tuple(block.shape)),
                    out_axes=(None, None, 0, None))(resp_stack)
                from ..ops.rag import device_edge_stats_submit_multi

                handles = device_edge_stats_submit_multi(
                    u, v, ok, [vals[k] for k in range(len(responses))],
                    e_max=e_max)
            elif offsets is None:
                bmap = raw.astype("float32") / scale
                u, v, val, ok = boundary_pair_values(
                    jnp.asarray(dense), jnp.asarray(bmap),
                    inner_shape=tuple(block.shape))
                # per-edge reduction ON DEVICE: only the compact (uv,
                # stats) tables cross the host link (the padded sample
                # arrays are ~10x the block size)
                handles = [device_edge_stats_submit(u, v, val, ok,
                                                    e_max=e_max)]
            else:
                affs = raw.astype("float32") / scale
                u, v, val, ok = affinity_pair_values(
                    jnp.asarray(dense), jnp.asarray(affs), offsets,
                    inner_begin=tuple(b - bo for b, bo in
                                      zip(block.begin, begin)),
                    inner_shape=tuple(block.shape))
                handles = [device_edge_stats_submit(u, v, val, ok,
                                                    e_max=e_max)]
            return block_id, lut, edges, edge_ids, handles

        def drain(entry):
            block_id, lut, edges, edge_ids, handles = entry
            if handles is None:
                np.savez(_block_feature_path(cfg["output_path"], block_id),
                         edge_ids=np.zeros(0, "int64"),
                         features=np.zeros((0, n_feats), "float64"))
                log_fn(f"processed block {block_id}")
                return
            if handles == "host":
                _, uv, edge_feats = lut
            else:
                groups = []
                for h in handles:
                    uv_dense, ef = device_edge_stats_finalize(h, e_max)
                    groups.append(ef)
                if responses:
                    edge_feats = np.concatenate(
                        [f[:, :9] for f in groups] + [groups[-1][:, 9:10]],
                        axis=1)
                else:
                    edge_feats = groups[0]
                uv = np.stack([lut[uv_dense[:, 0]], lut[uv_dense[:, 1]]],
                              axis=1)
            if offsets is None:
                # boundary faces share the RAG's ownership rule, so every
                # edge maps into the block's own sub-graph
                local_ids = g.find_edge_ids(edges, uv)
                feats = np.zeros((len(edges), n_feats), "float64")
                feats[local_ids] = edge_feats
                out_ids = edge_ids
            else:
                # global mapping; long-range pairs that are not RAG edges
                # anywhere are dropped (strict=False)
                gids = g.find_edge_ids(global_edges, uv, strict=False)
                keep = gids >= 0
                out_ids, feats = gids[keep], edge_feats[keep]
            np.savez(_block_feature_path(cfg["output_path"], block_id),
                     edge_ids=out_ids.astype("int64"), features=feats)
            log_fn(f"processed block {block_id}")

        from ..core.runtime import prefetch_iter, stream_window

        for _ in stream_window(prefetch_iter(job_config["block_list"], load),
                               submit, drain,
                               window=int(cfg.get("stream_window", 3))):
            pass


class MergeEdgeFeatures(BlockTask):
    """Merge per-block features into the global edge table, sharded over the
    edge-id space (reference: MergeEdgeFeatures + §2.4.5 label-space
    sharding).  Each job owns a contiguous edge-id chunk and scans the block
    files for rows in its chunk."""

    task_name = "merge_edge_features"

    def __init__(self, graph_path: str, output_path: str,
                 output_key: str = "features", graph_key: str = "graph", **kw):
        self.graph_path = graph_path
        self.output_path = output_path
        self.output_key = output_key
        self.graph_key = graph_key
        super().__init__(**kw)

    def run_impl(self):
        _, edges, attrs = g.load_graph(self.graph_path, self.graph_key)
        n_edges = int(attrs["n_edges"])
        chunk = max(1, (n_edges + self.max_jobs - 1) // self.max_jobs)
        # feature width comes from the already-written block files (10 for
        # plain maps, 9*n_responses+1 for filter-bank features)
        n_feats = 10
        feat_dir = os.path.join(self.output_path, _BLOCK_FEAT_DIR)
        if os.path.isdir(feat_dir):
            for name in sorted(os.listdir(feat_dir)):
                if name.startswith("block_") and name.endswith(".npz"):
                    with np.load(os.path.join(feat_dir, name)) as d:
                        n_feats = int(d["features"].shape[1])
                    break
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=(n_edges, n_feats),
                              chunks=(min(n_edges, 64 * 1024), n_feats),
                              dtype="float64")
        chunks = list(range(0, n_edges, chunk))
        self.run_jobs(chunks, {
            "graph_path": self.graph_path, "output_path": self.output_path,
            "output_key": self.output_key, "n_edges": n_edges, "chunk": chunk,
            "n_feats": n_feats,
        }, n_jobs=self.max_jobs, consecutive_blocks=True)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from ..ops.rag import merge_feature_blocks

        cfg = job_config["config"]
        n_edges, chunk = cfg["n_edges"], cfg["chunk"]
        n_feats = int(cfg.get("n_feats", 10))
        feat_dir = os.path.join(cfg["output_path"], _BLOCK_FEAT_DIR)
        block_files = [os.path.join(feat_dir, n) for n in os.listdir(feat_dir)
                       if n.startswith("block_") and n.endswith(".npz")]
        f_out = file_reader(cfg["output_path"])
        ds = f_out[cfg["output_key"]]
        # one pass over the block files per JOB: each file is read once and
        # its rows binned into every owned edge range (the r1-flagged
        # O(blocks x ranges) re-read pattern scaled as blocks x jobs x
        # ranges_per_job at terabyte volumes)
        ranges = [(e0, min(e0 + chunk, n_edges))
                  for e0 in job_config["block_list"]]
        partials = {e0: [] for e0, _ in ranges}
        for path in block_files:
            with np.load(path) as d:
                ids, feats = d["edge_ids"], d["features"]
            for e0, e1 in ranges:
                sel = (ids >= e0) & (ids < e1)
                if sel.any():
                    partials[e0].append((ids[sel] - e0, feats[sel]))
        for e0, e1 in ranges:
            merged = merge_feature_blocks(partials[e0], e1 - e0, n_feats)
            ds[slice(e0, e1), slice(0, n_feats)] = merged
            log_fn(f"processed block {e0}")


class EdgeFeaturesWorkflow(Task):
    """BlockEdgeFeatures -> MergeEdgeFeatures (reference:
    features_workflow.py:33-59)."""

    def __init__(self, input_path: str, input_key: str, labels_path: str,
                 labels_key: str, graph_path: str, output_path: str,
                 tmp_folder: str, config_dir: str, max_jobs: int = 1,
                 target: str = "local", output_key: str = "features",
                 offsets: Optional[List[List[int]]] = None,
                 graph_key: str = "graph",
                 dependency: Optional[Task] = None):
        self.kw = dict(tmp_folder=tmp_folder, config_dir=config_dir,
                       max_jobs=max_jobs, target=target)
        self.args = dict(input_path=input_path, input_key=input_key,
                         labels_path=labels_path, labels_key=labels_key,
                         graph_path=graph_path, output_path=output_path)
        self.output_key = output_key
        self.offsets = offsets
        self.graph_key = graph_key
        self.tmp_folder = tmp_folder
        self.dependency = dependency
        super().__init__()

    def requires(self):
        t1 = BlockEdgeFeatures(offsets=self.offsets,
                               graph_key=self.graph_key,
                               dependency=self.dependency,
                               **self.args, **self.kw)
        return MergeEdgeFeatures(
            graph_path=self.args["graph_path"],
            output_path=self.args["output_path"],
            output_key=self.output_key, graph_key=self.graph_key,
            dependency=t1, **self.kw)

    def output(self):
        from ..core.workflow import FileTarget

        return FileTarget(os.path.join(self.tmp_folder,
                                       "merge_edge_features.status"))
