"""Decomposition multicut: distributed alternative solver.

Re-specification of the reference's ``decomposition_multicut/`` package
(decompose.py:93-150 — connected components of the graph restricted to
attractive edges; solve_subproblems.py:117-153 — independent per-component
solves; insert.py:96+ — recombine component solutions).  Unlike the
hierarchical ladder, the decomposition never merges across repulsive cuts,
so the components are embarrassingly parallel."""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..core import graph as g
from ..core.runtime import BlockTask
from ..core.solvers import key_to_agglomerator
from ..core.storage import file_reader
from ..core.workflow import FileTarget, Task
from .multicut import _load_costs, _load_scale_graph, save_assignment_table
from .write import WriteAssignments


class Decompose(BlockTask):
    """Connected components of the attractive subgraph (reference:
    decompose.py:93-150 via ndist.connectedComponents)."""

    task_name = "decompose"
    global_task = True
    allow_retry = False

    def __init__(self, problem_path: str, **kw):
        self.problem_path = problem_path
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {"problem_path": self.problem_path})

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from .. import native

        cfg = job_config["config"]
        uv_dense, n_nodes, _ = _load_scale_graph(cfg["problem_path"], 0)
        costs = _load_costs(cfg["problem_path"], 0)
        attractive = costs > 0
        roots = native.ufd_merge_pairs(n_nodes, uv_dense[attractive])
        _, comp = np.unique(roots, return_inverse=True)
        with file_reader(cfg["problem_path"]) as f:
            f.require_dataset("decomposition/labeling",
                              data=comp.astype("uint64"),
                              chunks=(min(int(1e6), max(len(comp), 1)),))
        log_fn(f"decomposed {n_nodes} nodes into {comp.max() + 1 if len(comp) else 0} components")


class SolveDecomposition(BlockTask):
    """Independent multicut per component, components sharded across jobs
    (reference: decomposition solve_subproblems.py:117-153)."""

    task_name = "solve_decomposition"

    def __init__(self, problem_path: str, **kw):
        self.problem_path = problem_path
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"agglomerator": "kernighan-lin"})
        return conf

    def run_impl(self):
        with file_reader(self.problem_path, "r") as f:
            comp = f["decomposition/labeling"][:]
        n_components = int(comp.max()) + 1 if len(comp) else 0
        self.run_jobs(list(range(n_components)), {
            "problem_path": self.problem_path,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        problem_path = cfg["problem_path"]
        agglomerator = key_to_agglomerator(
            cfg.get("agglomerator", "kernighan-lin"))
        uv_dense, n_nodes, _ = _load_scale_graph(problem_path, 0)
        costs = _load_costs(problem_path, 0)
        with file_reader(problem_path, "r") as f:
            comp = f["decomposition/labeling"][:]
        edge_comp = comp[uv_dense[:, 0]]
        inner = comp[uv_dense[:, 0]] == comp[uv_dense[:, 1]]
        res_dir = os.path.join(problem_path, "decomposition", "results")
        os.makedirs(res_dir, exist_ok=True)

        for comp_id in job_config["block_list"]:
            sel = inner & (edge_comp == comp_id)
            sub_uv = uv_dense[sel]
            if len(sub_uv) == 0:
                log_fn(f"processed block {comp_id}")
                continue
            nodes, local_flat = np.unique(sub_uv, return_inverse=True)
            local_uv = local_flat.reshape(-1, 2).astype("int64")
            sub_res = agglomerator(len(nodes), local_uv, costs[sel])
            # np.savez appends .npz to names without the suffix
            tmp = os.path.join(res_dir, f"component_{comp_id}.tmp.npz")
            np.savez(tmp, nodes=nodes.astype("uint64"),
                     labels=sub_res.astype("uint64"))
            os.replace(tmp, os.path.join(res_dir,
                                         f"component_{comp_id}.npz"))
            log_fn(f"processed block {comp_id}")


class InsertDecomposition(BlockTask):
    """Combine the per-component solutions into one node labeling
    (reference: insert.py:96+)."""

    task_name = "insert_decomposition"
    global_task = True
    allow_retry = False

    def __init__(self, problem_path: str, assignment_path: str, **kw):
        self.problem_path = problem_path
        self.assignment_path = assignment_path
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "problem_path": self.problem_path,
            "assignment_path": self.assignment_path,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        problem_path = cfg["problem_path"]
        _, n_nodes, s0_nodes = _load_scale_graph(problem_path, 0)
        with file_reader(problem_path, "r") as f:
            comp = f["decomposition/labeling"][:].astype("uint64")
        # nodes not covered by any component solution keep their component
        # id; solved nodes get component-offset local labels
        final = comp.copy()
        offset = int(comp.max()) + 1 if len(comp) else 0
        res_dir = os.path.join(problem_path, "decomposition", "results")
        if os.path.isdir(res_dir):
            for name in sorted(os.listdir(res_dir)):
                if not name.endswith(".npz") or ".tmp." in name:
                    continue
                with np.load(os.path.join(res_dir, name)) as d:
                    nodes, labels = d["nodes"], d["labels"]
                final[nodes.astype("int64")] = labels + offset
                offset += int(labels.max()) + 1 if len(labels) else 0
        _, final = np.unique(final, return_inverse=True)
        nodes0 = (s0_nodes if s0_nodes is not None
                  else np.arange(n_nodes, dtype="uint64"))
        save_assignment_table(nodes0, final, cfg["assignment_path"])
        log_fn(f"inserted solutions: {len(np.unique(final))} segments")


class DecompositionWorkflow(Task):
    """Decompose -> per-component solves -> insert -> write (reference:
    decomposition_multicut workflow wiring)."""

    def __init__(self, problem_path: str, ws_path: str, ws_key: str,
                 output_path: str, output_key: str, tmp_folder: str,
                 config_dir: str, max_jobs: int = 1, target: str = "local",
                 dependency: Optional[Task] = None):
        self.problem_path = problem_path
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.output_path = output_path
        self.output_key = output_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        assignment_path = os.path.join(self.tmp_folder,
                                       "decomposition_assignments.npy")
        dec = Decompose(problem_path=self.problem_path,
                        dependency=self.dependency, **common)
        solve = SolveDecomposition(problem_path=self.problem_path,
                                   dependency=dec, **common)
        insert = InsertDecomposition(
            problem_path=self.problem_path, assignment_path=assignment_path,
            dependency=solve, **common)
        return WriteAssignments(
            input_path=self.ws_path, input_key=self.ws_key,
            output_path=self.output_path, output_key=self.output_key,
            assignment_path=assignment_path, identifier="decomposition",
            dependency=insert, **common)

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "write_decomposition.status"))
