"""On-device region-adjacency-graph primitives.

TPU-native replacement for ``nifty.distributed.computeMergeableRegionGraph``
and the ndist feature-extraction entry points (reference:
graph/initial_sub_graphs.py:114-118, features/block_edge_features.py:113-141)
— the reference delegates per-block RAG extraction to a fused C++ IO+compute
call; here the *compute* is a jitted device program over the label block
(static shapes: every axis-neighbor pair is emitted with a validity mask) and
the host does only `np.unique` over the surviving pairs.

Face ownership: the pair between voxel ``i`` and ``i+1`` along an axis
belongs to the block that owns voxel ``i``; blocks read a +1 halo on their
upper faces (the reference's ``increaseRoi`` convention) so inter-block faces
are extracted exactly once globally.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def densify_labels(labels: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Map arbitrary (uint64) labels to dense int32 ids for device transfer.

    JAX silently truncates int64 inputs to int32 unless x64 is enabled, and
    watershed fragment labels carry per-block voxel offsets that exceed 2**31
    at cluster scale.  Device kernels therefore always run on dense per-block
    ids; callers map pair results back through the returned LUT.  Returns
    (lut, dense) with ``lut[dense] == labels`` and ``lut[0] == 0`` so the
    kernels' ignore-label-0 convention survives densification.
    """
    uniq, inv = np.unique(labels, return_inverse=True)
    inv = inv.reshape(labels.shape)
    if len(uniq) == 0 or uniq[0] != 0:
        uniq = np.concatenate([np.zeros(1, dtype=uniq.dtype), uniq])
        inv = inv + 1
    if len(uniq) >= 2 ** 31:  # one block can never hold this many labels
        raise ValueError("more than 2**31 distinct labels in one block")
    return uniq.astype("uint64"), inv.astype("int32")


def _axis_slices(ndim: int, axis: int, lo_size: int):
    lo = [slice(None)] * ndim
    hi = [slice(None)] * ndim
    lo[axis] = slice(0, lo_size)
    hi[axis] = slice(1, lo_size + 1)
    return tuple(lo), tuple(hi)


@partial(jax.jit, static_argnames=("ignore_label", "inner_shape"))
def label_pairs(labels: jnp.ndarray, ignore_label: bool = True,
                inner_shape: Optional[Tuple[int, ...]] = None):
    """All differing axis-neighbor label pairs in the block.

    ``labels`` is the haloed block (inner block + 1 voxel on upper faces where
    available).  ``inner_shape`` restricts pair *ownership* to faces whose
    first voxel lies in the inner block.  Returns (u, v, valid) flat arrays
    with u < v for valid entries; invalid slots are zero.
    """
    ndim = labels.ndim
    us: List[jnp.ndarray] = []
    vs: List[jnp.ndarray] = []
    ok: List[jnp.ndarray] = []
    inner = inner_shape or labels.shape
    for axis in range(ndim):
        size = labels.shape[axis] - 1
        if size <= 0:
            continue
        lo_sl, hi_sl = _axis_slices(ndim, axis, size)
        a = labels[lo_sl]
        b = labels[hi_sl]
        valid = a != b
        if ignore_label:
            valid &= (a != 0) & (b != 0)
        # ownership: first voxel inside the inner block (on every axis)
        for ax2 in range(ndim):
            lim = inner[ax2] if ax2 != axis else min(inner[ax2], size)
            if a.shape[ax2] > lim:
                idx = jnp.arange(a.shape[ax2]) < lim
                shape = [1] * ndim
                shape[ax2] = a.shape[ax2]
                valid &= idx.reshape(shape)
        u = jnp.minimum(a, b).reshape(-1)
        v = jnp.maximum(a, b).reshape(-1)
        m = valid.reshape(-1)
        us.append(jnp.where(m, u, 0))
        vs.append(jnp.where(m, v, 0))
        ok.append(m)
    return jnp.concatenate(us), jnp.concatenate(vs), jnp.concatenate(ok)


@partial(jax.jit, static_argnames=("ignore_label", "inner_shape"))
def boundary_pair_values(labels: jnp.ndarray, bmap: jnp.ndarray,
                         ignore_label: bool = True,
                         inner_shape: Optional[Tuple[int, ...]] = None):
    """Pairs plus boundary-map samples for edge-feature accumulation.

    Each owned face contributes TWO samples: the boundary-map value at both
    face voxels (nifty gridRag convention — an edge's statistics pool the
    boundary pixels on both sides).  Returns (u, v, value, valid) with the
    two samples concatenated — a thin expansion of
    :func:`boundary_pair_values_dual`, which owns the face convention.
    """
    u, v, va, vb, ok = boundary_pair_values_dual(
        labels, bmap, ignore_label=ignore_label, inner_shape=inner_shape)
    return (jnp.concatenate([u, u]), jnp.concatenate([v, v]),
            jnp.concatenate([va, vb]), jnp.concatenate([ok, ok]))


def affinity_pair_values(labels: jnp.ndarray, affs: jnp.ndarray,
                         offsets: Sequence[Sequence[int]],
                         ignore_label: bool = True,
                         inner_begin: Optional[Tuple[int, ...]] = None,
                         inner_shape: Optional[Tuple[int, ...]] = None):
    """Pairs + affinity samples for long-range offset channels.

    ``affs`` has shape (n_channels,) + labels.shape; channel c holds the
    affinity between anchor voxel i and voxel i + offsets[c].  One sample per
    valid (in-bounds, differing) pair whose *anchor* lies in the inner window
    ``[inner_begin, inner_begin + inner_shape)`` of the (two-sided-haloed)
    local block — each anchor is owned by exactly one block globally
    (reference: ndist extractBlockFeaturesFromAffinityMaps).
    """
    ndim = labels.ndim
    inner = inner_shape or labels.shape
    begin = inner_begin or (0,) * ndim
    us, vs, vals, ok = [], [], [], []
    for c, off in enumerate(offsets):
        sl_a = []
        sl_b = []
        for o, s in zip(off, labels.shape):
            if o >= 0:
                sl_a.append(slice(0, s - o))
                sl_b.append(slice(o, s))
            else:
                sl_a.append(slice(-o, s))
                sl_b.append(slice(0, s + o))
        a = labels[tuple(sl_a)]
        b = labels[tuple(sl_b)]
        fv = affs[c][tuple(sl_a)]
        valid = a != b
        if ignore_label:
            valid &= (a != 0) & (b != 0)
        for ax2 in range(ndim):
            # anchor position in the local (haloed) frame
            pos = jnp.arange(a.shape[ax2]) + sl_a[ax2].start
            owned = (pos >= begin[ax2]) & (pos < begin[ax2] + inner[ax2])
            shape = [1] * ndim
            shape[ax2] = a.shape[ax2]
            valid &= owned.reshape(shape)
        u = jnp.minimum(a, b).reshape(-1)
        v = jnp.maximum(a, b).reshape(-1)
        m = valid.reshape(-1)
        us.append(jnp.where(m, u, 0))
        vs.append(jnp.where(m, v, 0))
        vals.append(fv.reshape(-1))
        ok.append(m)
    return (jnp.concatenate(us), jnp.concatenate(vs),
            jnp.concatenate(vals), jnp.concatenate(ok))


# ---------------------------------------------------------------------------
# device-side segmented statistics
# ---------------------------------------------------------------------------
#
# The padded (u, v, value, ok) arrays are ~10x the block size; shipping them
# to the host made feature extraction transfer-bound (tunnel-attached chips
# pay seconds per block).  Instead the per-edge reduction runs ON DEVICE:
# one lexsort groups samples by edge (and by value within an edge, giving
# exact quantiles), a segmented reduce emits fixed-capacity (e_max) compact
# tables, and only e_max x 12 numbers cross the link.


@partial(jax.jit, static_argnames=("cap",))
def compact_valid(ok, arrays, cap: int):
    """Compact the valid samples of several same-layout arrays into ``cap``
    slots: one shared cumsum computes each valid element's target slot,
    then every channel pays one scatter pass (invalid entries go OUT OF
    BOUNDS, ``mode='drop'`` — an in-bounds dump slot would serialize
    millions of colliding writes on TPU).  Entries past ``cap`` are
    counted in the overflow return.

    Each scatter is an O(n) pass (~0.3 s at the fused block's ~40M pair
    elements), so hot paths should MINIMIZE CHANNELS by packing several
    small fields into one int32 (see
    :func:`_edge_stats_hist_packed` — the uint8 flagship path packs
    (u,v) and (byte_a,byte_b) into two channels).  Gather-based
    alternatives were measured and rejected on real blocks: a
    ``searchsorted`` position discovery costs ~3.9 s (26 binary-search
    rounds of random gathers from the 156 MB cumsum) and row-scatter of
    an (n, 4) operand ~2.7 s.

    Returns ``(compacted_list, cok, overflow)`` (slot s holds the s-th
    valid sample; ``cok`` flags the populated slots)."""
    idx = jnp.cumsum(ok.astype(jnp.int32)) - 1
    tgt = jnp.where(ok & (idx < cap), idx, cap + 1)
    n_valid = jnp.sum(ok.astype(jnp.int32))
    cok = jnp.arange(cap, dtype=jnp.int32) < jnp.minimum(n_valid, cap)
    return ([jnp.zeros((cap + 1,), x.dtype).at[tgt].set(
        x, mode="drop")[:cap] for x in arrays],
        cok, jnp.maximum(n_valid - cap, 0))


@partial(jax.jit, static_argnames=("e_max",))
def _edge_stats_device(u, v, values, ok, e_max: int):
    n = u.shape[0]
    big = jnp.int32(2 ** 31 - 1)
    u_s = jnp.where(ok, u, big)
    v_s = jnp.where(ok, v, big)
    order = jnp.lexsort((values, v_s, u_s))
    u_o, v_o = u_s[order], v_s[order]
    x = values[order].astype(jnp.float32)
    valid = u_o != big
    prev_u = jnp.concatenate([jnp.full((1,), -1, u_o.dtype), u_o[:-1]])
    prev_v = jnp.concatenate([jnp.full((1,), -1, v_o.dtype), v_o[:-1]])
    starts = ((u_o != prev_u) | (v_o != prev_v)) & valid
    run_id = jnp.cumsum(starts.astype(jnp.int32)) - 1
    n_runs = run_id[-1] + 1
    # invalid samples and run overflow land in the dump bin e_max
    run_id = jnp.where(valid & (run_id < e_max), run_id, e_max)

    num = e_max + 1
    ones = jnp.where(run_id < e_max, 1.0, 0.0)
    count = jax.ops.segment_sum(
        jnp.where(run_id < e_max, 1, 0), run_id,
        num_segments=num).astype(jnp.float32)
    s1 = jax.ops.segment_sum(x * ones, run_id, num_segments=num)
    mn = jax.ops.segment_min(jnp.where(run_id < e_max, x, jnp.inf), run_id,
                             num_segments=num)
    mx = jax.ops.segment_max(jnp.where(run_id < e_max, x, -jnp.inf), run_id,
                             num_segments=num)
    pos = jnp.arange(n, dtype=jnp.int32)
    start_pos = jax.ops.segment_min(jnp.where(starts, pos, n), run_id,
                                    num_segments=num)
    uv_u = jax.ops.segment_min(jnp.where(run_id < e_max, u_o, big), run_id,
                               num_segments=num)
    uv_v = jax.ops.segment_min(jnp.where(run_id < e_max, v_o, big), run_id,
                               num_segments=num)

    cnt = count[:e_max]
    denom = jnp.maximum(cnt, 1.0)
    mean = s1[:e_max] / denom
    # variance via the centered second pass: the raw sum-of-squares form
    # cancels catastrophically in float32 for low-variance edges
    mean_full = jnp.concatenate([mean, jnp.zeros((1,), mean.dtype)])
    centered = (x - mean_full[run_id]) ** 2
    s2c = jax.ops.segment_sum(centered * ones, run_id, num_segments=num)
    var = jnp.maximum(s2c[:e_max] / denom, 0.0)
    sp = start_pos[:e_max]
    last = jnp.clip(sp + cnt.astype(jnp.int32) - 1, 0, n - 1)
    qs = []
    for q in _QS:
        # keep the base position integral: sp + float(q*(cnt-1)) promotes to
        # float32 and loses whole indices beyond 2**24 samples
        qoff = q * (cnt - 1.0)          # bounded by the run length: f32-safe
        lo_off = jnp.floor(qoff)
        lo = jnp.clip(sp + lo_off.astype(jnp.int32), 0, n - 1)
        hi = jnp.minimum(lo + 1, last)
        frac = qoff - lo_off
        qs.append(x[lo] * (1.0 - frac) + x[hi] * frac)
    feats = jnp.stack(
        [mean, var, mn[:e_max]] + qs + [mx[:e_max], cnt], axis=1)
    uv = jnp.stack([uv_u[:e_max], uv_v[:e_max]], axis=1)
    overflow = jnp.sum(jnp.where((run_id == e_max) & valid, 1, 0))
    return uv, feats, jnp.minimum(n_runs, e_max), overflow


def boundary_pair_values_dual(labels: jnp.ndarray, bmap: jnp.ndarray,
                              ignore_label: bool = True,
                              inner_shape: Optional[Tuple[int, ...]] = None):
    """Like :func:`boundary_pair_values` but each face pair appears ONCE
    with BOTH side samples as separate columns — half the pair-array
    length, so the downstream compaction passes touch half the elements.
    Returns (u, v, value_a, value_b, valid).  This is the CORE extractor:
    the two-sample variant is a thin expansion of it, so the
    face-ownership convention lives in exactly one place."""
    ndim = labels.ndim
    us, vs, va, vb, ok = [], [], [], [], []
    inner = inner_shape or labels.shape
    for axis in range(ndim):
        size = labels.shape[axis] - 1
        if size <= 0:
            continue
        lo_sl, hi_sl = _axis_slices(ndim, axis, size)
        a, b = labels[lo_sl], labels[hi_sl]
        fa, fb = bmap[lo_sl], bmap[hi_sl]
        valid = a != b
        if ignore_label:
            valid &= (a != 0) & (b != 0)
        for ax2 in range(ndim):
            lim = inner[ax2] if ax2 != axis else min(inner[ax2], size)
            if a.shape[ax2] > lim:
                idx = jnp.arange(a.shape[ax2]) < lim
                shape = [1] * ndim
                shape[ax2] = a.shape[ax2]
                valid &= idx.reshape(shape)
        u = jnp.minimum(a, b).reshape(-1)
        v = jnp.maximum(a, b).reshape(-1)
        m = valid.reshape(-1)
        us.append(jnp.where(m, u, 0))
        vs.append(jnp.where(m, v, 0))
        va.append(fa.reshape(-1))
        vb.append(fb.reshape(-1))
        ok.append(m)
    return (jnp.concatenate(us), jnp.concatenate(vs),
            jnp.concatenate(va), jnp.concatenate(vb), jnp.concatenate(ok))


def plane_face_pairs(lab_a: jnp.ndarray, lab_b: jnp.ndarray,
                     valid: Optional[jnp.ndarray] = None,
                     ignore_label: bool = True):
    """Face pairs between two OPPOSING boundary planes of adjacent
    subproblems (blocks or mesh shards): ``lab_a[i]`` and ``lab_b[i]``
    are the labels of the two voxels straddling the face.  This is the
    device-side form of the host face scan in FusedFaceAssembly — the
    mesh-resident program feeds it the ``ppermute``-received neighbor
    plane, so cross-shard edges join the same collective edge-feature
    reduction as interior pairs instead of a host stitching pass.

    Returns flat ``(u, v, ok)`` with u < v for valid entries (the pair
    (i, i+1) belongs to the subproblem owning voxel i — the reference's
    ownership rule; the caller masks out subproblems without a real
    upper neighbor via ``valid``)."""
    ok = lab_a != lab_b
    if ignore_label:
        ok &= (lab_a != 0) & (lab_b != 0)
    if valid is not None:
        ok &= valid
    u = jnp.minimum(lab_a, lab_b).reshape(-1)
    v = jnp.maximum(lab_a, lab_b).reshape(-1)
    m = ok.reshape(-1)
    return jnp.where(m, u, 0), jnp.where(m, v, 0), m


def _hist_finish(hist, u_o, v_o, run_id, valid, n_runs, e_max: int):
    """Shared tail of the histogram edge statistics: exact
    mean/var/min/max and position-interpolated quantiles from per-edge
    256-bin histograms (hist still carries the flat dump bin), plus the
    per-edge (u, v) and overflow accounting.  One implementation for the
    single- and dual-sample front ends — the stats math must stay
    bit-compatible between them."""
    big = jnp.int32(2 ** 31 - 1)
    num = e_max + 1
    hist = hist[:e_max * 256].reshape(e_max, 256).astype(jnp.float32)
    cnt = hist.sum(axis=1)
    denom = jnp.maximum(cnt, 1.0)
    levels = (jnp.arange(256, dtype=jnp.float32) / 255.0)
    mean = (hist @ levels) / denom
    # centered second moment (the raw sum-of-squares form cancels
    # catastrophically in float32 for low-variance edges)
    diff = levels[None, :] - mean[:, None]
    var = jnp.maximum((hist * diff * diff).sum(axis=1) / denom, 0.0)
    has = hist > 0
    first = jnp.argmax(has, axis=1)
    last = 255 - jnp.argmax(has[:, ::-1], axis=1)
    mn = jnp.where(cnt > 0, levels[first], jnp.inf)
    mx = jnp.where(cnt > 0, levels[last], -jnp.inf)
    cum = jnp.cumsum(hist, axis=1)

    def value_at(pos):
        # value of the pos-th (0-based) sample in the edge's sorted
        # multiset: first bin whose cumulative count exceeds pos
        idx = jnp.sum((cum <= pos[:, None]).astype(jnp.int32), axis=1)
        return levels[jnp.clip(idx, 0, 255)]

    qs = []
    for q in _QS:
        qoff = q * (cnt - 1.0)
        lo_off = jnp.floor(qoff)
        frac = qoff - lo_off
        lo_v = value_at(lo_off)
        hi_v = value_at(jnp.minimum(lo_off + 1.0, cnt - 1.0))
        qs.append(lo_v * (1.0 - frac) + hi_v * frac)

    uv_u = jax.ops.segment_min(jnp.where(run_id < e_max, u_o, big), run_id,
                               num_segments=num)
    uv_v = jax.ops.segment_min(jnp.where(run_id < e_max, v_o, big), run_id,
                               num_segments=num)
    feats = jnp.stack([mean, var, mn] + qs + [mx, cnt], axis=1)
    uv = jnp.stack([uv_u[:e_max], uv_v[:e_max]], axis=1)
    overflow = jnp.sum(jnp.where((run_id == e_max) & valid, 1, 0))
    return uv, feats, jnp.minimum(n_runs, e_max), overflow


@partial(jax.jit, static_argnames=("e_max",))
def _edge_stats_hist_dual(u, v, bins_a_u8, bins_b_u8, ok, e_max: int):
    """Histogram edge statistics over DUAL-sample pairs (each compacted
    slot carries the boundary bytes of both face sides): identical
    results to :func:`_edge_stats_hist_device` fed the two-sample
    expansion, at half the grouping-sort length."""
    n = u.shape[0]
    big = jnp.int32(2 ** 31 - 1)
    u_s = jnp.where(ok, u, big)
    v_s = jnp.where(ok, v, big)
    order = jnp.lexsort((v_s, u_s))
    u_o, v_o = u_s[order], v_s[order]
    ba = bins_a_u8[order].astype(jnp.int32)
    bb = bins_b_u8[order].astype(jnp.int32)
    valid = u_o != big
    prev_u = jnp.concatenate([jnp.full((1,), -1, u_o.dtype), u_o[:-1]])
    prev_v = jnp.concatenate([jnp.full((1,), -1, v_o.dtype), v_o[:-1]])
    starts = ((u_o != prev_u) | (v_o != prev_v)) & valid
    run_id = jnp.cumsum(starts.astype(jnp.int32)) - 1
    n_runs = run_id[-1] + 1
    run_id = jnp.where(valid & (run_id < e_max), run_id, e_max)

    ones = jnp.ones((n,), jnp.int32)
    hidx_a = jnp.where(run_id < e_max, run_id * 256 + ba, e_max * 256)
    hidx_b = jnp.where(run_id < e_max, run_id * 256 + bb, e_max * 256)
    hist = (jax.ops.segment_sum(ones, hidx_a,
                                num_segments=e_max * 256 + 1)
            + jax.ops.segment_sum(ones, hidx_b,
                                  num_segments=e_max * 256 + 1))
    return _hist_finish(hist, u_o, v_o, run_id, valid, n_runs, e_max)


@partial(jax.jit, static_argnames=("e_max",))
def _edge_stats_hist_packed(key, vab, ok, e_max: int):
    """Histogram edge statistics over PACKED dual-sample pairs: ``key``
    carries ``u * 32768 + v`` (requires every dense label < 2^15 — the
    caller guards this; any block that dense would overflow ``e_max``
    anyway) and ``vab`` carries ``byte_a * 256 + byte_b``.  Identical
    results to :func:`_edge_stats_hist_dual`, but the compaction upstream
    pays TWO scatter passes instead of four and the grouping sort is a
    single-key sort with one payload operand instead of a two-key
    lexsort — the pair-statistics stage was the hottest piece of the
    fused block program (calibration r5: 1.56 s of the 2.8 s block)."""
    n = key.shape[0]
    big = jnp.int32(2 ** 31 - 1)
    k_s = jnp.where(ok, key, big)
    k_o, vab_o = jax.lax.sort([k_s, vab], num_keys=1)
    valid = k_o != big
    prev = jnp.concatenate([jnp.full((1,), -1, k_o.dtype), k_o[:-1]])
    starts = (k_o != prev) & valid
    run_id = jnp.cumsum(starts.astype(jnp.int32)) - 1
    n_runs = run_id[-1] + 1
    run_id = jnp.where(valid & (run_id < e_max), run_id, e_max)

    ba = vab_o >> 8
    bb = vab_o & 255
    ones = jnp.ones((n,), jnp.int32)
    hidx_a = jnp.where(run_id < e_max, run_id * 256 + ba, e_max * 256)
    hidx_b = jnp.where(run_id < e_max, run_id * 256 + bb, e_max * 256)
    hist = (jax.ops.segment_sum(ones, hidx_a,
                                num_segments=e_max * 256 + 1)
            + jax.ops.segment_sum(ones, hidx_b,
                                  num_segments=e_max * 256 + 1))
    u_o = k_o >> 15
    v_o = k_o & 32767
    return _hist_finish(hist, u_o, v_o, run_id, valid, n_runs, e_max)


@partial(jax.jit, static_argnames=("e_max",))
def _edge_stats_hist_device(u, v, bins_u8, ok, e_max: int):
    """Per-edge statistics via 256-bin histograms — EXACT for uint8
    boundary maps (the reference's CNN-output convention), and ~2x
    cheaper than :func:`_edge_stats_device`: the lexsort drops the value
    key (2-key grouping sort instead of 3-key full sort) and quantiles
    come from per-edge histogram cumsums instead of sorted-position
    gathers, reproducing the same position-interpolation formula
    (``q*(cnt-1)`` with linear interpolation) bit-compatibly for
    discrete values."""
    n = u.shape[0]
    big = jnp.int32(2 ** 31 - 1)
    u_s = jnp.where(ok, u, big)
    v_s = jnp.where(ok, v, big)
    order = jnp.lexsort((v_s, u_s))
    u_o, v_o = u_s[order], v_s[order]
    b = bins_u8[order].astype(jnp.int32)
    valid = u_o != big
    prev_u = jnp.concatenate([jnp.full((1,), -1, u_o.dtype), u_o[:-1]])
    prev_v = jnp.concatenate([jnp.full((1,), -1, v_o.dtype), v_o[:-1]])
    starts = ((u_o != prev_u) | (v_o != prev_v)) & valid
    run_id = jnp.cumsum(starts.astype(jnp.int32)) - 1
    n_runs = run_id[-1] + 1
    run_id = jnp.where(valid & (run_id < e_max), run_id, e_max)

    hidx = jnp.where(run_id < e_max, run_id * 256 + b, e_max * 256)
    hist = jax.ops.segment_sum(jnp.ones((n,), jnp.int32), hidx,
                               num_segments=e_max * 256 + 1)
    return _hist_finish(hist, u_o, v_o, run_id, valid, n_runs, e_max)


def device_edge_stats(u, v, values, ok, e_max: int = 65536):
    """Compact per-edge statistics computed on device.

    Returns (uv [E, 2] int32 dense labels, features [E, 10] float64) with
    E = number of distinct valid edges; raises when the block holds more
    than ``e_max`` edges (raise e_max or shrink blocks).

    Inputs are padded to the next power of two so every (clipped) border
    block shares one compiled program — per-shape compiles of the sort
    kernel cost ~a minute each on tunnel-attached devices."""
    return device_edge_stats_finalize(
        device_edge_stats_submit(u, v, values, ok, e_max=e_max), e_max)


def _pad_pow2(arr, n_pad, fill=None):
    n = int(arr.shape[0])
    if n == n_pad:
        return arr
    if fill is None:
        return jnp.pad(arr, (0, n_pad - n))
    return jnp.pad(arr, (0, n_pad - n), constant_values=fill)


def _should_compact(n: int, compact: Optional[bool]) -> bool:
    import os

    if compact is not None:
        return compact
    return (n >= (1 << 20)
            and os.environ.get("CTT_RAG_COMPACT", "1") != "0")


def device_edge_stats_submit(u, v, values, ok, e_max: int = 65536,
                             compact: Optional[bool] = None):
    """Enqueue the edge-stats device program WITHOUT synchronizing: returns
    the device result handles so callers can pipeline several blocks (jax
    async dispatch overlaps block i+1's compute with block i's readback —
    per-block device latency dominates on tunnel-attached chips).  Pass the
    handles to :func:`device_edge_stats_finalize`.

    Large sample arrays (>= 2^20, after the shared power-of-two padding
    that keeps the compile classes bounded) are first COMPACTED to the
    valid entries: the sort then runs on n/4 instead of n.  Semantics are
    identical — the stats sort re-orders everything anyway.  A capacity
    overflow (boundary fraction > 25% of all samples — pathological for
    label volumes) raises at finalize; set ``compact=False`` or
    ``CTT_RAG_COMPACT=0`` for such inputs."""
    return device_edge_stats_submit_multi(
        u, v, ok, [values], e_max=e_max, compact=compact)[0]


def device_edge_stats_submit_multi(u, v, ok, values_list,
                                   e_max: int = 65536,
                                   compact: Optional[bool] = None):
    """Like :func:`device_edge_stats_submit` for SEVERAL value channels
    sharing one (u, v, ok) pair layout (the filter-bank features path):
    the pair padding and compaction targets are computed once and every
    channel only pays its own scatter + sort."""
    n = int(u.shape[0])
    n_pad = 1 << max(int(np.ceil(np.log2(max(n, 1)))), 4)
    u = _pad_pow2(u, n_pad)
    v = _pad_pow2(v, n_pad)
    ok = _pad_pow2(ok, n_pad, fill=False)
    if _should_compact(n_pad, compact):
        cap = max(n_pad // 4, 1 << 14)
        (compacted, cok, overflow) = compact_valid(
            ok, [u, v] + [_pad_pow2(x, n_pad) for x in values_list], cap)
        cu, cv = compacted[0], compacted[1]
        return [("compact",
                 _edge_stats_device(cu, cv, cx, cok, e_max=e_max),
                 overflow, cap)
                for cx in compacted[2:]]
    return [("full",
             _edge_stats_device(u, v, _pad_pow2(x, n_pad), ok, e_max=e_max))
            for x in values_list]


def device_edge_stats_finalize(handles, e_max: int = 65536):
    """Synchronize one submitted edge-stats program and return the compact
    host (uv, features) tables."""
    if handles[0] == "compact":
        _, inner, cap_overflow, cap = handles
        if int(cap_overflow) > 0:
            raise RuntimeError(
                f"boundary samples exceeded the compaction capacity {cap} "
                "(boundary fraction > 25%); pass compact=False or set "
                "CTT_RAG_COMPACT=0 for this volume")
        handles = ("full", inner)
    uv, feats, n_runs, overflow = handles[1]
    if int(overflow) > 0:
        raise RuntimeError(
            f"block has more than e_max={e_max} distinct edges; "
            "increase e_max or use smaller blocks")
    n = int(n_runs)
    return (np.asarray(uv)[:n].astype("int64"),
            np.asarray(feats)[:n].astype("float64"))


def device_unique_edges(u, v, ok, e_max: int = 65536) -> np.ndarray:
    """Compact unique (u, v) edge list computed on device (the RAG
    extraction reduction; same sort machinery, no values).

    Synchronous convenience API: blocks on the device result.  Pipelined
    callers should use :func:`device_edge_stats_submit` /
    :func:`device_edge_stats_finalize` instead (as InitialSubGraphs does)
    so consecutive blocks overlap."""
    uv, _ = device_edge_stats(u, v, jnp.zeros_like(u, jnp.float32), ok,
                               e_max=e_max)
    return uv


# ---------------------------------------------------------------------------
# host-side pair extraction (the reference-faithful CPU path: plain numpy
# slicing, compact output — selected by task config ``impl: 'host'``)
# ---------------------------------------------------------------------------


def _host_axis_pairs(labels: np.ndarray, ignore_label: bool,
                     inner_shape) -> List[Tuple[np.ndarray, ...]]:
    ndim = labels.ndim
    inner = inner_shape or labels.shape
    out = []
    for axis in range(ndim):
        size = labels.shape[axis] - 1
        if size <= 0:
            continue
        lo = [slice(None)] * ndim
        hi = [slice(None)] * ndim
        lo[axis] = slice(0, size)
        hi[axis] = slice(1, size + 1)
        a, b = labels[tuple(lo)], labels[tuple(hi)]
        valid = a != b
        if ignore_label:
            valid &= (a != 0) & (b != 0)
        for ax2 in range(ndim):
            lim = inner[ax2] if ax2 != axis else min(inner[ax2], size)
            if a.shape[ax2] > lim:
                sl = [slice(None)] * ndim
                sl[ax2] = slice(lim, None)
                valid[tuple(sl)] = False
        out.append((a, b, valid, tuple(lo)))
    return out


def host_label_pairs(labels: np.ndarray, ignore_label: bool = True,
                     inner_shape=None) -> np.ndarray:
    """Numpy analog of :func:`label_pairs` + dedup: the compact sorted
    (u, v) edge table of the block, computed entirely on host."""
    pairs = []
    for a, b, valid, _ in _host_axis_pairs(labels, ignore_label,
                                           inner_shape):
        av, bv = a[valid], b[valid]
        pairs.append(np.stack([np.minimum(av, bv), np.maximum(av, bv)],
                              axis=1))
    if not pairs:
        return np.zeros((0, 2), "uint64")
    return np.unique(np.concatenate(pairs), axis=0)


def host_boundary_edge_features(labels: np.ndarray, bmap: np.ndarray,
                                ignore_label: bool = True,
                                inner_shape=None
                                ) -> Tuple[np.ndarray, np.ndarray]:
    """Numpy analog of boundary_pair_values + device_edge_stats: per-edge
    (uv, features) tables via :func:`segmented_stats` (two samples per face
    voxel pair, the nifty gridRag convention)."""
    ndim = labels.ndim
    us, vs, xs = [], [], []
    for a, b, valid, lo in _host_axis_pairs(labels, ignore_label,
                                            inner_shape):
        axis = next(d for d in range(ndim) if lo[d] != slice(None))
        hi_sl = list(lo)
        hi_sl[axis] = slice(1, a.shape[axis] + 1)
        av, bv = a[valid], b[valid]
        u, v = np.minimum(av, bv), np.maximum(av, bv)
        for side in (lo, tuple(hi_sl)):
            us.append(u)
            vs.append(v)
            xs.append(bmap[side][valid])
    if not us:
        return np.zeros((0, 2), "int64"), np.zeros((0, N_FEATURES),
                                                   "float64")
    u = np.concatenate(us)
    v = np.concatenate(vs)
    x = np.concatenate(xs).astype("float64")
    uv = np.stack([u, v], axis=1)
    uniq, inv = np.unique(uv, axis=0, return_inverse=True)
    feats = segmented_stats(inv, x, len(uniq))
    return uniq.astype("int64"), feats


# ---------------------------------------------------------------------------
# host-side segmented statistics (fallback / oracle for tests)
# ---------------------------------------------------------------------------

FEATURE_NAMES = ("mean", "variance", "min", "q10", "q25", "q50", "q75", "q90",
                 "max", "count")
N_FEATURES = len(FEATURE_NAMES)
_QS = (0.1, 0.25, 0.5, 0.75, 0.9)


def unique_pairs(u: np.ndarray, v: np.ndarray):
    """Deduplicated ``(u, v)`` rows plus inverse indices.

    Packed-u64-key path (``np.unique`` on a 1-D key array is ~10x the
    ``axis=0`` structured sort at face-table sizes) with a structured
    fallback for ids past 2^32.  ONE home for the idiom — the fused face
    assembly and the server's in-memory tail both merge edge tables
    through it."""
    u = np.asarray(u)
    v = np.asarray(v)
    if len(v) == 0:
        return np.zeros((0, 2), "uint64"), np.zeros((0,), "int64")
    if v.max() < (1 << 32):
        keys = (u.astype("uint64") << np.uint64(32)) | v.astype("uint64")
        ukeys, inv = np.unique(keys, return_inverse=True)
        uniq = np.stack([ukeys >> np.uint64(32),
                         ukeys & np.uint64(0xFFFFFFFF)], axis=1)
    else:
        pairs = np.stack([u.astype("uint64"), v.astype("uint64")], axis=1)
        uniq, inv = np.unique(pairs, axis=0, return_inverse=True)
    return uniq.astype("uint64"), inv


def segmented_stats(edge_index: np.ndarray, values: np.ndarray,
                    n_edges: int) -> np.ndarray:
    """Per-edge [mean, var, min, q10..q90, max, count] over samples.

    Sort-based: one lexsort by (edge, value), then reduceat for moments and
    fractional indexing for exact interpolated quantiles per segment.
    """
    out = np.zeros((n_edges, N_FEATURES), dtype="float64")
    if len(edge_index) == 0:
        return out
    order = np.lexsort((values, edge_index))
    e = edge_index[order]
    x = values[order].astype("float64")
    starts = np.flatnonzero(np.r_[True, e[1:] != e[:-1]])
    seg_ids = e[starts]
    counts = np.diff(np.r_[starts, len(e)])
    sums = np.add.reduceat(x, starts)
    sqs = np.add.reduceat(x * x, starts)
    mean = sums / counts
    var = np.maximum(sqs / counts - mean ** 2, 0.0)
    out[seg_ids, 0] = mean
    out[seg_ids, 1] = var
    out[seg_ids, 2] = x[starts]                      # min (sorted within seg)
    out[seg_ids, 8] = x[starts + counts - 1]         # max
    for qi, q in enumerate(_QS):
        pos = starts + q * (counts - 1)
        lo = np.floor(pos).astype(np.int64)
        hi = np.minimum(lo + 1, starts + counts - 1)
        frac = pos - lo
        out[seg_ids, 3 + qi] = x[lo] * (1 - frac) + x[hi] * frac
    out[seg_ids, 9] = counts
    return out


def merge_feature_blocks(partials: Sequence[Tuple[np.ndarray, np.ndarray]],
                         n_edges: int, n_feats: int = N_FEATURES
                         ) -> np.ndarray:
    """Combine per-block feature rows into global per-edge features.

    ``partials`` = iterable of (edge_ids, features[E_b, n_feats]), where the
    columns are one or more 9-wide stat groups ([mean, variance, min,
    q10, q25, q50, q75, q90, max] — one group per filter response in the
    filter-bank features path) followed by a single shared sample-count
    column.  Mean/variance merge exactly (count-weighted moments); min/max
    elementwise; quantiles merge as count-weighted means — an approximation
    (exact distributed quantiles would need the raw samples; the reference's
    C++ merge makes the same trade, nifty mergeFeatureBlocks).
    """
    n_groups = (n_feats - 1) // 9
    assert n_groups * 9 + 1 == n_feats, n_feats
    cnt = np.zeros(n_edges, "float64")
    s1 = np.zeros((n_edges, n_groups), "float64")    # Σ w·mean
    s2 = np.zeros((n_edges, n_groups), "float64")    # Σ w·(var + mean²)
    mn = np.full((n_edges, n_groups), np.inf)
    mx = np.full((n_edges, n_groups), -np.inf)
    qs = np.zeros((n_edges, n_groups, len(_QS)), "float64")
    for edge_ids, feats in partials:
        # zero-count rows (edges with no samples in this block) must not
        # pollute min/max/moments
        nz = feats[:, -1] > 0
        edge_ids, feats = edge_ids[nz], feats[nz]
        if len(edge_ids) == 0:
            continue
        w = feats[:, -1]
        np.add.at(cnt, edge_ids, w)
        for gi in range(n_groups):
            base = 9 * gi
            np.add.at(s1[:, gi], edge_ids, w * feats[:, base])
            np.add.at(s2[:, gi], edge_ids,
                      w * (feats[:, base + 1] + feats[:, base] ** 2))
            np.minimum.at(mn[:, gi], edge_ids, feats[:, base + 2])
            np.maximum.at(mx[:, gi], edge_ids, feats[:, base + 8])
            for qi in range(len(_QS)):
                np.add.at(qs[:, gi, qi], edge_ids,
                          w * feats[:, base + 3 + qi])
    out = np.zeros((n_edges, n_feats), "float64")
    nz = cnt > 0
    for gi in range(n_groups):
        base = 9 * gi
        out[nz, base] = s1[nz, gi] / cnt[nz]
        out[nz, base + 1] = np.maximum(
            s2[nz, gi] / cnt[nz] - out[nz, base] ** 2, 0.0)
        out[nz, base + 2] = mn[nz, gi]
        out[nz, base + 8] = mx[nz, gi]
        for qi in range(len(_QS)):
            out[nz, base + 3 + qi] = qs[nz, gi, qi] / cnt[nz]
    out[:, -1] = cnt
    return out
