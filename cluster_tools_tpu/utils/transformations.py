"""Affine transformation helpers.

Re-specification of the reference's ``utils/transformation_utils.py``
(2d/3d affine matrix construction :18-113, matrix <-> parameter conversion,
``transform_roi``).  Matrices are homogeneous (ndim+1, ndim+1), acting on
zyx coordinate vectors — the convention of ``TransformedVolume``
(core/volume_views.py)."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np


def matrix_2d(scale: Sequence[float] = (1.0, 1.0), rotation: float = 0.0,
              shear: float = 0.0,
              translation: Sequence[float] = (0.0, 0.0)) -> np.ndarray:
    """Homogeneous 2d affine from parameters (rotation in degrees;
    reference: transformation_utils.py:18-60)."""
    t = np.deg2rad(rotation)
    cos, sin = np.cos(t), np.sin(t)
    mat = np.eye(3)
    mat[0, 0] = scale[0] * cos
    mat[0, 1] = -scale[1] * (sin + shear)
    mat[1, 0] = scale[0] * (sin + shear)
    mat[1, 1] = scale[1] * cos
    mat[:2, 2] = translation
    return mat


def matrix_3d(scale: Sequence[float] = (1.0, 1.0, 1.0),
              rotation: Sequence[float] = (0.0, 0.0, 0.0),
              translation: Sequence[float] = (0.0, 0.0, 0.0)) -> np.ndarray:
    """Homogeneous 3d affine from parameters (Euler zyx rotations in
    degrees; reference: transformation_utils.py:62-113)."""
    a, b, c = np.deg2rad(rotation)
    rz = np.array([[np.cos(a), -np.sin(a), 0],
                   [np.sin(a), np.cos(a), 0], [0, 0, 1]])
    ry = np.array([[np.cos(b), 0, np.sin(b)], [0, 1, 0],
                   [-np.sin(b), 0, np.cos(b)]])
    rx = np.array([[1, 0, 0], [0, np.cos(c), -np.sin(c)],
                   [0, np.sin(c), np.cos(c)]])
    mat = np.eye(4)
    mat[:3, :3] = rz @ ry @ rx @ np.diag(scale)
    mat[:3, 3] = translation
    return mat


def parameters_from_matrix(matrix: np.ndarray):
    """(scale, rotation_degrees, translation) from a homogeneous affine
    (inverse of matrix_2d / matrix_3d for shear-free transforms)."""
    matrix = np.asarray(matrix)
    ndim = matrix.shape[0] - 1
    lin = matrix[:ndim, :ndim]
    translation = matrix[:ndim, ndim].copy()
    scale = np.linalg.norm(lin, axis=0)
    rot = lin / scale[None, :]
    if ndim == 2:
        rotation = float(np.rad2deg(np.arctan2(rot[1, 0], rot[0, 0])))
    else:
        # Euler zyx angles back from the rotation matrix
        ry = -np.arcsin(np.clip(rot[2, 0], -1, 1))
        if abs(np.cos(ry)) < 1e-9:
            # gimbal lock: rz and rx are degenerate; fix rz = 0
            rz = 0.0
            rx = np.arctan2(-rot[1, 2], rot[1, 1])
        else:
            rz = np.arctan2(rot[1, 0] / np.cos(ry), rot[0, 0] / np.cos(ry))
            rx = np.arctan2(rot[2, 1] / np.cos(ry), rot[2, 2] / np.cos(ry))
        rotation = tuple(np.rad2deg([rz, ry, rx]))
    return tuple(scale), rotation, tuple(translation)


def transform_roi(roi_begin: Sequence[float], roi_end: Sequence[float],
                  matrix: np.ndarray) -> Tuple[Tuple[float, ...],
                                               Tuple[float, ...]]:
    """Axis-aligned bounding box of a transformed ROI (reference:
    transformation_utils.py transform_roi): transform all corners, take the
    min/max envelope."""
    matrix = np.asarray(matrix)
    ndim = len(roi_begin)
    corners = []
    for bits in range(2 ** ndim):
        c = [roi_begin[d] if (bits >> d) & 1 == 0 else roi_end[d]
             for d in range(ndim)]
        corners.append(c + [1.0])
    pts = (matrix @ np.asarray(corners).T)[:ndim]
    return tuple(pts.min(axis=1)), tuple(pts.max(axis=1))
