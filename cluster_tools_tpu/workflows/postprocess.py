"""Segmentation post-processing toolbox.

Re-specification of the reference's ``postprocess/`` package
(postprocess_workflow.py:24-420): size filters (background / watershed-fill
modes), id filters over semantic node labels, graph connected components,
graph-watershed reassignment of discarded fragments, orphan merging.

Structure: small blockwise map steps (count sizes, zero out filtered ids,
refill) plus global graph steps over the assignment tables.  The graph
steps reuse the native kernels (graph_watershed, ufd) over flat edge lists;
the per-block refill runs the device seeded watershed.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.blocking import Blocking
from ..core.config import write_config
from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import FileTarget, Task
from .relabel import RelabelWorkflow
from .write import WriteAssignments


def _relabel_consecutive(assignments: np.ndarray) -> np.ndarray:
    """vigra.relabelConsecutive(start_label=1, keep_zeros=True) equivalent."""
    nz = assignments != 0
    uniq = np.unique(assignments[nz])
    out = np.zeros_like(assignments)
    out[nz] = np.searchsorted(uniq, assignments[nz]).astype(
        assignments.dtype) + 1
    return out


class BlockCounts(BlockTask):
    """Per-block label histogram -> block npz (the FindUniques
    return_counts=True analog, reference: relabel/find_uniques.py +
    size_filter_blocks.py:23)."""

    task_name = "block_counts"

    def __init__(self, input_path: str, input_key: str,
                 identifier: str = "", **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.identifier = identifier
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            shape = list(f[self.input_key].shape)
        block_shape = self.global_block_shape()[-len(shape):]
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        f = file_reader(cfg["input_path"], "r")
        ds = f[cfg["input_key"]]
        for block_id in job_config["block_list"]:
            ids, counts = np.unique(ds[blocking.get_block(block_id).bb],
                                    return_counts=True)
            np.savez(os.path.join(
                job_config["tmp_folder"],
                f"{job_config['task_name']}_block_{block_id}.npz"),
                ids=ids.astype("uint64"), counts=counts.astype("uint64"))
            log_fn(f"processed block {block_id}")


def merge_block_counts(tmp_folder: str, prefix: str):
    """Sum the per-block histograms -> (ids, total_counts)."""
    all_ids: List[np.ndarray] = []
    all_counts: List[np.ndarray] = []
    for name in sorted(os.listdir(tmp_folder)):
        if name.startswith(prefix + "_block_") and name.endswith(".npz"):
            with np.load(os.path.join(tmp_folder, name)) as d:
                all_ids.append(d["ids"])
                all_counts.append(d["counts"])
    if not all_ids:
        return np.zeros(0, "uint64"), np.zeros(0, "uint64")
    ids = np.concatenate(all_ids)
    counts = np.concatenate(all_counts)
    uniq, inv = np.unique(ids, return_inverse=True)
    totals = np.zeros(len(uniq), "uint64")
    np.add.at(totals, inv, counts)
    return uniq, totals


class SizeFilterDiscardIds(BlockTask):
    """Global reduce: ids with total size below threshold -> discard npy
    (reference: size_filter_blocks.py)."""

    task_name = "size_filter_discard_ids"
    global_task = True
    allow_retry = False

    def __init__(self, counts_prefix: str, output_path: str,
                 size_threshold: int, identifier: str = "", **kw):
        self.counts_prefix = counts_prefix
        self.output_path = output_path
        self.size_threshold = size_threshold
        self.identifier = identifier
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "counts_prefix": self.counts_prefix,
            "output_path": self.output_path,
            "size_threshold": self.size_threshold,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        ids, totals = merge_block_counts(job_config["tmp_folder"],
                                         cfg["counts_prefix"])
        discard = ids[(totals < cfg["size_threshold"]) & (ids != 0)]
        np.save(cfg["output_path"], discard)
        log_fn(f"discarding {len(discard)} / {len(ids)} ids below size "
               f"{cfg['size_threshold']}")


class FilterBlocksBase(BlockTask):
    """Shared map step: load the discard-id set, zero those ids out blockwise
    (reference: background_size_filter.py:20, filter_blocks.py:25).  The
    filling variant regrows the survivors over a height map instead of
    leaving holes (reference: filling_size_filter.py:21)."""

    filling: bool = False

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, filter_path: str,
                 hmap_path: str = "", hmap_key: str = "", **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.filter_path = filter_path
        self.hmap_path = hmap_path
        self.hmap_key = hmap_key
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            shape = list(f[self.input_key].shape)
        block_shape = self.global_block_shape()[-len(shape):]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=shape,
                              chunks=block_shape, dtype="uint64")
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "filter_path": self.filter_path,
            "hmap_path": self.hmap_path, "hmap_key": self.hmap_key,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        f_in = file_reader(cfg["input_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in, ds_out = f_in[cfg["input_key"]], f_out[cfg["output_key"]]
        if cfg["filter_path"].endswith(".json"):
            with open(cfg["filter_path"]) as f:
                discard = np.asarray(json.load(f), dtype="uint64")
        else:
            discard = np.load(cfg["filter_path"]).astype("uint64")
        discard = np.sort(discard)
        ds_hmap = None
        if cls.filling and cfg.get("hmap_path"):
            ds_hmap = file_reader(cfg["hmap_path"], "r")[cfg["hmap_key"]]

        for block_id in job_config["block_list"]:
            bb = blocking.get_block(block_id).bb
            seg = np.asarray(ds_in[bb])
            if len(discard):
                idx = np.searchsorted(discard, seg)
                hit = (idx < len(discard)) & (
                    discard[np.minimum(idx, len(discard) - 1)] == seg)
                seg = np.where(hit, np.uint64(0), seg)
            if ds_hmap is not None and (seg == 0).any() and (seg != 0).any():
                seg = cls._fill(seg, np.asarray(ds_hmap[bb]).astype("float32"))
            ds_out[bb] = seg
            log_fn(f"processed block {block_id}")

    @staticmethod
    def _fill(seg: np.ndarray, hmap: np.ndarray) -> np.ndarray:
        """Regrow surviving labels into the zeroed voxels over the height
        map (device seeded watershed — the watershedsNew fill of
        filling_size_filter.py)."""
        import jax.numpy as jnp

        from ..ops.rag import densify_labels
        from ..ops.watershed import seeded_watershed

        lut, dense = densify_labels(seg)
        ws = np.asarray(seeded_watershed(jnp.asarray(hmap),
                                         jnp.asarray(dense)))
        return lut[ws]


class BackgroundSizeFilter(FilterBlocksBase):
    task_name = "background_size_filter"
    filling = False


class FillingSizeFilter(FilterBlocksBase):
    task_name = "filling_size_filter"
    filling = True


class FilterBlocks(FilterBlocksBase):
    """Zero out an explicit id list (json) blockwise (reference:
    filter_blocks.py:25)."""

    task_name = "filter_blocks"
    filling = False


class IdFilter(BlockTask):
    """Find node ids whose (max-overlap) semantic label is in
    ``filter_labels`` -> json id list (reference: id_filter.py:22)."""

    task_name = "id_filter"
    global_task = True
    allow_retry = False

    def __init__(self, node_label_path: str, node_label_key: str,
                 output_path: str, filter_labels: Sequence[int], **kw):
        self.node_label_path = node_label_path
        self.node_label_key = node_label_key
        self.output_path = output_path
        self.filter_labels = list(filter_labels)
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "node_label_path": self.node_label_path,
            "node_label_key": self.node_label_key,
            "output_path": self.output_path,
            "filter_labels": self.filter_labels,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        with file_reader(cfg["node_label_path"], "r") as f:
            node_labels = f[cfg["node_label_key"]][:]
        filter_mask = np.isin(node_labels,
                              np.asarray(cfg["filter_labels"], "uint64"))
        filter_ids = np.flatnonzero(filter_mask)
        write_config(cfg["output_path"], [int(i) for i in filter_ids])
        log_fn(f"filtering {len(filter_ids)} / {len(node_labels)} ids")


class GraphWatershedAssignments(BlockTask):
    """Re-assign discarded fragments by seeded graph watershed over the RAG
    edge weights (reference: graph_watershed_assignments.py:100-180)."""

    task_name = "graph_watershed_assignments"
    global_task = True
    allow_retry = False

    def __init__(self, problem_path: str, graph_key: str, features_key: str,
                 assignment_path: str, assignment_key: str, output_path: str,
                 output_key: str, filter_nodes_path: str,
                 relabel: bool = False, from_costs: bool = False, **kw):
        self.problem_path = problem_path
        self.graph_key = graph_key
        self.features_key = features_key
        self.assignment_path = assignment_path
        self.assignment_key = assignment_key
        self.output_path = output_path
        self.output_key = output_key
        self.filter_nodes_path = filter_nodes_path
        self.relabel = relabel
        self.from_costs = from_costs
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "problem_path": self.problem_path, "graph_key": self.graph_key,
            "features_key": self.features_key,
            "assignment_path": self.assignment_path,
            "assignment_key": self.assignment_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "filter_nodes_path": self.filter_nodes_path,
            "relabel": self.relabel, "from_costs": self.from_costs,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from .. import native
        from ..core.graph import load_graph

        cfg = job_config["config"]
        nodes, uv_ids, _ = load_graph(cfg["problem_path"], cfg["graph_key"])
        n_nodes = max(int(nodes.max()) + 1 if len(nodes) else 0,
                      int(uv_ids.max()) + 1 if len(uv_ids) else 0)
        with file_reader(cfg["problem_path"], "r") as f:
            ds = f[cfg["features_key"]]
            feats = (ds[:, 0] if len(ds.shape) == 2 else ds[:]).astype(
                "float64").squeeze()
        if cfg["from_costs"]:
            # costs (attractive > 0) -> [0, 1] boundary probabilities
            feats = feats - feats.min()
            mx = feats.max()
            if mx > 0:
                feats = feats / mx
            feats = 1.0 - feats
        with file_reader(cfg["assignment_path"], "r") as f:
            assignments = f[cfg["assignment_key"]][:].astype("uint64")
        if n_nodes != len(assignments):
            raise ValueError(
                f"graph has {n_nodes} nodes but assignment table has "
                f"{len(assignments)} entries")

        discard_ids = np.load(cfg["filter_nodes_path"])
        if (discard_ids == 0).any():
            raise ValueError("discard ids must not contain the ignore label")
        # temporarily alias segment 0 so background survives the watershed
        seed_offset = np.uint64(int(assignments.max()) + 1)
        assignments[assignments == 0] = seed_offset
        discard_mask = np.isin(assignments, discard_ids.astype("uint64"))
        assignments[discard_mask] = 0
        log_fn(f"discarding {int(discard_mask.sum())} fragments")

        assignments = native.graph_watershed(
            n_nodes, uv_ids, feats, assignments, grow_smallest_first=True)
        assignments[assignments == seed_offset] = 0
        if cfg["relabel"]:
            assignments = _relabel_consecutive(assignments)
        with file_reader(cfg["output_path"]) as f:
            f.require_dataset(cfg["output_key"], data=assignments,
                              chunks=(min(int(1e5), len(assignments)),))
        log_fn(f"graph watershed reassigned; "
               f"{len(np.unique(assignments))} segments")


class OrphanAssignments(BlockTask):
    """Merge degree-one segments into their single neighbor (reference:
    orphan_assignments.py:95-150)."""

    task_name = "orphan_assignments"
    global_task = True
    allow_retry = False

    def __init__(self, graph_path: str, graph_key: str, assignment_path: str,
                 assignment_key: str, output_path: str, output_key: str,
                 relabel: bool = False, **kw):
        self.graph_path = graph_path
        self.graph_key = graph_key
        self.assignment_path = assignment_path
        self.assignment_key = assignment_key
        self.output_path = output_path
        self.output_key = output_key
        self.relabel = relabel
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "graph_path": self.graph_path, "graph_key": self.graph_key,
            "assignment_path": self.assignment_path,
            "assignment_key": self.assignment_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "relabel": self.relabel,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from ..core.graph import load_graph, unique_edges

        cfg = job_config["config"]
        _, uv_ids, _ = load_graph(cfg["graph_path"], cfg["graph_key"])
        with file_reader(cfg["assignment_path"], "r") as f:
            assignments = f[cfg["assignment_key"]][:].astype("uint64")

        # segment-level graph (nt.EdgeMapping newUvIds equivalent)
        seg_u = assignments[uv_ids[:, 0]]
        seg_v = assignments[uv_ids[:, 1]]
        keep = (seg_u != seg_v) & (seg_u != 0) & (seg_v != 0)
        new_uv = unique_edges(seg_u[keep], seg_v[keep])
        ids, degrees = np.unique(new_uv, return_counts=True)
        orphans = ids[degrees == 1]
        log_fn(f"found {len(orphans)} orphans of "
               f"{len(np.unique(assignments))} segments")
        if len(orphans):
            # each orphan has exactly one incident edge; remap it to its
            # partner via a flat lookup table (one pass over the volume ids)
            seg_max = int(max(int(assignments.max()), int(new_uv.max())))
            remap = np.arange(seg_max + 1, dtype="uint64")
            lookup = np.sort(orphans)
            hits = []
            for col in (0, 1):
                idx = np.searchsorted(lookup, new_uv[:, col])
                hits.append((idx < len(lookup)) & (
                    lookup[np.minimum(idx, len(lookup) - 1)]
                    == new_uv[:, col]))
            # mutual-orphan pairs (their only edge is to each other) would
            # just swap labels — merge them to the smaller id instead
            both = hits[0] & hits[1]
            remap[new_uv[hits[0] & ~both, 0]] = new_uv[hits[0] & ~both, 1]
            remap[new_uv[hits[1] & ~both, 1]] = new_uv[hits[1] & ~both, 0]
            lo = np.minimum(new_uv[both, 0], new_uv[both, 1])
            remap[new_uv[both, 0]] = lo
            remap[new_uv[both, 1]] = lo
            assignments = remap[assignments]
        if cfg["relabel"]:
            assignments = _relabel_consecutive(assignments)
        with file_reader(cfg["output_path"]) as f:
            f.require_dataset(cfg["output_key"], data=assignments,
                              chunks=(min(int(1e5), len(assignments)),))


class GraphConnectedComponents(BlockTask):
    """Split spatially disconnected segments: connected components of the
    node graph restricted to same-assignment edges (reference:
    graph_connected_components.py via ndist.connectedComponentsFromNodes)."""

    task_name = "graph_connected_components"
    global_task = True
    allow_retry = False

    def __init__(self, problem_path: str, graph_key: str,
                 assignment_path: str, assignment_key: str, output_path: str,
                 output_key: str, **kw):
        self.problem_path = problem_path
        self.graph_key = graph_key
        self.assignment_path = assignment_path
        self.assignment_key = assignment_key
        self.output_path = output_path
        self.output_key = output_key
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "problem_path": self.problem_path, "graph_key": self.graph_key,
            "assignment_path": self.assignment_path,
            "assignment_key": self.assignment_key,
            "output_path": self.output_path, "output_key": self.output_key,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from .. import native
        from ..core.graph import load_graph

        cfg = job_config["config"]
        nodes, uv_ids, _ = load_graph(cfg["problem_path"], cfg["graph_key"])
        with file_reader(cfg["assignment_path"], "r") as f:
            assignments = f[cfg["assignment_key"]][:].astype("uint64")
        n_nodes = len(assignments)
        same = (assignments[uv_ids[:, 0]] == assignments[uv_ids[:, 1]]) \
            & (assignments[uv_ids[:, 0]] != 0)
        roots = native.ufd_merge_pairs(n_nodes, uv_ids[same])
        # nodes sharing a root are one (connected) segment; nodes of the
        # same old assignment in different components get split.  +1 keeps a
        # component rooted at node 0 from being erased as background by the
        # relabel below.
        out = np.zeros(n_nodes, "uint64")
        nz = assignments != 0
        out[nz] = roots[nz] + np.uint64(1)
        out = _relabel_consecutive(out)
        n_old = len(np.unique(assignments))
        log_fn(f"split {n_old} segments into {len(np.unique(out))} "
               "connected components")
        with file_reader(cfg["output_path"]) as f:
            f.require_dataset(cfg["output_key"], data=out,
                              chunks=(min(int(1e5), len(out)),))


# ---------------------------------------------------------------------------
# workflows
# ---------------------------------------------------------------------------

class SizeFilterWorkflow(Task):
    """Count sizes -> discard small ids -> background or watershed-fill
    filter -> optional relabel (reference: postprocess_workflow.py:24-120)."""

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, size_threshold: int, tmp_folder: str,
                 config_dir: str, max_jobs: int = 1, target: str = "local",
                 hmap_path: str = "", hmap_key: str = "",
                 relabel: bool = True, dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.size_threshold = size_threshold
        self.hmap_path = hmap_path
        self.hmap_key = hmap_key
        self.relabel = relabel
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        counts = BlockCounts(input_path=self.input_path,
                             input_key=self.input_key,
                             identifier="size_filter",
                             dependency=self.dependency, **common)
        discard_path = os.path.join(self.tmp_folder,
                                    "size_filter_discard.npy")
        discard = SizeFilterDiscardIds(
            counts_prefix=counts.name_with_id, output_path=discard_path,
            size_threshold=self.size_threshold, identifier="size_filter",
            dependency=counts, **common)
        filter_cls = FillingSizeFilter if self.hmap_path else \
            BackgroundSizeFilter
        dep: Task = filter_cls(
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            filter_path=discard_path, hmap_path=self.hmap_path,
            hmap_key=self.hmap_key, dependency=discard, **common)
        if self.relabel:
            dep = RelabelWorkflow(
                input_path=self.output_path, input_key=self.output_key,
                identifier="relabel_size_filter", dependency=dep, **common)
        return dep

    def output(self):
        if self.relabel:
            return FileTarget(os.path.join(self.tmp_folder,
                                           "write_relabel_size_filter.status"))
        name = ("filling_size_filter" if self.hmap_path
                else "background_size_filter")
        return FileTarget(os.path.join(self.tmp_folder, f"{name}.status"))


class FilterLabelsWorkflow(Task):
    """Remove fragments whose max-overlap label (vs a semantic map) is in
    ``filter_labels`` (reference: postprocess_workflow.py:115-162)."""

    def __init__(self, input_path: str, input_key: str, label_path: str,
                 label_key: str, node_label_path: str, node_label_key: str,
                 output_path: str, output_key: str,
                 filter_labels: Sequence[int], tmp_folder: str,
                 config_dir: str, max_jobs: int = 1, target: str = "local",
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.label_path = label_path
        self.label_key = label_key
        self.node_label_path = node_label_path
        self.node_label_key = node_label_key
        self.output_path = output_path
        self.output_key = output_key
        self.filter_labels = list(filter_labels)
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        from .node_labels import NodeLabelWorkflow

        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        labels = NodeLabelWorkflow(
            ws_path=self.input_path, ws_key=self.input_key,
            input_path=self.label_path, input_key=self.label_key,
            output_path=self.node_label_path,
            output_key=self.node_label_key, prefix="filter_labels",
            max_overlap=True, dependency=self.dependency, **common)
        id_filter_path = os.path.join(self.tmp_folder, "filtered_ids.json")
        id_filter = IdFilter(
            node_label_path=self.node_label_path,
            node_label_key=self.node_label_key, output_path=id_filter_path,
            filter_labels=self.filter_labels, dependency=labels, **common)
        return FilterBlocks(
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            filter_path=id_filter_path, dependency=id_filter, **common)

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "filter_blocks.status"))


class ApplyThreshold(BlockTask):
    """Threshold a per-node feature vector -> filtered-id json (reference:
    postprocess_workflow.py:164-196 ApplyThreshold)."""

    task_name = "apply_threshold"
    global_task = True
    allow_retry = False

    _MODES = ("less", "greater", "equal")

    def __init__(self, feature_path: str, feature_key: str, out_path: str,
                 threshold: float, threshold_mode: str = "less", **kw):
        if threshold_mode not in self._MODES:
            raise ValueError(f"threshold_mode must be one of {self._MODES}")
        self.feature_path = feature_path
        self.feature_key = feature_key
        self.out_path = out_path
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "feature_path": self.feature_path,
            "feature_key": self.feature_key, "out_path": self.out_path,
            "threshold": self.threshold,
            "threshold_mode": self.threshold_mode,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        with file_reader(cfg["feature_path"], "r") as f:
            feats = f[cfg["feature_key"]][:]
        mode = cfg["threshold_mode"]
        if mode == "less":
            mask = feats < cfg["threshold"]
        elif mode == "greater":
            mask = feats > cfg["threshold"]
        else:
            mask = feats == cfg["threshold"]
        filter_ids = np.flatnonzero(mask)
        write_config(cfg["out_path"], [int(i) for i in filter_ids])
        log_fn(f"filtering {len(filter_ids)} / {len(feats)} ids "
               f"({mode} {cfg['threshold']})")


class FilterByThresholdWorkflow(Task):
    """Region features -> threshold -> zero out filtered segments ->
    optional relabel (reference: postprocess_workflow.py:198-250)."""

    def __init__(self, input_path: str, input_key: str, seg_in_path: str,
                 seg_in_key: str, seg_out_path: str, seg_out_key: str,
                 threshold: float, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 relabel: bool = True, dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.seg_in_path = seg_in_path
        self.seg_in_key = seg_in_key
        self.seg_out_path = seg_out_path
        self.seg_out_key = seg_out_key
        self.threshold = threshold
        self.relabel = relabel
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        from .region_features import RegionFeaturesWorkflow

        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        feat_path = os.path.join(self.tmp_folder, "reg_feats.n5")
        feats = RegionFeaturesWorkflow(
            input_path=self.input_path, input_key=self.input_key,
            labels_path=self.seg_in_path, labels_key=self.seg_in_key,
            output_path=feat_path, output_key="feats",
            dependency=self.dependency, **common)
        id_filter_path = os.path.join(self.tmp_folder, "filtered_ids.json")
        thresh = ApplyThreshold(
            feature_path=feat_path, feature_key="feats",
            out_path=id_filter_path, threshold=self.threshold,
            dependency=feats, **common)
        dep: Task = FilterBlocks(
            input_path=self.seg_in_path, input_key=self.seg_in_key,
            output_path=self.seg_out_path, output_key=self.seg_out_key,
            filter_path=id_filter_path, dependency=thresh, **common)
        if self.relabel:
            dep = RelabelWorkflow(
                input_path=self.seg_out_path, input_key=self.seg_out_key,
                identifier="relabel_filter", dependency=dep, **common)
        return dep

    def output(self):
        if self.relabel:
            return FileTarget(os.path.join(self.tmp_folder,
                                           "write_relabel_filter.status"))
        return FileTarget(os.path.join(self.tmp_folder,
                                       "filter_blocks.status"))


class ConnectedComponentsWorkflow(Task):
    """GraphConnectedComponents -> optional Write (reference:
    postprocess_workflow.py:296-340)."""

    def __init__(self, problem_path: str, graph_key: str,
                 assignment_path: str, assignment_key: str, output_path: str,
                 assignment_out_key: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local", path: str = "",
                 fragments_key: str = "", output_key: str = "",
                 dependency: Optional[Task] = None):
        self.problem_path = problem_path
        self.graph_key = graph_key
        self.assignment_path = assignment_path
        self.assignment_key = assignment_key
        self.output_path = output_path
        self.assignment_out_key = assignment_out_key
        self.path = path
        self.fragments_key = fragments_key
        self.output_key = output_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        dep: Task = GraphConnectedComponents(
            problem_path=self.problem_path, graph_key=self.graph_key,
            assignment_path=self.assignment_path,
            assignment_key=self.assignment_key,
            output_path=self.output_path,
            output_key=self.assignment_out_key,
            dependency=self.dependency, **common)
        if self.output_key:
            dep = WriteAssignments(
                input_path=self.path, input_key=self.fragments_key,
                output_path=self.output_path, output_key=self.output_key,
                assignment_path=self.output_path,
                assignment_key=self.assignment_out_key,
                identifier="graph_cc", dependency=dep, **common)
        return dep

    def output(self):
        if self.output_key:
            return FileTarget(os.path.join(self.tmp_folder,
                                           "write_graph_cc.status"))
        return FileTarget(os.path.join(self.tmp_folder,
                                       "graph_connected_components.status"))


class FilterOrphansWorkflow(Task):
    """OrphanAssignments -> optional Write (reference:
    postprocess_workflow.py:252-295; upstream marked 'FIXME not debugged',
    this implementation is tested)."""

    def __init__(self, graph_path: str, graph_key: str, path: str,
                 segmentation_key: str, assignment_key: str,
                 output_path: str, assignment_out_key: str, tmp_folder: str,
                 config_dir: str, max_jobs: int = 1, target: str = "local",
                 output_key: str = "", relabel: bool = False,
                 dependency: Optional[Task] = None):
        self.graph_path = graph_path
        self.graph_key = graph_key
        self.path = path
        self.segmentation_key = segmentation_key
        self.assignment_key = assignment_key
        self.output_path = output_path
        self.assignment_out_key = assignment_out_key
        self.output_key = output_key
        self.relabel = relabel
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        dep: Task = OrphanAssignments(
            graph_path=self.graph_path, graph_key=self.graph_key,
            assignment_path=self.path, assignment_key=self.assignment_key,
            output_path=self.output_path,
            output_key=self.assignment_out_key, relabel=self.relabel,
            dependency=self.dependency, **common)
        if self.output_key:
            dep = WriteAssignments(
                input_path=self.path, input_key=self.segmentation_key,
                output_path=self.output_path, output_key=self.output_key,
                assignment_path=self.output_path,
                assignment_key=self.assignment_out_key,
                identifier="filter_orphans", dependency=dep, **common)
        return dep

    def output(self):
        if self.output_key:
            return FileTarget(os.path.join(self.tmp_folder,
                                           "write_filter_orphans.status"))
        return FileTarget(os.path.join(self.tmp_folder,
                                       "orphan_assignments.status"))


class SizeFilterAndGraphWatershedWorkflow(Task):
    """Find small segments, then re-assign their fragments by graph
    watershed instead of deleting them (reference:
    postprocess_workflow.py:342-420)."""

    def __init__(self, problem_path: str, graph_key: str, features_key: str,
                 path: str, segmentation_key: str, assignment_key: str,
                 size_threshold: int, output_path: str,
                 assignment_out_key: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 fragments_key: str = "", output_key: str = "",
                 relabel: bool = False, from_costs: bool = False,
                 dependency: Optional[Task] = None):
        self.problem_path = problem_path
        self.graph_key = graph_key
        self.features_key = features_key
        self.path = path
        self.segmentation_key = segmentation_key
        self.assignment_key = assignment_key
        self.size_threshold = size_threshold
        self.output_path = output_path
        self.assignment_out_key = assignment_out_key
        self.fragments_key = fragments_key
        self.output_key = output_key
        self.relabel = relabel
        self.from_costs = from_costs
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        counts = BlockCounts(
            input_path=self.path, input_key=self.segmentation_key,
            identifier="gws", dependency=self.dependency, **common)
        discard_path = os.path.join(self.tmp_folder, "discard_ids.npy")
        discard = SizeFilterDiscardIds(
            counts_prefix=counts.name_with_id, output_path=discard_path,
            size_threshold=self.size_threshold, identifier="gws",
            dependency=counts, **common)
        dep: Task = GraphWatershedAssignments(
            problem_path=self.problem_path, graph_key=self.graph_key,
            features_key=self.features_key, assignment_path=self.path,
            assignment_key=self.assignment_key,
            output_path=self.output_path,
            output_key=self.assignment_out_key,
            filter_nodes_path=discard_path, relabel=self.relabel,
            from_costs=self.from_costs, dependency=discard, **common)
        if self.output_key:
            dep = WriteAssignments(
                input_path=self.path, input_key=self.fragments_key,
                output_path=self.output_path, output_key=self.output_key,
                assignment_path=self.output_path,
                assignment_key=self.assignment_out_key,
                identifier="size_filter_gws", dependency=dep, **common)
        return dep

    def output(self):
        if self.output_key:
            return FileTarget(os.path.join(
                self.tmp_folder, "write_size_filter_gws.status"))
        return FileTarget(os.path.join(
            self.tmp_folder, "graph_watershed_assignments.status"))
