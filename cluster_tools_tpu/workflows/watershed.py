"""Blockwise distance-transform watershed.

Re-specification of the reference's ``watershed/`` package
(watershed/watershed.py): per block (with halo) — read boundary/affinity map,
threshold + Euclidean distance transform, seeds from smoothed-DT maxima,
seeded watershed on a height map mixing boundary evidence and inverted DT,
size filter, per-block label offset, write inner block.  All pixel compute
runs on device (ops/edt.py, ops/filters.py, ops/watershed.py); under
``target='tpu'`` the whole per-block pipeline is one jitted program.

2d variants (``apply_dt_2d`` / ``apply_ws_2d``, for anisotropic EM stacks)
process z-slices via vmap over the z axis — the reference loops slices in
Python (watershed.py:211-230); here it is one batched device call.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..core.blocking import Blocking
from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import Task
from .relabel import RelabelWorkflow


def _read_input(ds, bb, cfg) -> np.ndarray:
    """Read + normalize boundary evidence; agglomerate affinity channels by
    mean/max over the configured channel range (reference:
    watershed.py:267-283 _read_data)."""
    if ds.ndim == len(bb) + 1:
        chan = cfg.get("channel_begin", 0), cfg.get("channel_end", None)
        cb = chan[0]
        ce = ds.shape[0] if chan[1] is None else chan[1]
        data = ds[(slice(cb, ce),) + bb].astype("float32")
        agglo = cfg.get("agglomerate_channels", "mean")
        data = data.max(axis=0) if agglo == "max" else data.mean(axis=0)
    else:
        data = ds[bb].astype("float32")
    mx = data.max()
    if mx > 1.0:
        data = data / 255.0 if mx <= 255 else data / mx
    if cfg.get("invert_inputs", False):
        data = 1.0 - data
    return data


def run_ws_block(data: np.ndarray, cfg: Dict[str, Any],
                 mask: Optional[np.ndarray] = None) -> np.ndarray:
    """The per-block watershed pipeline (reference: _ws_block
    watershed.py:285-341), device compute with host glue."""
    import jax.numpy as jnp

    from ..ops.components import connected_components
    from ..ops.edt import distance_transform_edt
    from ..ops.filters import gaussian, local_maxima
    from ..ops.watershed import (seeded_watershed, seeded_watershed_batched,
                                 size_filter)

    import jax

    threshold = cfg.get("threshold", 0.25)
    sigma_seeds = cfg.get("sigma_seeds", 2.0)
    sigma_weights = cfg.get("sigma_weights", 2.0)
    min_size = cfg.get("size_filter", 25)
    alpha = cfg.get("alpha", 0.8)
    pixel_pitch = cfg.get("pixel_pitch")
    dt_2d = cfg.get("apply_dt_2d", False)
    ws_2d = cfg.get("apply_ws_2d", False)

    x = jnp.asarray(data)
    jmask = None if mask is None else jnp.asarray(mask.astype(bool))

    # distance to boundaries (vigra distanceTransform equivalent)
    fg = x < threshold
    if jmask is not None:
        fg = fg & jmask
    if dt_2d or ws_2d:
        dt = jax.vmap(lambda m: distance_transform_edt(m))(fg)
    else:
        sampling = tuple(pixel_pitch) if pixel_pitch else None
        dt = distance_transform_edt(fg, sampling=sampling)

    # height map: boundary evidence blended with inverted DT
    # (reference fit_to_hmap/_make_hmap, utils/volume_utils.py:294-391)
    hmap = gaussian(x, sigma_weights) if sigma_weights else x
    dmax = jnp.maximum(dt.max(), 1e-6)
    height = alpha * hmap + (1.0 - alpha) * (1.0 - dt / dmax)

    if ws_2d:
        # independent watershed per z-slice (reference: watershed.py:211-230
        # loops slices; here one vmapped device program).  Per-slice labels
        # are made unique across slices by a per-slice offset.
        dt_smooth = (jax.vmap(lambda d: gaussian(d, sigma_seeds))(dt)
                     if sigma_seeds else dt)
        maxima = jax.vmap(lambda d, f: local_maxima(d, 2) & f)(dt_smooth, fg)
        seeds = jax.vmap(lambda m: connected_components(m, connectivity=2))(maxima)
        ws = seeded_watershed_batched(height, seeds, jmask, connectivity=1)
        # per-slice offsets in host uint64: device int32 would overflow for
        # n_slices * slice_size >= 2**31 (large in-plane blocks)
        ws = np.array(ws).astype(np.uint64)
        slice_size = np.uint64(np.prod(data.shape[1:]))
        offsets = (np.arange(data.shape[0], dtype=np.uint64)
                   * slice_size)[:, None, None]
        ws = np.where(ws > 0, ws + offsets, 0)
    else:
        # seeds: connected maxima clusters of the smoothed DT
        dt_smooth = gaussian(dt, sigma_seeds) if sigma_seeds else dt
        maxima = local_maxima(dt_smooth, radius=2) & fg
        seeds = connected_components(maxima, connectivity=len(data.shape))
        ws = np.array(seeded_watershed(height, seeds, jmask, connectivity=1))
    if min_size:
        ws = size_filter(ws, np.asarray(height), min_size,
                         mask=None if mask is None else mask.astype(bool),
                         per_slice=ws_2d)
    return ws.astype("uint64")


class WatershedTask(BlockTask):
    """Blockwise DT watershed (reference: WatershedBase, watershed.py:34-110).

    Labels are made globally unique by offsetting with
    ``block_id * prod(block_shape)`` (reference: watershed.py:307); chain
    RelabelWorkflow (or use WatershedWorkflow) to compact them.
    """

    task_name = "watershed"

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, mask_path: str = "", mask_key: str = "", **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({
            "threshold": 0.25, "apply_dt_2d": False, "apply_ws_2d": False,
            "sigma_seeds": 2.0, "sigma_weights": 2.0, "size_filter": 25,
            "alpha": 0.8, "halo": [4, 32, 32], "pixel_pitch": None,
            "invert_inputs": False, "agglomerate_channels": "mean",
            "channel_begin": 0, "channel_end": None,
        })
        return conf

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            in_shape = f[self.input_key].shape
        shape = list(in_shape[1:] if len(in_shape) == 4 else in_shape)
        block_shape = self.global_block_shape()[-len(shape):]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=shape, chunks=block_shape,
                              dtype="uint64")
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "mask_path": self.mask_path, "mask_key": self.mask_key,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        halo = cfg.get("halo") or [0] * blocking.ndim
        halo = halo[-blocking.ndim:]
        f_in = file_reader(cfg["input_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in, ds_out = f_in[cfg["input_key"]], f_out[cfg["output_key"]]
        mask = None
        if cfg.get("mask_path"):
            from ..core.volume_views import load_mask

            mask = load_mask(cfg["mask_path"], cfg["mask_key"], cfg["shape"])

        label_offset_unit = np.uint64(np.prod(cfg["block_shape"]))
        for block_id in job_config["block_list"]:
            bh = blocking.get_block_with_halo(block_id, halo)
            data = _read_input(ds_in, bh.outer.bb, cfg)
            bmask = None
            if mask is not None:
                bmask = np.asarray(mask[bh.outer.bb]) > 0
                if not bmask.any():
                    log_fn(f"processed block {block_id}")
                    continue
            ws = run_ws_block(data, cfg, bmask)
            inner = ws[bh.inner_local.bb]
            # compact to 1..k (k <= inner voxel count < offset unit), THEN
            # offset for global uniqueness (reference: watershed.py:307) —
            # uncompacted CC root indices range over the larger outer block
            # and would collide across blocks
            nonzero = np.unique(inner[inner > 0])
            compact = np.searchsorted(nonzero, inner).astype("uint64") + 1
            compact[inner == 0] = 0
            compact = np.where(
                compact > 0, compact + np.uint64(block_id) * label_offset_unit, 0)
            ds_out[bh.inner.bb] = compact
            log_fn(f"processed block {block_id}")


class WatershedWorkflow(Task):
    """Watershed -> RelabelWorkflow (reference:
    watershed/watershed_workflow.py:20-60; agglomeration step arrives with the
    graph stack)."""

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 mask_path: str = "", mask_key: str = "",
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.mask_path = mask_path
        self.mask_key = mask_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        ws = WatershedTask(
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path, output_key=self.output_key,
            mask_path=self.mask_path, mask_key=self.mask_key,
            dependency=self.dependency, **common)
        return RelabelWorkflow(
            input_path=self.output_path, input_key=self.output_key,
            identifier="relabel_ws", dependency=ws, **common)

    def output(self):
        from ..core.workflow import FileTarget

        return FileTarget(os.path.join(self.tmp_folder,
                                       "write_relabel_ws.status"))
