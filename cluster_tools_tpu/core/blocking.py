"""Domain decomposition: blocking geometry over N-d volumes.

TPU-native re-specification of the reference's L1 layer (nifty.tools.blocking +
cluster_tools/utils/volume_utils.py:52-276 in the reference repo): block grids,
halos, ROI restriction, inter-block faces and checkerboard 2-colorings — as pure
Python/numpy geometry with no native dependency.  The same geometry doubles as
the sharding layout for device meshes (see parallel/stencil.py): a "block" is
either a unit of host work or a per-device shard.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from itertools import product
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

Coord = Tuple[int, ...]
BB = Tuple[slice, ...]


@dataclass(frozen=True)
class Block:
    """A single block of a :class:`Blocking` grid (reference:
    nifty.tools.blocking block objects, used e.g. watershed/watershed.py:252-264).
    """

    begin: Coord
    end: Coord

    @property
    def shape(self) -> Coord:
        return tuple(e - b for b, e in zip(self.begin, self.end))

    @property
    def bb(self) -> BB:
        return tuple(slice(b, e) for b, e in zip(self.begin, self.end))


@dataclass(frozen=True)
class BlockWithHalo:
    """Outer (halo-expanded, clipped) block, inner block, and the inner block in
    outer-local coordinates (reference: blocking.getBlockWithHalo(...).outerBlock
    / innerBlock / innerBlockLocal)."""

    outer: Block
    inner: Block
    inner_local: Block


class Blocking:
    """Regular grid of blocks covering ``shape``.

    Block ids enumerate the grid in C (row-major) order.  Semantics match the
    reference's nifty.tools.blocking (58 call sites, SURVEY.md L1): the last
    block along an axis is clipped to the volume boundary.
    """

    def __init__(self, shape: Sequence[int], block_shape: Sequence[int]):
        if len(shape) != len(block_shape):
            raise ValueError(f"dim mismatch: {shape} vs {block_shape}")
        if any(s <= 0 for s in shape) or any(b <= 0 for b in block_shape):
            raise ValueError(f"non-positive extent: {shape}, {block_shape}")
        self.shape = tuple(int(s) for s in shape)
        self.block_shape = tuple(int(b) for b in block_shape)
        self.grid_shape = tuple(
            (s + b - 1) // b for s, b in zip(self.shape, self.block_shape)
        )
        self._strides = np.array(
            [int(np.prod(self.grid_shape[i + 1:])) for i in range(self.ndim)],
            dtype=np.int64,
        )

    @property
    def ndim(self) -> int:
        return len(self.shape)

    @property
    def n_blocks(self) -> int:
        return int(np.prod(self.grid_shape))

    def block_grid_position(self, block_id: int) -> Coord:
        if not 0 <= block_id < self.n_blocks:
            raise IndexError(f"block {block_id} out of range [0, {self.n_blocks})")
        pos = []
        rem = block_id
        for st in self._strides:
            pos.append(int(rem // st))
            rem = rem % st
        return tuple(pos)

    def grid_position_to_id(self, pos: Sequence[int]) -> int:
        return int(np.dot(np.asarray(pos, dtype=np.int64), self._strides))

    def get_block(self, block_id: int) -> Block:
        pos = self.block_grid_position(block_id)
        begin = tuple(p * b for p, b in zip(pos, self.block_shape))
        end = tuple(
            min(beg + b, s)
            for beg, b, s in zip(begin, self.block_shape, self.shape)
        )
        return Block(begin, end)

    def get_block_with_halo(self, block_id: int, halo: Sequence[int]) -> BlockWithHalo:
        inner = self.get_block(block_id)
        outer_begin = tuple(max(b - h, 0) for b, h in zip(inner.begin, halo))
        outer_end = tuple(min(e + h, s) for e, h, s in zip(inner.end, halo, self.shape))
        outer = Block(outer_begin, outer_end)
        local = Block(
            tuple(ib - ob for ib, ob in zip(inner.begin, outer_begin)),
            tuple(ie - ob for ie, ob in zip(inner.end, outer_begin)),
        )
        return BlockWithHalo(outer=outer, inner=inner, inner_local=local)

    def neighbor_id(self, block_id: int, axis: int, direction: int) -> Optional[int]:
        """Id of the face-neighbor along ``axis`` (+1 / -1), or None at the border."""
        pos = list(self.block_grid_position(block_id))
        pos[axis] += direction
        if not 0 <= pos[axis] < self.grid_shape[axis]:
            return None
        return self.grid_position_to_id(pos)

    # -- block lists ------------------------------------------------------

    def blocks_in_roi(self, roi_begin: Sequence[int], roi_end: Sequence[int]) -> List[int]:
        """All block ids whose block intersects [roi_begin, roi_end) (reference:
        utils/volume_utils.py:52-88 blocks_in_volume with roi restriction)."""
        lo = [max(rb, 0) // b for rb, b in zip(roi_begin, self.block_shape)]
        hi = [
            min((re + b - 1) // b, g)
            for re, b, g in zip(roi_end, self.block_shape, self.grid_shape)
        ]
        ids = []
        for pos in product(*[range(l, h) for l, h in zip(lo, hi)]):
            ids.append(self.grid_position_to_id(pos))
        return ids

    def checkerboard(self) -> Tuple[List[int], List[int]]:
        """2-color the block grid for conflict-free two-pass updates (reference:
        utils/volume_utils.py:142-205 make_checkerboard_block_lists)."""
        colors: Tuple[List[int], List[int]] = ([], [])
        for bid in range(self.n_blocks):
            parity = sum(self.block_grid_position(bid)) % 2
            colors[parity].append(bid)
        return colors


def blocks_in_volume(
    shape: Sequence[int],
    block_shape: Sequence[int],
    roi_begin: Optional[Sequence[int]] = None,
    roi_end: Optional[Sequence[int]] = None,
    block_list_path: Optional[str] = None,
) -> List[int]:
    """List of block ids to process; semantics of the reference's
    blocks_in_volume (utils/volume_utils.py:52-88): full grid, optionally
    restricted to an ROI, optionally intersected with an explicit block-list
    file (as written by the masking component)."""
    blocking = Blocking(shape, block_shape)
    if (roi_begin is None) != (roi_end is None):
        raise ValueError("roi_begin and roi_end must be given together")
    if roi_begin is not None:
        roi_begin = [0 if rb is None else int(rb) for rb in roi_begin]
        roi_end = [
            s if re is None else min(int(re), s)
            for re, s in zip(roi_end, shape)
        ]
        block_ids = blocking.blocks_in_roi(roi_begin, roi_end)
    else:
        block_ids = list(range(blocking.n_blocks))

    if block_list_path is not None:
        if not os.path.exists(block_list_path):
            raise FileNotFoundError(
                f"block_list_path {block_list_path} is configured but does "
                "not exist — refusing to silently process all blocks")
        with open(block_list_path) as f:
            allowed = set(json.load(f))
        block_ids = [bid for bid in block_ids if bid in allowed]
    return block_ids


def block_to_bb(block: Block) -> BB:
    """Block -> numpy slice tuple (reference: utils/volume_utils.py:91)."""
    return block.bb


@dataclass(frozen=True)
class Face:
    """Overlap region between two axis-neighboring blocks (reference:
    utils/volume_utils.py:221-270 get_face / iterate_faces)."""

    block_a: int
    block_b: int
    axis: int
    #: bounding box of the face region, `2*halo` thick along `axis`
    outer_bb: BB
    #: the two halves of the face, in face-local coordinates
    face_a: BB
    face_b: BB


def iterate_faces(
    blocking: Blocking,
    block_id: int,
    halo: Sequence[int],
    return_only_lower: bool = True,
) -> Iterator[Face]:
    """Iterate the faces between ``block_id`` and its axis neighbors.

    For each axis where a neighbor exists, yields the bounding box that spans
    ``halo[axis]`` voxels into each of the two blocks, plus face-local slices
    selecting each half.  ``return_only_lower`` yields only faces to the
    lower-id (preceding) neighbor so each face is visited once globally —
    matching the reference's iterate_faces contract.
    """
    block = blocking.get_block(block_id)
    ndim = blocking.ndim
    for axis in range(ndim):
        directions = [-1] if return_only_lower else [-1, 1]
        for direction in directions:
            nid = blocking.neighbor_id(block_id, axis, direction)
            if nid is None:
                continue
            h = int(halo[axis])
            boundary = block.begin[axis] if direction == -1 else block.end[axis]
            # clip to the volume so thin border blocks don't overflow
            lo_edge = max(boundary - h, 0)
            hi_edge = min(boundary + h, blocking.shape[axis])
            lo_extent = boundary - lo_edge
            outer_bb = []
            for d in range(ndim):
                if d == axis:
                    outer_bb.append(slice(lo_edge, hi_edge))
                else:
                    outer_bb.append(slice(block.begin[d], block.end[d]))
            face_lo = tuple(
                slice(0, lo_extent) if d == axis else slice(None)
                for d in range(ndim)
            )
            face_hi = tuple(
                slice(lo_extent, hi_edge - lo_edge) if d == axis else slice(None)
                for d in range(ndim)
            )
            if direction == -1:
                yield Face(
                    block_a=nid, block_b=block_id, axis=axis,
                    outer_bb=tuple(outer_bb), face_a=face_lo, face_b=face_hi,
                )
            else:
                yield Face(
                    block_a=block_id, block_b=nid, axis=axis,
                    outer_bb=tuple(outer_bb), face_a=face_lo, face_b=face_hi,
                )
