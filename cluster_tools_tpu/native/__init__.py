"""First-party native kernels (C++), loaded via ctypes.

Replaces the reference's external pybind11 wheels for combinatorial work
(nifty solvers/ufd, affogato MWS — SURVEY §2.3).  The shared library is
compiled on demand with g++ (no pybind11 in the image; the C API is flat
arrays).  Every entry point has a pure-numpy/scipy fallback so the framework
degrades gracefully where no compiler exists.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "src", "solvers.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False


def _lib_path() -> Optional[str]:
    """Content-addressed build artifact: the library name embeds the source
    hash, so a stale binary (e.g. from a previous checkout — git does not
    preserve mtimes) can never be loaded for edited sources."""
    try:
        with open(_SRC, "rb") as f:
            digest = hashlib.sha256(f.read()).hexdigest()[:16]
    except OSError:
        return None
    return os.path.join(_HERE, f"libctt_native-{digest}.so")


def _build(lib_path: str) -> bool:
    # per-process tmp name: concurrent workers may build simultaneously on
    # first use; each publishes a complete file via atomic rename
    tmp = f"{lib_path}.tmp.{os.getpid()}"
    cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp]
    try:
        res = subprocess.run(cmd, capture_output=True, timeout=300)
    except (OSError, subprocess.TimeoutExpired):
        return False
    if res.returncode != 0:
        return False
    os.replace(tmp, lib_path)
    for name in os.listdir(_HERE):  # drop superseded build artifacts
        if (name.startswith("libctt_native-") and name.endswith(".so")
                and os.path.join(_HERE, name) != lib_path):
            try:
                os.unlink(os.path.join(_HERE, name))
            except OSError:
                pass
    return True


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _build_failed
    with _lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        lib_path = _lib_path()
        if lib_path is None or (not os.path.exists(lib_path)
                                and not _build(lib_path)):
            _build_failed = True
            return None
        lib = ctypes.CDLL(lib_path)
        i64 = ctypes.c_int64
        p_i64 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
        p_f64 = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
        p_u64 = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
        lib.ufd_merge_pairs.argtypes = [i64, i64, p_i64, p_u64]
        lib.mc_gaec.argtypes = [i64, i64, p_i64, p_f64, p_u64]
        lib.mc_gaec.restype = i64
        lib.mc_kl_refine.argtypes = [i64, i64, p_i64, p_f64, p_u64, i64,
                                     ctypes.c_double]
        lib.mc_kl_refine.restype = i64
        lib.mc_objective.argtypes = [i64, i64, p_i64, p_f64, p_u64]
        lib.mc_objective.restype = ctypes.c_double
        lib.mws_clustering.argtypes = [i64, i64, p_i64, p_f64, i64, p_i64,
                                       p_f64, p_u64]
        lib.mws_clustering.restype = i64
        p_i32 = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
        p_u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.mws_clustering_sorted.argtypes = [i64, i64, p_i32, p_i32, p_u8,
                                              p_u64]
        lib.mws_clustering_sorted.restype = i64
        lib.graph_watershed.argtypes = [i64, i64, p_i64, p_f64, p_u64]
        lib.lmc_gaec.argtypes = [i64, i64, p_i64, p_f64, i64, p_i64, p_f64,
                                 p_u64]
        lib.lmc_gaec.restype = i64
        lib.lmc_kl_refine.argtypes = [i64, i64, p_i64, p_f64, i64, p_i64,
                                      p_f64, p_u64, i64, ctypes.c_double]
        lib.lmc_kl_refine.restype = i64
        lib.agglomerate_edge_weighted.argtypes = [
            i64, i64, p_i64, p_f64, p_f64, p_f64, ctypes.c_double,
            ctypes.c_double, p_u64]
        lib.agglomerate_edge_weighted.restype = i64
        p_u8 = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
        lib.skeletonize_3d.argtypes = [p_u8, i64, i64, i64]
        lib.seeded_watershed_u8.argtypes = [p_u8, i64, i64, i64, p_i64]
        lib.size_filter_u8.argtypes = [p_u8, i64, i64, i64, p_i64, i64]
        _lib = lib
        return _lib


def have_native() -> bool:
    return _load() is not None


def _as_uv(uv_ids: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(uv_ids, dtype=np.int64).reshape(-1, 2)


# ---------------------------------------------------------------------------
# union-find
# ---------------------------------------------------------------------------

def ufd_merge_pairs(n_nodes: int, pairs: np.ndarray) -> np.ndarray:
    """Root label per node after merging all pairs (boost_ufd equivalent)."""
    pairs = _as_uv(pairs)
    lib = _load()
    if lib is not None:
        out = np.empty(n_nodes, dtype=np.uint64)
        lib.ufd_merge_pairs(n_nodes, len(pairs), pairs, out)
        return out
    # fallback: sparse connected components
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components as sparse_cc

    graph = coo_matrix((np.ones(len(pairs), bool),
                        (pairs[:, 0], pairs[:, 1])),
                       shape=(n_nodes, n_nodes))
    _, roots = sparse_cc(graph, directed=False)
    # normalize roots to "smallest member id" semantics? not required by
    # callers; any component representative works
    return roots.astype(np.uint64)


# ---------------------------------------------------------------------------
# multicut
# ---------------------------------------------------------------------------

def multicut_gaec(n_nodes: int, uv_ids: np.ndarray,
                  costs: np.ndarray) -> np.ndarray:
    """Greedy additive edge contraction (nifty greedyAdditive equivalent)."""
    uv = _as_uv(uv_ids)
    costs = np.ascontiguousarray(costs, dtype=np.float64)
    lib = _load()
    if lib is not None:
        out = np.empty(n_nodes, dtype=np.uint64)
        lib.mc_gaec(n_nodes, len(uv), uv, costs, out)
        return out
    return _py_gaec(n_nodes, uv, costs)


def multicut_kernighan_lin(n_nodes: int, uv_ids: np.ndarray,
                           costs: np.ndarray, warmstart: bool = True,
                           max_passes: int = 50,
                           time_limit: float = 0.0) -> np.ndarray:
    """GAEC warmstart + Kernighan-Lin-style greedy node moves (the nifty
    multicutKernighanLin role: polish a partition with local search).
    ``time_limit`` (seconds, 0 = none) bounds the refinement passes — the
    reference's time-limited solver visitor (segmentation_utils.py:166-181);
    the warmstart always completes, so a valid partition is returned."""
    uv = _as_uv(uv_ids)
    costs = np.ascontiguousarray(costs, dtype=np.float64)
    labels = (multicut_gaec(n_nodes, uv, costs) if warmstart
              else np.zeros(n_nodes, dtype=np.uint64))
    lib = _load()
    if lib is not None:
        labels = np.ascontiguousarray(labels, dtype=np.uint64)
        lib.mc_kl_refine(n_nodes, len(uv), uv, costs, labels, max_passes,
                         float(time_limit or 0.0))
        return labels
    return _py_moves(n_nodes, uv, costs, labels, max_passes,
                     time_limit=time_limit)


def multicut_objective(uv_ids: np.ndarray, costs: np.ndarray,
                       labels: np.ndarray) -> float:
    """Sum of costs over cut edges (the minimized energy)."""
    uv = _as_uv(uv_ids)
    cut = labels[uv[:, 0]] != labels[uv[:, 1]]
    return float(np.asarray(costs)[cut].sum())


def _py_gaec(n_nodes: int, uv: np.ndarray, costs: np.ndarray) -> np.ndarray:
    """Heap-based python fallback (small problems only)."""
    import heapq

    adj = [dict() for _ in range(n_nodes)]
    for (u, v), c in zip(uv, costs):
        if u == v:
            continue
        adj[u][v] = adj[u].get(v, 0.0) + c
        adj[v][u] = adj[v].get(u, 0.0) + c
    parent = np.arange(n_nodes)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    heap = [(-w, u, v) for u in range(n_nodes)
            for v, w in adj[u].items() if v > u and w > 0]
    heapq.heapify(heap)
    while heap:
        nw, u, v = heapq.heappop(heap)
        w = -nw
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        cur = adj[ru].get(rv)
        if cur is None or cur != w or {u, v} != {ru, rv}:
            if cur is not None and cur > 0:
                heapq.heappush(heap, (-cur, min(ru, rv), max(ru, rv)))
            continue
        if len(adj[ru]) < len(adj[rv]):
            ru, rv = rv, ru
        parent[rv] = ru
        adj[ru].pop(rv, None)
        adj[rv].pop(ru, None)
        for n, nw2 in adj[rv].items():
            adj[n].pop(rv, None)
            acc = adj[ru].get(n, 0.0) + nw2
            adj[ru][n] = acc
            adj[n][ru] = acc
            if acc > 0:
                heapq.heappush(heap, (-acc, min(ru, n), max(ru, n)))
        adj[rv].clear()
    roots = np.array([find(i) for i in range(n_nodes)])
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.uint64)


def _py_moves(n_nodes: int, uv: np.ndarray, costs: np.ndarray,
              labels: np.ndarray, max_passes: int,
              time_limit: float = 0.0) -> np.ndarray:
    import time as _time

    deadline = _time.monotonic() + time_limit if time_limit else None
    labels = labels.astype(np.uint64).copy()
    nbrs = [dict() for _ in range(n_nodes)]
    for (u, v), c in zip(uv, costs):
        nbrs[u][v] = nbrs[u].get(v, 0.0) + c
        nbrs[v][u] = nbrs[v].get(u, 0.0) + c
    next_label = int(labels.max()) + 1 if n_nodes else 0
    for _ in range(max_passes):
        if deadline is not None and _time.monotonic() > deadline:
            break
        improved = False
        for x in range(n_nodes):
            if not nbrs[x]:
                continue
            comp_w = {}
            for n, w in nbrs[x].items():
                comp_w[labels[n]] = comp_w.get(labels[n], 0.0) + w
            own = labels[x]
            w_own = comp_w.get(own, 0.0)
            best_gain, best_label = -w_own, next_label
            for lbl, w in comp_w.items():
                if lbl != own and w - w_own > best_gain + 1e-12:
                    best_gain, best_label = w - w_own, lbl
            if best_gain > 1e-12:
                labels[x] = best_label
                if best_label == next_label:
                    next_label += 1
                improved = True
        if not improved:
            break
    return labels


# ---------------------------------------------------------------------------
# lifted multicut
# ---------------------------------------------------------------------------

def lifted_multicut_gaec(n_nodes: int, uv_ids: np.ndarray, costs: np.ndarray,
                         lifted_uv_ids: np.ndarray,
                         lifted_costs: np.ndarray) -> np.ndarray:
    """Greedy additive contraction for the lifted multicut objective
    (nifty liftedMulticutGreedyAdditive equivalent): only local edges are
    contracted; priorities include the lifted cost between components."""
    uv = _as_uv(uv_ids)
    luv = _as_uv(lifted_uv_ids)
    c = np.ascontiguousarray(costs, dtype=np.float64)
    lc = np.ascontiguousarray(lifted_costs, dtype=np.float64)
    lib = _load()
    if lib is not None:
        out = np.empty(n_nodes, dtype=np.uint64)
        lib.lmc_gaec(n_nodes, len(uv), uv, c, len(luv), luv, lc, out)
        return out
    return _py_lmc_gaec(n_nodes, uv, c, luv, lc)


def lifted_multicut_kernighan_lin(n_nodes: int, uv_ids: np.ndarray,
                                  costs: np.ndarray,
                                  lifted_uv_ids: np.ndarray,
                                  lifted_costs: np.ndarray,
                                  warmstart: bool = True,
                                  max_passes: int = 50,
                                  time_limit: float = 0.0) -> np.ndarray:
    """Lifted GAEC warmstart + KL-style node moves over the lifted objective
    (nifty liftedMulticutKernighanLin equivalent)."""
    uv = _as_uv(uv_ids)
    luv = _as_uv(lifted_uv_ids)
    c = np.ascontiguousarray(costs, dtype=np.float64)
    lc = np.ascontiguousarray(lifted_costs, dtype=np.float64)
    labels = (lifted_multicut_gaec(n_nodes, uv, c, luv, lc) if warmstart
              else np.zeros(n_nodes, dtype=np.uint64))
    lib = _load()
    if lib is not None:
        labels = np.ascontiguousarray(labels, dtype=np.uint64)
        lib.lmc_kl_refine(n_nodes, len(uv), uv, c, len(luv), luv, lc,
                          labels, max_passes, float(time_limit or 0.0))
        return labels
    return _py_lmc_moves(n_nodes, uv, c, luv, lc, labels, max_passes,
                         time_limit=time_limit)


def lifted_objective(uv_ids: np.ndarray, costs: np.ndarray,
                     lifted_uv_ids: np.ndarray, lifted_costs: np.ndarray,
                     labels: np.ndarray) -> float:
    uv = _as_uv(uv_ids)
    luv = _as_uv(lifted_uv_ids)
    e = float(np.asarray(costs)[labels[uv[:, 0]] != labels[uv[:, 1]]].sum())
    if len(luv):
        e += float(np.asarray(lifted_costs)[
            labels[luv[:, 0]] != labels[luv[:, 1]]].sum())
    return e


def _py_lmc_gaec(n_nodes, uv, c, luv, lc):
    import heapq

    adj = [dict() for _ in range(n_nodes)]
    lift = [dict() for _ in range(n_nodes)]
    for (u, v), w in zip(uv, c):
        if u != v:
            adj[u][v] = adj[u].get(v, 0.0) + w
            adj[v][u] = adj[u][v]
    for (u, v), w in zip(luv, lc):
        if u != v:
            lift[u][v] = lift[u].get(v, 0.0) + w
            lift[v][u] = lift[u][v]
    parent = np.arange(n_nodes)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def pair_w(a, b):
        return adj[a].get(b, 0.0) + lift[a].get(b, 0.0)

    heap = [(-pair_w(u, v), u, v) for u in range(n_nodes)
            for v in adj[u] if v > u and pair_w(u, v) > 0]
    heapq.heapify(heap)
    while heap:
        nw, u, v = heapq.heappop(heap)
        w = -nw
        ru, rv = find(u), find(v)
        if ru == rv or rv not in adj[ru]:
            continue
        live = pair_w(ru, rv)
        if live != w or u != min(ru, rv) or v != max(ru, rv):
            if live > 0:
                heapq.heappush(heap, (-live, min(ru, rv), max(ru, rv)))
            continue
        parent[rv] = ru
        adj[ru].pop(rv, None)
        adj[rv].pop(ru, None)
        lift[ru].pop(rv, None)
        lift[rv].pop(ru, None)
        for store in (adj, lift):
            for n, w2 in store[rv].items():
                store[n].pop(rv, None)
                store[ru][n] = store[ru].get(n, 0.0) + w2
                store[n][ru] = store[ru][n]
            store[rv].clear()
        for n in adj[ru]:
            pw = pair_w(ru, n)
            if pw > 0:
                heapq.heappush(heap, (-pw, min(ru, n), max(ru, n)))
    roots = np.array([find(i) for i in range(n_nodes)])
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.uint64)


def _py_lmc_moves(n_nodes, uv, c, luv, lc, labels, max_passes,
                  time_limit: float = 0.0):
    import time as _time

    deadline = _time.monotonic() + time_limit if time_limit else None
    labels = labels.astype(np.uint64).copy()
    local = [dict() for _ in range(n_nodes)]
    lifted = [dict() for _ in range(n_nodes)]
    for (u, v), w in zip(uv, c):
        local[u][v] = local[u].get(v, 0.0) + w
        local[v][u] = local[v].get(u, 0.0) + w
    for (u, v), w in zip(luv, lc):
        lifted[u][v] = lifted[u].get(v, 0.0) + w
        lifted[v][u] = lifted[v].get(u, 0.0) + w
    next_label = int(labels.max()) + 1 if n_nodes else 0
    for _ in range(max_passes):
        if deadline is not None and _time.monotonic() > deadline:
            break
        improved = False
        for x in range(n_nodes):
            if not local[x]:
                continue
            comp_w = {}
            cands = set()
            for n, w in local[x].items():
                comp_w[labels[n]] = comp_w.get(labels[n], 0.0) + w
                cands.add(labels[n])
            for n, w in lifted[x].items():
                comp_w[labels[n]] = comp_w.get(labels[n], 0.0) + w
            own = labels[x]
            w_own = comp_w.get(own, 0.0)
            best_gain, best_label = -w_own, next_label
            for lbl in cands:
                if lbl != own and comp_w[lbl] - w_own > best_gain + 1e-12:
                    best_gain, best_label = comp_w[lbl] - w_own, lbl
            if best_gain > 1e-12:
                labels[x] = best_label
                if best_label == next_label:
                    next_label += 1
                improved = True
        if not improved:
            break
    return labels


# ---------------------------------------------------------------------------
# mutex watershed
# ---------------------------------------------------------------------------

def mutex_clustering(n_nodes: int, uv_attractive: np.ndarray,
                     w_attractive: np.ndarray, uv_mutex: np.ndarray,
                     w_mutex: np.ndarray) -> np.ndarray:
    """Kruskal-style mutex watershed over explicit edge lists
    (affogato compute_mws_clustering equivalent)."""
    uva = _as_uv(uv_attractive)
    uvm = _as_uv(uv_mutex)
    wa = np.ascontiguousarray(w_attractive, dtype=np.float64)
    wm = np.ascontiguousarray(w_mutex, dtype=np.float64)
    lib = _load()
    if lib is not None:
        out = np.empty(n_nodes, dtype=np.uint64)
        lib.mws_clustering(n_nodes, len(uva), uva, wa, len(uvm), uvm, wm, out)
        return out
    return _py_mws(n_nodes, uva, wa, uvm, wm)


def mutex_clustering_sorted(n_nodes: int, u: np.ndarray, v: np.ndarray,
                            mutex_flag: np.ndarray) -> np.ndarray:
    """Mutex-watershed union-find scan over a PRE-SORTED edge stream
    (descending priority; the device extracted and sorted the edges).
    ``u[i] < 0`` marks dropped edges; ``mutex_flag[i] != 0`` marks mutex
    edges.  Only the inherently sequential scan stays on the host —
    the std::stable_sort of tens of millions of 24-byte edge structs
    was the dominant cost of :func:`mutex_clustering`."""
    u = np.ascontiguousarray(u, dtype=np.int32)
    v = np.ascontiguousarray(v, dtype=np.int32)
    mutex_flag = np.ascontiguousarray(mutex_flag, dtype=np.uint8)
    lib = _load()
    out = np.empty(n_nodes, dtype=np.uint64)
    if lib is not None:
        lib.mws_clustering_sorted(n_nodes, len(u), u, v, mutex_flag, out)
        return out
    # pure-python fallback: rebuild (uv, w) lists in stream order with a
    # descending fake priority so _py_mws's sort is a stable no-op
    keep = u >= 0
    n = int(keep.sum())
    pri = np.arange(n, 0, -1, dtype="float64")
    am = mutex_flag[keep] != 0
    uv = np.stack([u[keep], v[keep]], axis=1).astype("int64")
    return _py_mws(n_nodes, uv[~am], pri[~am], uv[am], pri[am])


def _py_mws(n_nodes, uva, wa, uvm, wm):
    order_a = [(w, u, v, False) for (u, v), w in zip(uva, wa)]
    order_m = [(w, u, v, True) for (u, v), w in zip(uvm, wm)]
    edges = sorted(order_a + order_m, key=lambda e: -e[0])
    parent = np.arange(n_nodes)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    mutex = [set() for _ in range(n_nodes)]
    for w, u, v, is_mutex in edges:
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        if is_mutex:
            mutex[ru].add(rv)
            mutex[rv].add(ru)
        else:
            if rv in mutex[ru]:
                continue
            if len(mutex[ru]) < len(mutex[rv]):
                ru, rv = rv, ru
            parent[rv] = ru
            for c in mutex[rv]:
                mutex[c].discard(rv)
                if c != ru:
                    mutex[c].add(ru)
                    mutex[ru].add(c)
            mutex[rv].clear()
    roots = np.array([find(i) for i in range(n_nodes)])
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.uint64)


# ---------------------------------------------------------------------------
# agglomerative clustering
# ---------------------------------------------------------------------------

def agglomerative_clustering(n_nodes: int, uv_ids: np.ndarray,
                             edge_weights: np.ndarray,
                             edge_sizes: Optional[np.ndarray] = None,
                             node_sizes: Optional[np.ndarray] = None,
                             threshold: float = 0.5,
                             size_regularizer: float = 0.0) -> np.ndarray:
    """Edge-weighted agglomeration of a RAG: merge the lowest size-weighted
    mean boundary weight while it is below ``threshold``
    (nifty.graph.agglo edgeWeighted/mala cluster-policy equivalent,
    reference: utils/segmentation_utils.py:298-321).  Returns dense labels."""
    uv = _as_uv(uv_ids)
    w = np.ascontiguousarray(edge_weights, dtype=np.float64)
    es = np.ascontiguousarray(
        edge_sizes if edge_sizes is not None else np.ones(len(uv)),
        dtype=np.float64)
    ns = np.ascontiguousarray(
        node_sizes if node_sizes is not None else np.ones(n_nodes),
        dtype=np.float64)
    lib = _load()
    if lib is not None:
        out = np.empty(n_nodes, dtype=np.uint64)
        lib.agglomerate_edge_weighted(n_nodes, len(uv), uv, w, es, ns,
                                      float(threshold),
                                      float(size_regularizer), out)
        return out
    return _py_agglomerate(n_nodes, uv, w, es, ns, threshold,
                           size_regularizer)


def _py_agglomerate(n_nodes, uv, w, es, ns, threshold, size_regularizer):
    import heapq

    adj = [dict() for _ in range(n_nodes)]
    for (u, v), ww, s in zip(uv, w, es):
        if u == v:
            continue
        ws0, s0 = adj[u].get(v, (0.0, 0.0))
        adj[u][v] = (ws0 + ww * s, s0 + s)
        adj[v][u] = adj[u][v]
    nsize = ns.copy()
    parent = np.arange(n_nodes)

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    def prio(ru, rv, ws, s):
        p = ws / s
        if size_regularizer > 0:
            hm = 2.0 / (1.0 / nsize[ru] + 1.0 / nsize[rv])
            p *= (hm / 2.0) ** size_regularizer
        return p

    heap = [(prio(u, v, ws, s), u, v)
            for u in range(n_nodes) for v, (ws, s) in adj[u].items() if v > u]
    heapq.heapify(heap)
    while heap:
        p, u, v = heapq.heappop(heap)
        if p >= threshold:
            break
        ru, rv = find(u), find(v)
        if ru == rv:
            continue
        acc = adj[ru].get(rv)
        if acc is None:
            continue
        live = prio(ru, rv, *acc)
        if live != p or u != min(ru, rv) or v != max(ru, rv):
            heapq.heappush(heap, (live, min(ru, rv), max(ru, rv)))
            continue
        if len(adj[ru]) < len(adj[rv]):
            ru, rv = rv, ru
        parent[rv] = ru
        nsize[ru] += nsize[rv]
        adj[ru].pop(rv, None)
        adj[rv].pop(ru, None)
        for n, (ws2, s2) in adj[rv].items():
            adj[n].pop(rv, None)
            ws0, s0 = adj[ru].get(n, (0.0, 0.0))
            adj[ru][n] = (ws0 + ws2, s0 + s2)
            adj[n][ru] = adj[ru][n]
            heapq.heappush(heap, (prio(ru, find(n), *adj[ru][n]),
                                  min(ru, n), max(ru, n)))
        adj[rv].clear()
    roots = np.array([find(i) for i in range(n_nodes)])
    _, labels = np.unique(roots, return_inverse=True)
    return labels.astype(np.uint64)


# ---------------------------------------------------------------------------
# skeletonization
# ---------------------------------------------------------------------------

def seeded_watershed_u8(height: np.ndarray, seeds: np.ndarray) -> np.ndarray:
    """Seeded 3d priority-flood watershed over a uint8 height map — the
    vigra ``watershedsNew`` algorithm (reference: utils/volume_utils.py:124)
    as a C++ monotone bucket-queue flood; the reference-faithful CPU
    watershed for ``impl='host'`` task configs.  Returns int64 labels
    (seeds preserved, every seed-connected voxel labeled, 6-connectivity).
    """
    if height.ndim != 3:
        raise ValueError("seeded_watershed_u8 expects a 3d volume")
    hq = np.ascontiguousarray(height, dtype=np.uint8)
    labels = np.ascontiguousarray(seeds, dtype=np.int64).copy()
    lib = _load()
    if lib is not None:
        lib.seeded_watershed_u8(hq, *hq.shape, labels)
        return labels
    # fallback without a compiler: the level-ordered flood formulation
    # (ops/watershed.py) on the CPU jax backend — same flooding semantics,
    # slower than the C++ bucket queue.  Negative labels are barriers in
    # the C++ convention: express them as a mask so the flood never enters,
    # and restore them in the output.
    import jax.numpy as jnp

    from ..ops.watershed import seeded_watershed_flood

    if labels.size and labels.max() >= 2 ** 31:
        raise ValueError("python fallback is int32-seeded; relabel first")
    barrier = labels < 0
    out = seeded_watershed_flood(
        jnp.asarray(hq.astype("float32")),
        jnp.asarray(np.where(barrier, 0, labels).astype("int32")),
        mask=jnp.asarray(~barrier))
    out = np.asarray(out).astype(np.int64)
    out[barrier] = labels[barrier]
    return out


def size_filter_u8(height: np.ndarray, labels: np.ndarray,
                   min_size: int) -> np.ndarray:
    """Remove fragments below ``min_size`` and regrow their voxels from
    the surviving neighborhood by a LOCAL priority flood (touches only the
    removed voxels; the reference regrows with a second full watershed).
    Requires the native library (callers fall back to ops.size_filter)."""
    if not have_native():
        raise RuntimeError("size_filter_u8 needs the native library")
    hq = np.ascontiguousarray(height, dtype=np.uint8)
    out = np.ascontiguousarray(labels, dtype=np.int64).copy()
    _load().size_filter_u8(hq, *hq.shape, out, int(min_size))
    return out


def skeletonize_3d(volume: np.ndarray) -> np.ndarray:
    """Thin a 3d binary volume to a 1-voxel skeleton by topological
    border-peeling (skimage skeletonize_3d equivalent; the reference's
    skeletons component uses that — skeletons/skeletonize.py:129-157)."""
    if volume.ndim != 3:
        raise ValueError("skeletonize_3d expects a 3d volume")
    vol = np.ascontiguousarray(volume != 0, dtype=np.uint8)
    lib = _load()
    if lib is not None:
        lib.skeletonize_3d(vol, *vol.shape)
        return vol.astype(bool)
    return _py_skeletonize(vol)


def _py_skeletonize(vol: np.ndarray) -> np.ndarray:
    """Python fallback: same directional border-peeling with simple-point
    tests (slow; small per-object bounding boxes only)."""
    from scipy import ndimage

    vol = vol.astype(bool)
    struct26 = np.ones((3, 3, 3), bool)
    struct6 = ndimage.generate_binary_structure(3, 1)

    def simple_point(padded, z, y, x):
        nb = padded[z - 1:z + 2, y - 1:y + 2, x - 1:x + 2].copy()
        center = nb[1, 1, 1]
        assert center
        nb[1, 1, 1] = False
        lab, n_obj = ndimage.label(nb, structure=struct26)
        if n_obj != 1:
            return False
        bg = ~nb
        bg[1, 1, 1] = False
        # 18-neighborhood only (drop corners)
        manhattan = np.add.outer(np.add.outer(
            np.abs(np.arange(3) - 1), np.abs(np.arange(3) - 1)),
            np.abs(np.arange(3) - 1))
        bg &= manhattan <= 2
        lab_bg, _ = ndimage.label(bg, structure=struct6)
        face_ids = {lab_bg[0, 1, 1], lab_bg[2, 1, 1], lab_bg[1, 0, 1],
                    lab_bg[1, 2, 1], lab_bg[1, 1, 0], lab_bg[1, 1, 2]}
        face_ids.discard(0)
        return len(face_ids) == 1

    changed = True
    while changed:
        changed = False
        for axis in range(3):
            for direction in (-1, 1):
                padded = np.pad(vol, 1)
                shifted = np.roll(padded, direction, axis=axis)
                border = padded & ~shifted
                n_nb = ndimage.convolve(padded.astype(np.uint8),
                                        struct26.astype(np.uint8),
                                        mode="constant") - padded
                cand = np.stack(np.nonzero(border & (n_nb > 1)), 1)
                for z, y, x in cand:
                    if not padded[z, y, x]:
                        continue
                    nbh = padded[z - 1:z + 2, y - 1:y + 2, x - 1:x + 2]
                    if (nbh.sum() - 1) <= 1:
                        continue
                    if simple_point(padded, z, y, x):
                        padded[z, y, x] = False
                        changed = True
                vol = padded[1:-1, 1:-1, 1:-1]
    return vol


# ---------------------------------------------------------------------------
# graph watershed
# ---------------------------------------------------------------------------

def graph_watershed(n_nodes: int, uv_ids: np.ndarray, edge_weights: np.ndarray,
                    seeds: np.ndarray, grow_smallest_first: bool = True
                    ) -> np.ndarray:
    """Seeded watershed on a graph (nifty edgeWeightedWatershedsSegmentation
    equivalent).  ``grow_smallest_first=True`` floods across the lowest
    boundary evidence first (the reference's convention with probability
    weights, postprocess/graph_watershed_assignments.py:172)."""
    uv = _as_uv(uv_ids)
    w = np.ascontiguousarray(edge_weights, dtype=np.float64)
    if grow_smallest_first:
        w = -w
    out = np.ascontiguousarray(seeds, dtype=np.uint64).copy()
    lib = _load()
    if lib is not None:
        lib.graph_watershed(n_nodes, len(uv), uv, w, out)
        return out
    # fallback: heap-based python
    import heapq

    adj = [[] for _ in range(n_nodes)]
    for (u, v), ww in zip(uv, w):
        adj[u].append((v, ww))
        adj[v].append((u, ww))
    heap = []
    for i in range(n_nodes):
        if out[i]:
            for n, ww in adj[i]:
                if not out[n]:
                    heapq.heappush(heap, (-ww, i, n))
    while heap:
        nw, frm, to = heapq.heappop(heap)
        if out[to]:
            continue
        out[to] = out[frm]
        for n, ww in adj[to]:
            if not out[n]:
                heapq.heappush(heap, (-ww, to, n))
    return out
