"""blocking-under-lock: blocking/IO calls lexically inside ``with
<lock>:`` blocks in ``core/``.

This is the PR-15 "span emitted OUTSIDE the locked accumulation" rule,
generalized: anything that can block for unbounded time (file IO,
device syncs, ``.result()``/``.join()`` waits, subprocess, sleeps) or
re-enters the telemetry/stage machinery must not run while a lock is
held — it stalls every thread contending on that lock and is the
static half of the lock-order witness's held-across-blocking-call
check (``core.runtime.witness_blocking``).

Deliberately NOT flagged:

* ``.wait()`` — Condition waits RELEASE the lock while blocked;
  waiting under ``with cond:`` is the correct idiom,
* ``", ".join(...)`` / ``os.path.join(...)`` — string/path joins, not
  thread joins,
* code inside nested ``def``/``lambda`` — defined under the lock,
  executed elsewhere.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator, List, Optional

from .base import Finding, Pass, SourceFile, dotted_name

#: last-segment call names that re-enter stage/telemetry/status IO
_REENTRANT = frozenset({
    "stage_add", "stage_bytes", "stage", "timed_stage",
    "flight_record", "write_prometheus", "write_metrics",
    "write_config", "_write_status", "_store", "_load",
})

_OS_BLOCKING = frozenset({
    "os.replace", "os.remove", "os.rename", "os.makedirs",
    "os.listdir", "os.stat", "os.unlink", "os.fsync",
})


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


_LOCK_NAME = re.compile(r"(?:^|_)r?lock$|^r?lock(?:$|_)")


def _looks_like_lock(expr: ast.AST) -> Optional[str]:
    """The lock's display name when ``expr`` is a lock acquisition.
    Word-boundary match so e.g. ``witness_blocking`` ("bLOCKing") does
    not read as a lock."""
    if isinstance(expr, ast.Call):
        expr = expr.func
    name = dotted_name(expr)
    if name and _LOCK_NAME.search(_last(name).lower()):
        return name
    return None


def _walk_no_fn(node: ast.AST) -> Iterator[ast.AST]:
    """Walk skipping nested function/lambda bodies (they run later,
    not under the lock)."""
    yield node
    for child in ast.iter_child_nodes(node):
        if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
            continue
        yield from _walk_no_fn(child)


def _string_or_path_join(func: ast.Attribute) -> bool:
    recv = func.value
    if isinstance(recv, ast.Constant) and isinstance(recv.value, str):
        return True
    rn = dotted_name(recv)
    return bool(rn) and ("path" in rn.lower() or rn in ("os", "sep"))


def _violation(call: ast.Call) -> Optional[str]:
    fn = dotted_name(call.func)
    if fn is not None:
        last = _last(fn)
        if fn in ("open", "print"):
            return "%s() is IO" % fn
        if fn in _OS_BLOCKING or fn.startswith("subprocess."):
            return "%s() is blocking IO" % fn
        if fn == "time.sleep":
            return "time.sleep() stalls every contender"
        if last == "dump" and "json" in fn.lower():
            return "%s() serializes + writes under the lock" % fn
        if last in _REENTRANT:
            return "`%s` re-enters stage/telemetry/status IO" % fn
        if last == "block_until_ready":
            return "device sync under the lock"
        if last == "result":
            return ".result() waits on another thread under the lock"
        if last == "join" and isinstance(call.func, ast.Attribute) \
                and not _string_or_path_join(call.func):
            return ".join() waits on another thread under the lock"
        return None
    if isinstance(call.func, ast.Attribute):
        attr = call.func.attr
        if attr == "block_until_ready":
            return "device sync under the lock"
        if attr == "result":
            return ".result() waits on another thread under the lock"
        if attr == "join" and not _string_or_path_join(call.func):
            return ".join() waits on another thread under the lock"
    return None


def run(sf: SourceFile) -> List[Finding]:
    if not sf.in_dir("core"):
        return []
    out: List[Finding] = []
    seen = set()
    for node in ast.walk(sf.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lock = None
        for item in node.items:
            lock = _looks_like_lock(item.context_expr)
            if lock:
                break
        if not lock:
            continue
        # block-level suppression: a reasoned pragma on the ``with``
        # line covers every finding inside the block (the common case
        # where the IO *is* the critical section being serialized)
        block_pragma = sf.pragma_for(node.lineno)
        if block_pragma is not None and (
                not block_pragma.covers("blocking-under-lock")
                or not block_pragma.reason):
            block_pragma = None
        for stmt in node.body:
            for sub in _walk_no_fn(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                why = _violation(sub)
                if why is None:
                    continue
                key = (sub.lineno, sub.col_offset)
                if key in seen:
                    continue
                seen.add(key)
                f = Finding(
                    sf.rel, sub.lineno, "blocking-under-lock",
                    "inside `with %s`: %s" % (lock, why))
                if block_pragma is not None:
                    f.suppressed = True
                    f.reason = block_pragma.reason
                out.append(f)
    return out


PASS = Pass(name="blocking-under-lock",
            rules=("blocking-under-lock",), run=run)
