"""Distributed region-adjacency-graph construction.

Re-specification of the reference's ``graph/`` package (SURVEY §2.1):
per-block sub-graphs -> hierarchical merge over scales -> global graph ->
block-edge -> global-edge id mapping.  The reference delegates each step to
``nifty.distributed`` C++ (initial_sub_graphs.py:114-118 ndist.
computeMergeableRegionGraph, merge_sub_graphs.py:133-141 ndist.mergeSubgraphs,
map_edge_ids.py:95-118 ndist.mapEdgeIds); here blocks are extracted by a
jitted device kernel (ops/rag.py) and merged with vectorized host set ops
(core/graph.py).
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..core import graph as g
from ..core.blocking import Blocking
from ..core.runtime import BlockTask, stream_window
from ..core.storage import file_reader
from ..core.workflow import Task


class InitialSubGraphs(BlockTask):
    """Per-block RAG extraction (reference: InitialSubGraphs,
    initial_sub_graphs.py:21).  Reads the label block with a +1 upper-face
    halo (increaseRoi) so every inter-block face is owned exactly once."""

    task_name = "initial_sub_graphs"

    def __init__(self, input_path: str, input_key: str, graph_path: str,
                 **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.graph_path = graph_path
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"ignore_label": True})
        return conf

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            shape = list(f[self.input_key].shape)
        block_shape = self.global_block_shape()
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "graph_path": self.graph_path,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        import jax.numpy as jnp

        from ..ops.rag import (densify_labels, device_edge_stats_finalize,
                               device_edge_stats_submit, label_pairs)

        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        ignore_label = bool(cfg.get("ignore_label", True))
        e_max = int(cfg.get("e_max", 65536))
        f = file_reader(cfg["input_path"], "r")
        ds = f[cfg["input_key"]]

        # three-stage pipeline over the job's blocks: threaded read
        # look-ahead feeds submit, submit enqueues the device programs
        # without synchronizing, drain materializes — block i+1's
        # transfer/compute overlap block i's readback + IO
        def load(block_id: int):
            block = blocking.get_block(block_id)
            # +1 halo on upper faces only, clipped at the volume border
            end = [min(e + 1, s) for e, s in zip(block.end, cfg["shape"])]
            bb = tuple(slice(b, e) for b, e in zip(block.begin, end))
            return block_id, block, np.asarray(ds[bb])

        host_impl = cfg.get("impl") == "host"

        def submit(entry):
            block_id, block, labels = entry
            if host_impl:
                # reference-faithful CPU path: numpy slicing + unique (the
                # shape of the reference's ndist C++ block extraction)
                from ..ops.rag import host_label_pairs

                uniq = np.unique(labels)
                zero_present = bool(len(uniq) and uniq[0] == 0)
                nodes = (uniq if (zero_present and not ignore_label)
                         else uniq[uniq != 0])
                edges = host_label_pairs(labels, ignore_label,
                                         tuple(block.shape))
                return block_id, nodes, None, edges.astype("uint64")
            lut, dense = densify_labels(labels)
            # nodes straight from the densification LUT (sorted uniques
            # with 0 prepended) — no second full-block unique, and the
            # pending window holds only the small per-block tables
            zero_present = bool(dense.min() == 0) if dense.size else False
            nodes = lut if (zero_present and not ignore_label) else lut[1:]
            u, v, ok = label_pairs(jnp.asarray(dense),
                                   ignore_label=ignore_label,
                                   inner_shape=tuple(block.shape))
            # edge dedup ON DEVICE: only the compact edge table crosses the
            # host link (the padded pair arrays are ~6x the block size)
            handles = device_edge_stats_submit(
                u, v, jnp.zeros_like(u, jnp.float32), ok, e_max=e_max)
            return block_id, nodes, lut, handles

        def drain(entry):
            block_id, nodes, lut, handles = entry
            if host_impl:
                edges = handles
            else:
                uv_dense, _ = device_edge_stats_finalize(handles, e_max)
                edges = np.stack([lut[uv_dense[:, 0]], lut[uv_dense[:, 1]]],
                                 axis=1).astype("uint64")
            g.save_sub_graph(cfg["graph_path"], 0, block_id,
                             nodes.astype("uint64"), edges)
            log_fn(f"processed block {block_id}")

        from ..core.runtime import prefetch_iter

        for _ in stream_window(prefetch_iter(job_config["block_list"], load),
                               submit, drain,
                               window=int(cfg.get("stream_window", 3))):
            pass


class MergeSubGraphs(BlockTask):
    """Hierarchical union of child sub-graphs (reference: MergeSubGraphs,
    merge_sub_graphs.py).  At scale s, one merged block covers 2**s base
    blocks per axis; with ``merge_complete_graph`` the single top job writes
    the global graph dataset."""

    task_name = "merge_sub_graphs"

    def __init__(self, graph_path: str, scale: int,
                 merge_complete_graph: bool = False, output_key: str = "graph",
                 input_path: str = "", input_key: str = "", **kw):
        self.graph_path = graph_path
        self.scale = scale
        self.merge_complete_graph = merge_complete_graph
        self.output_key = output_key
        self.input_path = input_path
        self.input_key = input_key
        self.identifier = f"s{scale}" + ("_full" if merge_complete_graph else "")
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            shape = list(f[self.input_key].shape)
        base_bs = self.global_block_shape()
        if self.merge_complete_graph:
            self.run_jobs(None, {
                "graph_path": self.graph_path, "scale": self.scale,
                "shape": shape, "block_shape": base_bs,
                "merge_complete_graph": True, "output_key": self.output_key,
                "ignore_label": True,
            })
            return
        factor = 2 ** self.scale
        scale_bs = [b * factor for b in base_bs]
        block_list = self.blocks_in_volume(shape, scale_bs)
        self.run_jobs(block_list, {
            "graph_path": self.graph_path, "scale": self.scale,
            "shape": shape, "block_shape": base_bs,
            "merge_complete_graph": False, "output_key": self.output_key,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        scale = int(cfg["scale"])
        base_bs = cfg["block_shape"]
        shape = cfg["shape"]
        graph_path = cfg["graph_path"]

        if cfg.get("merge_complete_graph"):
            # union every sub-graph at `scale` (scale may be 0: union of all
            # initial blocks)
            src_blocking = (Blocking(shape, [b * 2 ** scale for b in base_bs])
                            if scale > 0 else Blocking(shape, base_bs))
            read_scale = scale
            edge_lists = []
            node_lists = []
            for bid in range(src_blocking.n_blocks):
                data = g.load_sub_graph(graph_path, read_scale, bid)
                edge_lists.append(data["edges"])
                node_lists.append(data["nodes"])
            edges = g.merge_edge_lists(edge_lists)
            nodes = (np.unique(np.concatenate([n for n in node_lists if len(n)]))
                     if any(len(n) for n in node_lists) else np.zeros(0, "uint64"))
            g.save_graph(graph_path, cfg["output_key"], nodes, edges, shape,
                         ignore_label=bool(cfg.get("ignore_label", True)))
            # record the decomposition the sub-graphs were built on: the
            # problem container is self-describing, so the solver stack
            # (SolveSubproblems/ReduceProblem) iterates the SAME grid even
            # when it differs from the global block shape (mesh-resident
            # slabs)
            with file_reader(graph_path) as f:
                f[cfg["output_key"]].attrs["sub_graph_block_shape"] = \
                    list(base_bs)
            log_fn(f"global graph: {len(nodes)} nodes, {len(edges)} edges")
            return

        child_blocking = Blocking(shape, [b * 2 ** (scale - 1) for b in base_bs])
        merged_blocking = Blocking(shape, [b * 2 ** scale for b in base_bs])
        for block_id in job_config["block_list"]:
            block = merged_blocking.get_block(block_id)
            child_ids = child_blocking.blocks_in_roi(block.begin, block.end)
            edge_lists, node_lists = [], []
            for cid in child_ids:
                data = g.load_sub_graph(graph_path, scale - 1, cid)
                edge_lists.append(data["edges"])
                node_lists.append(data["nodes"])
            edges = g.merge_edge_lists(edge_lists)
            nodes = (np.unique(np.concatenate([n for n in node_lists if len(n)]))
                     if any(len(n) for n in node_lists) else np.zeros(0, "uint64"))
            g.save_sub_graph(graph_path, scale, block_id, nodes, edges)
            log_fn(f"processed block {block_id}")


class MapEdgeIds(BlockTask):
    """Map per-block edges to global edge ids at one scale (reference:
    MapEdgeIds, map_edge_ids.py:95-118)."""

    task_name = "map_edge_ids"

    def __init__(self, graph_path: str, scale: int, graph_key: str = "graph",
                 input_path: str = "", input_key: str = "", **kw):
        self.graph_path = graph_path
        self.scale = scale
        self.graph_key = graph_key
        self.input_path = input_path
        self.input_key = input_key
        self.identifier = f"s{scale}"
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            shape = list(f[self.input_key].shape)
        base_bs = self.global_block_shape()
        scale_bs = [b * 2 ** self.scale for b in base_bs]
        block_list = self.blocks_in_volume(shape, scale_bs)
        self.run_jobs(block_list, {
            "graph_path": self.graph_path, "scale": self.scale,
            "graph_key": self.graph_key,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        _, global_edges, _ = g.load_graph(cfg["graph_path"], cfg["graph_key"])
        for block_id in job_config["block_list"]:
            data = g.load_sub_graph(cfg["graph_path"], cfg["scale"], block_id)
            edge_ids = g.find_edge_ids(global_edges, data["edges"])
            g.save_sub_graph(cfg["graph_path"], cfg["scale"], block_id,
                             data["nodes"], data["edges"], edge_ids)
            log_fn(f"processed block {block_id}")


class GraphWorkflow(Task):
    """InitialSubGraphs -> MergeSubGraphs (scales) -> final merge ->
    MapEdgeIds per scale (reference: graph_workflow.py:22-64)."""

    def __init__(self, input_path: str, input_key: str, graph_path: str,
                 tmp_folder: str, config_dir: str, max_jobs: int = 1,
                 target: str = "local", n_scales: int = 1,
                 output_key: str = "graph", dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.graph_path = graph_path
        self.n_scales = n_scales
        self.output_key = output_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def _common(self):
        return dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                    max_jobs=self.max_jobs, target=self.target)

    def requires(self):
        dep = InitialSubGraphs(
            input_path=self.input_path, input_key=self.input_key,
            graph_path=self.graph_path, dependency=self.dependency,
            **self._common())
        for scale in range(1, self.n_scales):
            dep = MergeSubGraphs(
                graph_path=self.graph_path, scale=scale,
                input_path=self.input_path, input_key=self.input_key,
                dependency=dep, **self._common())
        dep = MergeSubGraphs(
            graph_path=self.graph_path, scale=self.n_scales - 1,
            merge_complete_graph=True, output_key=self.output_key,
            input_path=self.input_path, input_key=self.input_key,
            dependency=dep, **self._common())
        for scale in range(self.n_scales):
            dep = MapEdgeIds(
                graph_path=self.graph_path, scale=scale,
                graph_key=self.output_key,
                input_path=self.input_path, input_key=self.input_key,
                dependency=dep, **self._common())
        return dep

    def output(self):
        from ..core.workflow import FileTarget

        return FileTarget(os.path.join(
            self.tmp_folder, f"map_edge_ids_s{self.n_scales - 1}.status"))
