"""Minimal workflow DAG engine (luigi replacement).

The reference drives everything through luigi (`luigi.build([task],
local_scheduler=True)`, example/multicut.py:95-106) with filesystem log files
as completion targets (cluster_tasks.py:247-248) — giving free workflow-level
resume.  This module keeps exactly those semantics — tasks declare
``requires()`` and ``output()`` targets; ``build()`` topologically executes
incomplete tasks; completed targets are skipped — without the luigi dependency
or its worker-scheduler machinery, which the TPU runtime replaces.
"""

from __future__ import annotations

import logging
import os
import traceback
from typing import Dict, Iterable, List, Optional, Union

logger = logging.getLogger("cluster_tools_tpu")


class Target:
    def exists(self) -> bool:
        raise NotImplementedError


class FileTarget(Target):
    def __init__(self, path: str):
        self.path = path

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def touch(self) -> None:
        os.makedirs(os.path.dirname(os.path.abspath(self.path)), exist_ok=True)
        with open(self.path, "a"):
            pass

    def __repr__(self):
        return f"FileTarget({self.path})"


class DummyTarget(Target):
    """Always complete (reference: utils/task_utils.py:11-15 DummyTarget)."""

    def exists(self) -> bool:
        return True


class Task:
    """A node of the workflow DAG.

    Subclasses implement ``requires()`` (upstream tasks), ``output()``
    (completion target) and ``run()``.  Identity for deduplication is
    ``task_id`` which defaults to the class name plus the output path.
    """

    task_name: str = ""

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            setattr(self, k, v)
        if not self.task_name:
            self.task_name = type(self).__name__

    def requires(self) -> Union["Task", Iterable["Task"], None]:
        return None

    def output(self) -> Target:
        return DummyTarget()

    def run(self) -> None:
        pass

    def complete(self) -> bool:
        return self.output().exists()

    @property
    def task_id(self) -> str:
        out = self.output()
        # non-FileTarget outputs get identity-based ids so two distinct task
        # instances are never silently deduplicated
        suffix = out.path if isinstance(out, FileTarget) else hex(id(self))
        return f"{type(self).__name__}:{suffix}"

    def _deps(self) -> List["Task"]:
        req = self.requires()
        if req is None:
            return []
        if isinstance(req, Task):
            return [req]
        return [t for t in req if t is not None]


class DummyTask(Task):
    """Always-complete dependency root (reference: utils/task_utils.py:11-15)."""

    task_id = "DummyTask"  # all instances interchangeable

    def output(self) -> Target:
        return DummyTarget()


class BuildError(RuntimeError):
    def __init__(self, task: Task, cause: BaseException):
        super().__init__(f"task {task.task_id} failed: {cause}")
        self.task = task
        self.cause = cause


def build(tasks: Iterable[Task], raise_on_failure: bool = False) -> bool:
    """Execute the DAG rooted at ``tasks`` depth-first, skipping complete tasks.

    Returns True on success — matching `luigi.build`'s boolean contract used
    throughout the reference tests.
    """
    done: Dict[str, bool] = {}
    order: List[Task] = []

    def visit(task: Task, stack: List[str]):
        tid = task.task_id
        if tid in done:
            if not done[tid] and tid in stack:
                raise RuntimeError(f"dependency cycle at {tid}")
            return
        if tid in stack:
            raise RuntimeError(f"dependency cycle at {tid}")
        done[tid] = False
        for dep in task._deps():
            visit(dep, stack + [tid])
        done[tid] = True
        order.append(task)

    for t in tasks:
        visit(t, [])

    for task in order:
        if task.complete():
            logger.info("skipping complete task %s", task.task_id)
            continue
        logger.info("running task %s", task.task_id)
        try:
            task.run()
        except Exception as e:
            logger.error("task %s failed:\n%s", task.task_id, traceback.format_exc())
            if raise_on_failure:
                raise BuildError(task, e) from e
            return False
        if not task.complete():
            logger.error("task %s ran but target %s missing", task.task_id, task.output())
            if raise_on_failure:
                raise BuildError(task, RuntimeError("output target missing after run"))
            return False
    return True
