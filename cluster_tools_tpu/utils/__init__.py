"""Utility surface (reference: cluster_tools/utils/): validation metrics,
affine transformations, Knossos reader, mesh extraction."""

from .knossos import KnossosDataset, KnossosFile
from .mesh import marching_tetrahedra, object_mesh, smooth_mesh
from .transformations import (matrix_2d, matrix_3d, parameters_from_matrix,
                              transform_roi)

__all__ = [
    "KnossosDataset", "KnossosFile",
    "marching_tetrahedra", "object_mesh", "smooth_mesh",
    "matrix_2d", "matrix_3d", "parameters_from_matrix", "transform_roi",
]
