"""Open-loop load harness for the resident server (ISSUE 16 tentpole 1).

The serve path has only ever been smoke-tested with two tenants
(ROADMAP item 3); this module generates the missing evidence.  It is an
OPEN-loop generator: arrivals follow a Poisson schedule fixed ahead of
time and do NOT wait for completions — the defining property that makes
overload visible (a closed-loop generator self-throttles and hides the
queueing collapse this harness exists to measure).  Every request's
latency is charged from its SCHEDULED arrival instant (``arrival_t`` on
``submit``), so queue buildup under overload compounds into the tail
exactly as it would for real proofreaders.

Two execution modes share one schedule generator:

* **virtual** (:func:`run_virtual`, tier-1): single-threaded.  The
  server takes a :class:`VirtualClock`, the :class:`SyntheticPipeline`
  advances that same clock instead of sleeping, and the loop alternates
  "admit due arrivals" with ``server.step_once()``.  No threads, no
  wall clock — the same seed yields the same schedule, the same
  interleaving, the same latencies, and therefore byte-identical
  histogram bucket counts (asserted in tier-1).
* **threaded** (:func:`run_threaded`): the real server worker thread
  plus a submitter that sleeps until each scheduled arrival.  Used by
  ``bench.py serve`` for the committed BENCH_serve.json numbers (stub
  pipeline at several load levels, plus one real-pipeline row).

The request mix is declarative (:class:`LoadSpec`): hundreds of
synthetic tenants, weighted priority lanes, and weighted ROI-size
classes that map to per-request block counts via the pipeline's
``request_n_blocks`` hook.
"""

from __future__ import annotations

import random
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, \
    Sequence, Tuple

import numpy as np

from .server import AdmissionRejected, ResidentSegmentationServer


class LoadSpec(NamedTuple):
    """Declarative request mix for one load level.

    ``lanes`` and ``roi_classes`` are weighted choices;
    ``roi_classes`` rows are ``(name, n_blocks, weight)`` — the block
    count is what the synthetic pipeline's service time scales with, so
    the mix directly shapes the latency distribution.
    """

    seed: int = 0
    rate_hz: float = 50.0            # aggregate Poisson arrival rate
    n_requests: int = 200
    n_tenants: int = 100
    lanes: Tuple[Tuple[str, float], ...] = (("edit", 0.7), ("bulk", 0.3))
    roi_classes: Tuple[Tuple[str, int, float], ...] = (
        ("small", 1, 0.6), ("medium", 4, 0.3), ("large", 16, 0.1))


class Arrival(NamedTuple):
    t: float                         # scheduled arrival (s from start)
    tenant: str
    lane: str
    roi: str
    n_blocks: int


class VirtualClock:
    """A clock that only moves when told to — the shared timebase of the
    deterministic mode (generator, server and SLO engine all read it)."""

    def __init__(self, t0: float = 0.0):
        self.t = float(t0)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)

    def advance_to(self, t: float) -> None:
        self.t = max(self.t, float(t))


class SyntheticPipeline:
    """Stub request pipeline with a deterministic cost model.

    Service time is ``prepare_s + n_blocks * block_s + finalize_s``;
    with a :class:`VirtualClock` the cost advances the clock (virtual
    mode), without one it really sleeps (threaded mode).  ``fail_every``
    > 0 makes every Nth prepared request raise, exercising the
    availability SLO and the server's tenant isolation under load.
    """

    n_blocks = 1                      # fallback when request_n_blocks absent

    def __init__(self, clock: Optional[VirtualClock] = None,
                 prepare_s: float = 0.002, block_s: float = 0.004,
                 finalize_s: float = 0.001, fail_every: int = 0):
        self.clock = clock
        self.prepare_s = float(prepare_s)
        self.block_s = float(block_s)
        self.finalize_s = float(finalize_s)
        self.fail_every = int(fail_every)
        self.prepared = 0

    def _spend(self, dt: float) -> None:
        if self.clock is not None:
            self.clock.advance(dt)
        else:
            time.sleep(dt)

    def request_n_blocks(self, volume) -> int:
        # the generator encodes the ROI class's block count in the stub
        # volume's length (see synthetic_volume)
        return max(1, int(volume.shape[0]))

    def prepare(self, volume) -> Dict[str, Any]:
        self.prepared += 1
        self._spend(self.prepare_s)
        if self.fail_every and self.prepared % self.fail_every == 0:
            raise RuntimeError("synthetic pipeline fault injection")
        return {"n_blocks": self.request_n_blocks(volume)}

    def run_block(self, ctx, bid: int):
        self._spend(self.block_s)
        return bid

    def finalize(self, ctx, block_results) -> Dict[str, Any]:
        self._spend(self.finalize_s)
        return {"n_fragments": len(block_results),
                "n_segments": len(block_results)}


def _weighted(rng: random.Random, rows: Sequence[Tuple], weight_idx: int):
    """Seeded weighted choice (no numpy: the schedule must be a pure
    function of the stdlib Random stream)."""
    total = sum(r[weight_idx] for r in rows)
    x = rng.random() * total
    acc = 0.0
    for r in rows:
        acc += r[weight_idx]
        if x < acc:
            return r
    return rows[-1]


def generate_schedule(spec: LoadSpec) -> List[Arrival]:
    """The open-loop arrival schedule: Poisson inter-arrivals at
    ``rate_hz``, tenant/lane/ROI drawn per arrival from ONE seeded
    stream.  A pure function of the spec — same seed, same schedule."""
    rng = random.Random(spec.seed)
    t = 0.0
    out: List[Arrival] = []
    for _ in range(int(spec.n_requests)):
        t += rng.expovariate(spec.rate_hz)
        tenant = f"t{rng.randrange(spec.n_tenants):04d}"
        lane = _weighted(rng, spec.lanes, 1)[0]
        roi_name, n_blocks, _ = _weighted(rng, spec.roi_classes, 2)
        out.append(Arrival(round(t, 9), tenant, lane, roi_name,
                           int(n_blocks)))
    return out


def synthetic_volume(arrival: Arrival) -> np.ndarray:
    """The stub request payload: a tiny vector whose LENGTH carries the
    ROI class's block count into ``SyntheticPipeline.request_n_blocks``."""
    return np.zeros((arrival.n_blocks,), dtype=np.uint8)


def run_virtual(spec: LoadSpec, workdir: str, *,
                pipeline: Optional[SyntheticPipeline] = None,
                slo_engine=None,
                admission_hook=None,
                metrics_path: str = "") -> Dict[str, Any]:
    """Deterministic single-threaded replay of the schedule under a
    shared virtual clock.  Returns :func:`summarize`'s row plus the
    schedule and the server (tests inspect both)."""
    clock = VirtualClock()
    if pipeline is None:
        pipeline = SyntheticPipeline(clock=clock)
    elif pipeline.clock is None:
        raise ValueError("run_virtual needs a clock-driven pipeline "
                         "(pass SyntheticPipeline(clock=...))")
    else:
        clock = pipeline.clock
    if slo_engine is not None:
        slo_engine.clock = clock
    server = ResidentSegmentationServer(
        workdir, pipeline, clock=clock, slo=slo_engine,
        admission_hook=admission_hook, metrics_path=metrics_path)
    schedule = generate_schedule(spec)
    rejected = 0
    i = 0
    while True:
        # admit every arrival that is due at the current virtual time
        while i < len(schedule) and schedule[i].t <= clock():
            a = schedule[i]
            i += 1
            try:
                server.submit(a.tenant, synthetic_volume(a), lane=a.lane,
                              arrival_t=a.t)
            except AdmissionRejected:
                rejected += 1
        if not server.step_once():
            if i >= len(schedule):
                break
            # idle: jump straight to the next scheduled arrival
            clock.advance_to(schedule[i].t)
    wall = clock() - (schedule[0].t if schedule else 0.0)
    row = summarize(server, spec, wall, mode="virtual",
                    rejected=rejected, slo_engine=slo_engine)
    row["server"] = server
    row["schedule"] = schedule
    return row


def run_threaded(spec: LoadSpec, workdir: str, *,
                 pipeline=None,
                 slo_engine=None,
                 admission_hook=None,
                 volume_fn: Callable[[Arrival], np.ndarray]
                 = synthetic_volume,
                 metrics_path: Optional[str] = None,
                 drain_timeout: Optional[float] = 120.0) -> Dict[str, Any]:
    """Real-time open loop: the server's worker thread consumes while
    this thread submits on the wall-clock schedule.  The committed
    BENCH_serve.json rows come from here."""
    if pipeline is None:
        pipeline = SyntheticPipeline()        # sleeps for real
    server = ResidentSegmentationServer(
        workdir, pipeline, slo=slo_engine,
        admission_hook=admission_hook, metrics_path=metrics_path)
    schedule = generate_schedule(spec)
    rejected = 0
    server.start()
    try:
        t0 = time.perf_counter()
        for a in schedule:
            dt = (t0 + a.t) - time.perf_counter()
            if dt > 0:
                time.sleep(dt)
            try:
                server.submit(a.tenant, volume_fn(a), lane=a.lane,
                              arrival_t=t0 + a.t)
            except AdmissionRejected:
                rejected += 1
        drained = server.drain(timeout=drain_timeout)
        wall = time.perf_counter() - t0
    finally:
        server.shutdown(drain=False)
    row = summarize(server, spec, wall, mode="threaded",
                    rejected=rejected, slo_engine=slo_engine)
    row["drained"] = bool(drained)
    return row


def _lane_row(hist, wait_hist) -> Dict[str, Any]:
    out = {
        "n": hist.count,
        "mean_s": round(hist.sum / hist.count, 6) if hist.count else 0.0,
        "p50_s": round(hist.quantile(0.50), 6),
        "p95_s": round(hist.quantile(0.95), 6),
        "p99_s": round(hist.quantile(0.99), 6),
    }
    if wait_hist is not None:
        out["queue_wait_p95_s"] = round(wait_hist.quantile(0.95), 6)
    return out


def summarize(server: ResidentSegmentationServer, spec: LoadSpec,
              wall_s: float, *, mode: str, rejected: int = 0,
              slo_engine=None) -> Dict[str, Any]:
    """One BENCH_serve row: offered vs served throughput, per-lane
    latency percentiles straight off the cumulative histograms, and the
    SLO engine's full burn-rate report."""
    lat, wait, _tenant = server.latency_histograms()
    served = sum(h.count for h in lat.values())
    failed = sum(1 for r in server.stats()["requests"]
                 if r["state"] != "done")
    row: Dict[str, Any] = {
        "mode": mode,
        "seed": spec.seed,
        "offered_hz": spec.rate_hz,
        "n_requests": spec.n_requests,
        "n_tenants": spec.n_tenants,
        "wall_s": round(float(wall_s), 4),
        "served": served,
        "failed": failed,
        "rejected": rejected,
        "throughput_hz": round(served / wall_s, 4) if wall_s > 0 else 0.0,
        "lanes": {l: _lane_row(h, wait.get(l))
                  for l, h in sorted(lat.items())},
    }
    if slo_engine is not None:
        row["slo"] = slo_engine.report()
    return row
