"""Sparse lifted problems from biological priors.

Re-specification of the reference's ``lifted_features/`` package: a lifted
edge connects two fragments that are *not* RAG neighbors but lie within
``graph_depth`` hops of each other; its cost comes from agreement of semantic
node labels (reference: sparse_lifted_neighborhood.py:107
``ndist.computeLiftedNeighborhoodFromNodeLabels``,
costs_from_node_labels.py:119-139, clear_lifted_edges_from_labels.py:83,
lifted_feature_workflow.py:14-160).

TPU-first design: the BFS-by-depth neighborhood is a node-chunked sparse
boolean matrix sweep (scipy CSR on host, memory bounded by the chunk);
costs are a vectorized label-compare over the lifted edge list, sharded
over edge chunks.

Problem-container layout:

    s0/lifted_nh_<prefix>     (L, 2) uint64 lifted pairs
    s0/lifted_costs_<prefix>  (L,) float64
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional

import numpy as np

from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import FileTarget, Task
from .node_labels import NodeLabelWorkflow


def save_edge_list(path: str, key: str, edges: np.ndarray) -> None:
    """Store an (N, 2) edge list; zero-size datasets are not representable
    in the chunked store, so empty lists are padded to one row with the true
    count in the ``n_edges`` attribute."""
    edges = np.asarray(edges, dtype="uint64").reshape(-1, 2)
    data = edges if len(edges) else np.zeros((1, 2), "uint64")
    with file_reader(path) as f:
        ds = f.require_dataset(key, data=data, shape=data.shape,
                               chunks=(min(int(1e6), len(data)), 2))
        ds.attrs["n_edges"] = int(len(edges))


def load_edge_list(path: str, key: str) -> np.ndarray:
    with file_reader(path, "r") as f:
        ds = f[key]
        n = int(ds.attrs.get("n_edges", ds.shape[0]))
        return ds[:][:n]


def lifted_neighborhood(uv_ids: np.ndarray, n_nodes: int, node_labels:
                        np.ndarray, graph_depth: int, mode: str = "all",
                        ignore_label: int = 0,
                        node_chunk: int = 100_000) -> np.ndarray:
    """All node pairs with graph distance in [2, graph_depth] whose labels
    pass ``mode`` ('all' | 'same' | 'different'); nodes with the ignore
    label never participate (reference semantics of
    computeLiftedNeighborhoodFromNodeLabels).

    BFS runs in source-node chunks: global boolean matrix powers densify as
    degree^depth and would exhaust memory on million-node RAGs; a chunked
    (n_chunk x n_nodes) indicator sweep bounds peak memory by the chunk."""
    from scipy import sparse

    valid = node_labels != ignore_label
    uv = np.asarray(uv_ids, dtype="int64").reshape(-1, 2)
    # drop edges touching invalid nodes: paths THROUGH unlabeled nodes do
    # not create lifted edges between labeled ones
    keep = valid[uv[:, 0]] & valid[uv[:, 1]]
    uv = uv[keep]
    data = np.ones(len(uv), dtype=bool)
    adj = sparse.csr_matrix(
        (data, (uv[:, 0], uv[:, 1])), shape=(n_nodes, n_nodes))
    adj = (adj + adj.T).astype(bool)
    direct = sparse.csr_matrix(
        (np.ones(len(uv), bool),
         (np.minimum(uv[:, 0], uv[:, 1]), np.maximum(uv[:, 0], uv[:, 1]))),
        shape=(n_nodes, n_nodes)).tocsr()

    chunks_out = []
    for lo in range(0, n_nodes, node_chunk):
        hi = min(lo + node_chunk, n_nodes)
        # depth-1 reachability of this source chunk is just a row slice
        reach = adj[lo:hi].astype(bool).copy()
        acc = reach.copy()
        for _ in range(graph_depth - 1):
            reach = (reach @ adj).astype(bool)
            acc = (acc + reach).astype(bool)
        coo = acc.tocoo()
        rows = coo.row.astype("int64") + lo
        cols = coo.col.astype("int64")
        # upper triangle only (each pair reported once globally)
        m = rows < cols
        rows, cols = rows[m], cols[m]
        # minus direct RAG edges
        if len(rows):
            is_direct = np.asarray(
                direct[rows, cols]).ravel().astype(bool)
            rows, cols = rows[~is_direct], cols[~is_direct]
        if len(rows):
            chunks_out.append(
                np.stack([rows, cols], axis=1).astype("uint64"))
    pairs = (np.concatenate(chunks_out) if chunks_out
             else np.zeros((0, 2), "uint64"))
    la = node_labels[pairs[:, 0]]
    lb = node_labels[pairs[:, 1]]
    ok = (la != ignore_label) & (lb != ignore_label)
    if mode == "same":
        ok &= la == lb
    elif mode == "different":
        ok &= la != lb
    elif mode != "all":
        raise ValueError(f"unknown lifted mode {mode}")
    return pairs[ok]


class SparseLiftedNeighborhood(BlockTask):
    """Global task: compute the lifted pair list from the graph + node
    labels (reference: sparse_lifted_neighborhood.py)."""

    task_name = "sparse_lifted_neighborhood"
    global_task = True
    allow_retry = False

    def __init__(self, graph_path: str, graph_key: str, node_label_path: str,
                 node_label_key: str, output_path: str, output_key: str,
                 nh_graph_depth: int = 4, mode: str = "all",
                 node_ignore_label: int = 0, identifier: str = "", **kw):
        self.graph_path = graph_path
        self.graph_key = graph_key
        self.node_label_path = node_label_path
        self.node_label_key = node_label_key
        self.output_path = output_path
        self.output_key = output_key
        self.nh_graph_depth = nh_graph_depth
        self.mode = mode
        self.node_ignore_label = node_ignore_label
        self.identifier = identifier
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "graph_path": self.graph_path, "graph_key": self.graph_key,
            "node_label_path": self.node_label_path,
            "node_label_key": self.node_label_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "nh_graph_depth": self.nh_graph_depth, "mode": self.mode,
            "node_ignore_label": self.node_ignore_label,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from ..core.graph import Graph, load_graph

        cfg = job_config["config"]
        nodes, edges, _ = load_graph(cfg["graph_path"], cfg["graph_key"])
        with file_reader(cfg["node_label_path"], "r") as f:
            node_labels = f[cfg["node_label_key"]][:]
        # graph node ids may be non-dense (s0 original labels): map to dense
        graph = Graph(nodes, edges)
        uv_dense = np.stack([graph.node_index(edges[:, 0]),
                             graph.node_index(edges[:, 1])], axis=1) \
            if len(edges) else np.zeros((0, 2), "int64")
        dense_labels = node_labels[nodes.astype("int64")] if len(nodes) else \
            np.zeros(0, node_labels.dtype)
        pairs = lifted_neighborhood(
            uv_dense, len(nodes), dense_labels, cfg["nh_graph_depth"],
            cfg.get("mode", "all"), cfg.get("node_ignore_label", 0))
        # back to original node ids
        pairs = np.stack([nodes[pairs[:, 0].astype("int64")],
                          nodes[pairs[:, 1].astype("int64")]], axis=1) \
            if len(pairs) else np.zeros((0, 2), "uint64")
        save_edge_list(cfg["output_path"], cfg["output_key"], pairs)
        log_fn(f"extracted {len(pairs)} lifted edges at depth "
               f"{cfg['nh_graph_depth']}")


class CostsFromNodeLabels(BlockTask):
    """Lifted costs from label agreement, sharded over edge chunks
    (reference: costs_from_node_labels.py:119-139): attractive
    ``intra_label_cost`` when both nodes carry the same semantic label,
    repulsive ``inter_label_cost`` otherwise."""

    task_name = "costs_from_node_labels"

    def __init__(self, nh_path: str, nh_key: str, node_label_path: str,
                 node_label_key: str, output_path: str, output_key: str,
                 inter_label_cost: float = -12.0,
                 intra_label_cost: float = 12.0, identifier: str = "", **kw):
        self.nh_path = nh_path
        self.nh_key = nh_key
        self.node_label_path = node_label_path
        self.node_label_key = node_label_key
        self.output_path = output_path
        self.output_key = output_key
        self.inter_label_cost = inter_label_cost
        self.intra_label_cost = intra_label_cost
        self.identifier = identifier
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"chunk_size": int(1e6)})
        return conf

    def run_impl(self):
        with file_reader(self.nh_path, "r") as f:
            ds = f[self.nh_key]
            n_lifted = int(ds.attrs.get("n_edges", ds.shape[0]))
        chunk_size = int(self.task_config.get("chunk_size", 1e6))
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key, shape=(max(n_lifted, 1),),
                              chunks=(min(chunk_size, max(n_lifted, 1)),),
                              dtype="float64")
        self.run_jobs(self.id_chunks(n_lifted, chunk_size), {
            "nh_path": self.nh_path, "nh_key": self.nh_key,
            "node_label_path": self.node_label_path,
            "node_label_key": self.node_label_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "inter_label_cost": self.inter_label_cost,
            "intra_label_cost": self.intra_label_cost,
            "chunk_size": chunk_size, "n_lifted": n_lifted,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        n_lifted = cfg["n_lifted"]
        chunk = cfg["chunk_size"]
        f_nh = file_reader(cfg["nh_path"], "r")
        f_lab = file_reader(cfg["node_label_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_nh = f_nh[cfg["nh_key"]]
        node_labels = f_lab[cfg["node_label_key"]][:]
        ds_out = f_out[cfg["output_key"]]
        for block_id in job_config["block_list"]:
            lo = block_id * chunk
            hi = min(lo + chunk, n_lifted)
            if lo >= hi:
                log_fn(f"processed block {block_id}")
                continue
            uv = ds_nh[lo:hi]
            la = node_labels[uv[:, 0].astype("int64")]
            lb = node_labels[uv[:, 1].astype("int64")]
            costs = np.where(la == lb, cfg["intra_label_cost"],
                             cfg["inter_label_cost"]).astype("float64")
            ds_out[lo:hi] = costs
            log_fn(f"processed block {block_id}")


class ClearLiftedEdgesFromLabels(BlockTask):
    """Drop lifted edges whose endpoints carry different *clearing* labels
    — e.g. never keep a lifted edge across a known tissue boundary
    (reference: clear_lifted_edges_from_labels.py:83-120).  Rewrites the
    lifted nh dataset in place; the paired costs dataset (if it exists
    already) must be recomputed afterwards."""

    task_name = "clear_lifted_edges"
    global_task = True
    allow_retry = False

    def __init__(self, node_labels_path: str, node_labels_key: str,
                 lifted_edge_path: str, lifted_edge_key: str,
                 identifier: str = "", **kw):
        self.node_labels_path = node_labels_path
        self.node_labels_key = node_labels_key
        self.lifted_edge_path = lifted_edge_path
        self.lifted_edge_key = lifted_edge_key
        self.identifier = identifier
        super().__init__(**kw)

    def run_impl(self):
        self.run_jobs(None, {
            "node_labels_path": self.node_labels_path,
            "node_labels_key": self.node_labels_key,
            "lifted_edge_path": self.lifted_edge_path,
            "lifted_edge_key": self.lifted_edge_key,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        import shutil

        cfg = job_config["config"]
        with file_reader(cfg["node_labels_path"], "r") as f:
            node_labels = f[cfg["node_labels_key"]][:]
        lifted = load_edge_list(cfg["lifted_edge_path"],
                                cfg["lifted_edge_key"])
        mapped_a = node_labels[lifted[:, 0].astype("int64")]
        mapped_b = node_labels[lifted[:, 1].astype("int64")]
        keep = mapped_a == mapped_b
        new = lifted[keep]
        log_fn(f"cleared lifted edges {len(lifted)} -> {len(new)}")
        if len(new) < len(lifted):
            # shape changes: replace the dataset wholesale
            target = os.path.join(cfg["lifted_edge_path"],
                                  cfg["lifted_edge_key"])
            shutil.rmtree(target)
            save_edge_list(cfg["lifted_edge_path"], cfg["lifted_edge_key"],
                           new)


class LiftedFeaturesFromNodeLabelsWorkflow(Task):
    """NodeLabels(max-overlap) -> SparseLiftedNeighborhood ->
    CostsFromNodeLabels [-> ClearLiftedEdges] (reference:
    lifted_feature_workflow.py:80-160)."""

    def __init__(self, ws_path: str, ws_key: str, labels_path: str,
                 labels_key: str, graph_path: str, graph_key: str,
                 output_path: str, nh_out_key: str, feat_out_key: str,
                 prefix: str, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 nh_graph_depth: int = 4, mode: str = "all",
                 clear_labels_path: str = "", clear_labels_key: str = "",
                 dependency: Optional[Task] = None):
        self.ws_path = ws_path
        self.ws_key = ws_key
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.graph_path = graph_path
        self.graph_key = graph_key
        self.output_path = output_path
        self.nh_out_key = nh_out_key
        self.feat_out_key = feat_out_key
        self.prefix = prefix
        self.nh_graph_depth = nh_graph_depth
        self.mode = mode
        self.clear_labels_path = clear_labels_path
        self.clear_labels_key = clear_labels_key
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        labels_key = f"node_overlaps/{self.prefix}"
        dep: Task = NodeLabelWorkflow(
            ws_path=self.ws_path, ws_key=self.ws_key,
            input_path=self.labels_path, input_key=self.labels_key,
            output_path=self.output_path, output_key=labels_key,
            prefix=self.prefix, max_overlap=True,
            dependency=self.dependency, **common)
        dep = SparseLiftedNeighborhood(
            graph_path=self.graph_path, graph_key=self.graph_key,
            node_label_path=self.output_path, node_label_key=labels_key,
            output_path=self.output_path, output_key=self.nh_out_key,
            nh_graph_depth=self.nh_graph_depth, mode=self.mode,
            identifier=self.prefix, dependency=dep, **common)
        if self.clear_labels_path:
            clear_key = f"node_overlaps/clear_{self.prefix}"
            dep = NodeLabelWorkflow(
                ws_path=self.ws_path, ws_key=self.ws_key,
                input_path=self.clear_labels_path,
                input_key=self.clear_labels_key,
                output_path=self.output_path, output_key=clear_key,
                prefix=f"clear_{self.prefix}", max_overlap=True,
                dependency=dep, **common)
            dep = ClearLiftedEdgesFromLabels(
                node_labels_path=self.output_path, node_labels_key=clear_key,
                lifted_edge_path=self.output_path,
                lifted_edge_key=self.nh_out_key, identifier=self.prefix,
                dependency=dep, **common)
        return CostsFromNodeLabels(
            nh_path=self.output_path, nh_key=self.nh_out_key,
            node_label_path=self.output_path, node_label_key=labels_key,
            output_path=self.output_path, output_key=self.feat_out_key,
            identifier=self.prefix, dependency=dep, **common)

    def output(self):
        return FileTarget(os.path.join(
            self.tmp_folder,
            f"costs_from_node_labels_{self.prefix}.status"))
