"""Filter-bank features + RF pixel classification — the ilastik replacement.

The reference shells out to the external ilastik binary for headless pixel
classification (ilastik/prediction.py:104-228 ``run_ilastik.sh --headless``
per block with halo, merge_predictions.py) and separately precomputes filter
features (features/image_filter.py:24).  The TPU build makes both
first-party:

* ``ImageFilterTask`` — blockwise multi-filter/multi-scale feature stacks
  (gaussian, gaussian-gradient-magnitude, laplacian-of-gaussian — the core
  of ilastik's feature matrix), computed as jitted separable convolutions
  with halo reads.
* ``TrainPixelClassifier`` / ``PredictPixelClassifier`` — sklearn RF over
  the device-computed features: trained from a sparse scribble volume
  (0 = unlabeled, 1..K = class labels), predicted blockwise with halo and
  written as per-class probability channels.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.blocking import Blocking
from ..core.runtime import BlockTask
from ..core.storage import file_reader
from ..core.workflow import FileTarget, Task

#: ilastik-style default feature matrix: (filter, sigma); names follow the
#: reference's vigra-style registry (ops/filters.FILTERS)
DEFAULT_FEATURES: Tuple[Tuple[str, float], ...] = (
    ("gaussianSmoothing", 0.7), ("gaussianSmoothing", 1.6),
    ("gaussianSmoothing", 3.5),
    ("gaussianGradientMagnitude", 1.6), ("gaussianGradientMagnitude", 3.5),
    ("laplacianOfGaussian", 1.6), ("laplacianOfGaussian", 3.5),
)


def compute_feature_stack(data: np.ndarray,
                          features: Sequence[Sequence] = DEFAULT_FEATURES
                          ) -> np.ndarray:
    """(n_features, *shape) float32 filter responses (device compute)."""
    import jax.numpy as jnp

    from ..ops.filters import apply_filter

    x = jnp.asarray(data.astype("float32"))
    out = [np.asarray(apply_filter(x, name, sigma))
           for name, sigma in features]
    return np.stack(out).astype("float32")


class ImageFilterTask(BlockTask):
    """Blockwise precomputed filter features (reference:
    features/image_filter.py:24): output channel c holds filter c of the
    configured feature matrix."""

    task_name = "image_filter"

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str,
                 features: Sequence[Sequence] = DEFAULT_FEATURES, **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.features = [list(f) for f in features]
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            shape = list(f[self.input_key].shape)
        block_shape = [min(b, s) for b, s in
                       zip(self.global_block_shape()[-len(shape):], shape)]
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key,
                              shape=[len(self.features)] + shape,
                              chunks=[1] + block_shape, dtype="float32")
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "features": self.features,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        features = cfg["features"]
        halo = [_filter_halo(features)] * blocking.ndim
        f_in = file_reader(cfg["input_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in, ds_out = f_in[cfg["input_key"]], f_out[cfg["output_key"]]
        for block_id in job_config["block_list"]:
            bh = blocking.get_block_with_halo(block_id, halo)
            x = np.asarray(ds_in[bh.outer.bb]).astype("float32")
            stack = compute_feature_stack(x, features)
            ds_out[(slice(None),) + bh.inner.bb] = \
                stack[(slice(None),) + bh.inner_local.bb]
            log_fn(f"processed block {block_id}")


def _filter_halo(features) -> int:
    return int(max(4 * float(s) + 1 for _, s in features))


class TrainPixelClassifier(BlockTask):
    """Fit an RF on filter features at scribble-labeled voxels (the ilastik
    training step, first-party)."""

    task_name = "train_pixel_classifier"
    global_task = True
    allow_retry = False

    def __init__(self, input_path: str, input_key: str, labels_path: str,
                 labels_key: str, output_path: str,
                 features: Sequence[Sequence] = DEFAULT_FEATURES, **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.output_path = output_path
        self.features = [list(f) for f in features]
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"n_trees": 100})
        return conf

    def run_impl(self):
        self.run_jobs(None, {
            "input_path": self.input_path, "input_key": self.input_key,
            "labels_path": self.labels_path, "labels_key": self.labels_key,
            "output_path": self.output_path, "features": self.features,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from sklearn.ensemble import RandomForestClassifier

        cfg = job_config["config"]
        with file_reader(cfg["labels_path"], "r") as f:
            ds = f[cfg["labels_key"]]
            labels = ds[tuple(slice(0, s) for s in ds.shape)]
        # restrict feature computation to the scribble bounding box + filter
        # halo: scribbles cover a tiny fraction of cluster-scale volumes,
        # and the full-volume feature stack would not fit one host
        nz = np.nonzero(labels > 0)
        if len(nz[0]) == 0:
            raise ValueError("no scribble labels > 0 found")
        halo = _filter_halo(cfg["features"])
        lo = [max(int(c.min()) - halo, 0) for c in nz]
        hi = [min(int(c.max()) + 1 + halo, s)
              for c, s in zip(nz, labels.shape)]
        bb = tuple(slice(a, b) for a, b in zip(lo, hi))
        with file_reader(cfg["input_path"], "r") as f:
            data = np.asarray(f[cfg["input_key"]][bb])
        labels = labels[bb]
        stack = compute_feature_stack(data, cfg["features"])
        sel = labels > 0
        X = stack[:, sel].T
        y = labels[sel]
        log_fn(f"training RF on {len(y)} scribble voxels, "
               f"{X.shape[1]} features, {len(np.unique(y))} classes")
        rf = RandomForestClassifier(
            n_estimators=int(cfg.get("n_trees", 100)),
            n_jobs=int(cfg.get("threads_per_job", 1)))
        rf.fit(X, y)
        with open(cfg["output_path"], "wb") as f:
            pickle.dump({"rf": rf, "features": cfg["features"]}, f)


class PredictPixelClassifier(BlockTask):
    """Blockwise RF prediction over filter features (the ilastik headless
    prediction step, ilastik/prediction.py:104-228): per-class probability
    channels, halo reads, uint8 or float32 output."""

    task_name = "predict_pixel_classifier"

    def __init__(self, input_path: str, input_key: str, classifier_path: str,
                 output_path: str, output_key: str, n_classes: int, **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.classifier_path = classifier_path
        self.output_path = output_path
        self.output_key = output_key
        self.n_classes = n_classes
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"dtype": "float32"})
        return conf

    def run_impl(self):
        with file_reader(self.input_path, "r") as f:
            shape = list(f[self.input_key].shape)
        block_shape = [min(b, s) for b, s in
                       zip(self.global_block_shape()[-len(shape):], shape)]
        dtype = self.task_config.get("dtype", "float32")
        with file_reader(self.output_path) as f:
            f.require_dataset(self.output_key,
                              shape=[self.n_classes] + shape,
                              chunks=[1] + block_shape, dtype=dtype)
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "classifier_path": self.classifier_path,
            "output_path": self.output_path, "output_key": self.output_key,
            "n_classes": self.n_classes,
            "shape": shape, "block_shape": block_shape,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        with open(cfg["classifier_path"], "rb") as f:
            bundle = pickle.load(f)
        rf, features = bundle["rf"], bundle["features"]
        rf.n_jobs = int(cfg.get("threads_per_job", 1))
        halo = [_filter_halo(features)] * blocking.ndim
        f_in = file_reader(cfg["input_path"], "r")
        f_out = file_reader(cfg["output_path"])
        ds_in, ds_out = f_in[cfg["input_key"]], f_out[cfg["output_key"]]
        dtype = np.dtype(cfg.get("dtype", "float32"))
        classes = list(rf.classes_)
        bad = [int(c) for c in classes
               if not 1 <= int(c) <= cfg["n_classes"]]
        if bad:
            raise ValueError(
                f"classifier was trained on classes {classes} but the "
                f"workflow allocates n_classes={cfg['n_classes']} channels "
                f"(classes {bad} would be dropped) — scribble labels must "
                "be 1..n_classes")

        for block_id in job_config["block_list"]:
            bh = blocking.get_block_with_halo(block_id, halo)
            x = np.asarray(ds_in[bh.outer.bb]).astype("float32")
            stack = compute_feature_stack(x, features)
            inner = stack[(slice(None),) + bh.inner_local.bb]
            flat = inner.reshape(inner.shape[0], -1).T
            proba = rf.predict_proba(flat)
            out = np.zeros((cfg["n_classes"],) + inner.shape[1:], "float32")
            for col, cls_label in enumerate(classes):
                ch = int(cls_label) - 1
                if 0 <= ch < cfg["n_classes"]:
                    out[ch] = proba[:, col].reshape(inner.shape[1:])
            if dtype == np.uint8:
                out = np.clip(np.round(out * 255), 0, 255)
            ds_out[(slice(None),) + bh.inner.bb] = out.astype(dtype)
            log_fn(f"processed block {block_id}")


class PixelClassificationWorkflow(Task):
    """Train on scribbles -> predict blockwise (the IlastikPredictionWorkflow
    capability, first-party)."""

    def __init__(self, input_path: str, input_key: str, labels_path: str,
                 labels_key: str, output_path: str, output_key: str,
                 n_classes: int, tmp_folder: str, config_dir: str,
                 max_jobs: int = 1, target: str = "local",
                 features: Sequence[Sequence] = DEFAULT_FEATURES,
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.output_path = output_path
        self.output_key = output_key
        self.n_classes = n_classes
        self.features = features
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        classifier_path = os.path.join(self.tmp_folder,
                                       "pixel_classifier.pkl")
        train = TrainPixelClassifier(
            input_path=self.input_path, input_key=self.input_key,
            labels_path=self.labels_path, labels_key=self.labels_key,
            output_path=classifier_path, features=self.features,
            dependency=self.dependency, **common)
        return PredictPixelClassifier(
            input_path=self.input_path, input_key=self.input_key,
            classifier_path=classifier_path, output_path=self.output_path,
            output_key=self.output_key, n_classes=self.n_classes,
            dependency=train, **common)

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "predict_pixel_classifier.status"))


class WriteCarving(Task):
    """Export graph + edge weights as an ilastik carving project (.ilp h5)
    (reference: ilastik/carving.py:10-123 ``WriteCarving``).

    The graph dataset follows the serialization the reference targets
    (vigra adjacencyListGraph): a flat uint32 array
    ``[n_nodes, n_edges, max_node_id, max_edge_id] + uv_ids.ravel() +
    neighborhoods``.  ``n_nodes`` counts the DISTINCT node ids present
    (the vigra convention — smaller than ``max_node_id + 1`` when ids are
    non-consecutive), while ``neighborhoods`` is POSITIONAL over all
    ``max_node_id + 1`` ids in order — isolated ids contribute a degree-0
    record; readers must size the section from ``max_node_id``, not
    ``n_nodes``.  Each record is the node's degree followed by
    (neighbor_id, edge_id) pairs sorted by neighbor.
    Edge weights are the mean-probability feature column rescaled to the
    carving convention's 0-255 range (reference: carving.py:57-69)."""

    def __init__(self, graph_path: str, graph_key: str, features_path: str,
                 features_key: str, output_path: str, raw_path: str,
                 raw_key: str, uid: str, tmp_folder: str,
                 copy_inputs: bool = False,
                 dependency: Optional[Task] = None):
        self.graph_path = graph_path
        self.graph_key = graph_key
        self.features_path = features_path
        self.features_key = features_key
        self.output_path = output_path
        self.raw_path = raw_path
        self.raw_key = raw_key
        self.uid = uid
        self.copy_inputs = copy_inputs
        self.tmp_folder = tmp_folder
        self.dependency = dependency
        super().__init__()

    def requires(self):
        return self.dependency

    @staticmethod
    def serialize_graph(uv_ids: np.ndarray,
                        max_node_id: int) -> np.ndarray:
        """Flat uint32 serialization (header + uv ids + neighborhoods).

        The header matches the vigra adjacencyListGraph convention: n_nodes
        is the number of DISTINCT node ids present (not max_node_id + 1 —
        they differ for non-consecutive ids), and an empty graph's
        max_edge_id is -1, which wraps to 0xFFFFFFFF in uint32."""
        n_edges = len(uv_ids)
        n_nodes = len(np.unique(uv_ids)) if n_edges else 0
        header = np.array([n_nodes, n_edges,
                           max_node_id, n_edges - 1],
                          "int64").astype("uint32")
        # per-node adjacency: degree, then (neighbor, edge_id) by neighbor
        adj = [[] for _ in range(max_node_id + 1)]
        for eid, (u, v) in enumerate(uv_ids):
            adj[u].append((v, eid))
            adj[v].append((u, eid))
        hoods = []
        for node_adj in adj:
            hoods.append(len(node_adj))
            for nb, eid in sorted(node_adj):
                hoods.extend((nb, eid))
        return np.concatenate([header, uv_ids.astype("uint32").ravel(),
                               np.asarray(hoods, "uint32")])

    def run(self):
        import time

        import h5py

        from ..core.graph import load_graph

        _, edges, attrs = load_graph(self.graph_path, self.graph_key)
        if len(edges) and int(edges.max()) >= 2 ** 32:
            raise ValueError(
                f"carving serialization is uint32; node ids reach "
                f"{int(edges.max())} — relabel to consecutive ids first")
        uv_ids = edges.astype("uint32")
        max_node_id = int(uv_ids.max()) if len(uv_ids) else 0
        serialization = self.serialize_graph(uv_ids, max_node_id)

        with file_reader(self.features_path, "r") as f:
            feats = np.asarray(f[self.features_key][:, 0])
        feats = feats * 255.0  # carving weights use the 0-255 range

        # mode 'w' truncates: a retry after a partial previous run must not
        # trip over half-written groups (the export is single-writer)
        with h5py.File(self.output_path, "w") as f:
            g = f.create_group("preprocessing/graph")
            g.create_dataset("graph", data=serialization,
                             compression="gzip")
            g.create_dataset("nodeSeeds", shape=(max_node_id + 1,),
                             dtype="uint8")
            g.create_dataset("resultSegmentation", shape=(max_node_id + 1,),
                             dtype="uint8")
            g.attrs["numNodes"] = max_node_id + 1
            g.create_dataset("edgeWeights", data=feats)

            gi = f.create_group("Input Data")
            gi.create_dataset("Role Names",
                              data=[b"Raw Data", b"Overlay"])
            gi.create_dataset("StorageVersion", data="0.2")
            gi.create_group("local_data")
            lane = f.create_group("Input Data/infos/lane0000/Raw Data")
            lane.create_dataset("allowLabels", data=True)
            lane.create_dataset("axisorder", data=b"zyx")
            lane.create_dataset("fromstack", data=False)
            lane.create_dataset("datasetId", data=self.uid.encode("utf-8"))
            lane.create_dataset("display_mode", data=b"default")
            lane.create_dataset(
                "filePath",
                data=os.path.join(self.raw_path,
                                  self.raw_key).encode("utf-8"))
            lane.create_dataset(
                "location", data=b"ProjectInternal" if self.copy_inputs
                else b"FileSystem")
            lane.create_dataset("nickname", data=b"Input")

            f.create_dataset("workflowName", data=b"Carving")
            f.create_dataset("ilastikVersion", data=b"1.3.0b2")
            f.create_dataset("currentApplet", data=2)
            f.create_dataset("time", data=time.ctime().encode("utf-8"))
            f.create_dataset("preprocessing/StorageVersion", data="0.1")
            f.create_dataset("preprocessing/filter", data=3)
            f.create_dataset("preprocessing/sigma", data=1.0)
            f.create_dataset("preprocessing/invert_watershed_source",
                             data=False)
            f.create_dataset("preprocessing/watershed_source",
                             data=b"filtered")
            f.create_dataset("carving/StorageVersion", data="0.1")
            f.create_group("carving/objects")
        self.output().touch()

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "write_carving.status"))
