"""Gather-free seeded watershed via directional bottleneck scans.

TPU-native replacement for the priority-flood watershed (reference:
vigra ``watershedsNew`` via utils/volume_utils.py:123-139 and
watershed/watershed.py:211-249).  The flood's label assignment is the
bottleneck (minimax) shortest-path forest: a voxel joins the seed whose
path minimizes the maximum height along the way (watershed cuts, Cousty
et al.) — and bottleneck costs form a (min, max) semiring, so the
recurrence

    out[i] = min(state[i], max(out[i-1], h[i]))

composes ASSOCIATIVELY along grid lines.  Each sweep is one
``lax.associative_scan`` over an axis (forward or reverse), which XLA
lowers to log-depth vectorized passes: label fronts cross an entire grid
line per sweep with ZERO random gathers.  Six directional sweeps
(Gauss-Seidel: each feeds the next) make one round; rounds repeat until
the monotone-decreasing state reaches its fixpoint.  Basin diameters in
EM fragments are tens of voxels, so a handful of rounds converge — vs
the ~80 ms/19M-element random gathers that made pointer-jumping
formulations (`ops/watershed.seeded_watershed_basins`) gather-bound.

The path cost is Meyer's TOPOGRAPHIC DISTANCE (Meyer '94 — the standard
shortest-path-forest characterization of the watershed transform): each
step into voxel ``v`` from neighbor ``u`` costs
``max(0, h[v] - h[u]) * 256 + 1`` — total ascent, with a per-step unit
so plateaus resolve by geodesic BFS distance exactly like a flood
front.  On smooth height fields the minimum-ascent path follows the
gradient, so basins match the gradient-descent watershed (a pure
bottleneck/minimax cost does NOT: every voxel above the lowest saddle
is bottleneck-tied between basins and the labeling collapses to
arbitrary tie-breaks — measured VI ~1.0 vs the flood on CREMI-like
data, vs ~0.1 for topographic distance).  Min-plus path composition is
exactly associative, so each directional sweep is one
``associative_scan``; labels ride as a separate lexicographic
tie-break leaf.

A transit flag threaded through the scan keeps labels from crossing
masked voxels (composition over (value, barrier) pairs stays
associative); the same algebra with zero step costs yields connected
components by min-index propagation (`sweep_cc`).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

_INF = np.uint32(0xFFFFFFFF)
#: packed path cost, lexicographic: total ascent (anchored at the seed's
#: own height, 14 bits) | steps since the last ascent (9 bits) | total
#: steps (9 bits), all saturating.  The three levels earn their place:
#: ascent alone ties every above-saddle voxel between basins;
#: steps-since-last-ascent divides contested level bands like the flood
#: front's BFS; total steps breaks the remaining tie at a fresh riser
#: (where both fronts just reset) toward the nearer basin.
CLIMB_BITS, RSTEP_BITS, TSTEP_BITS = 14, 9, 9
_CLIMB_MAX = np.uint32((1 << CLIMB_BITS) - 1)
_RSTEP_MAX = np.uint32((1 << RSTEP_BITS) - 1)
_TSTEP_MAX = np.uint32((1 << TSTEP_BITS) - 1)


def _lex_min(P1, lab1, P2, lab2):
    take1 = (P1 < P2) | ((P1 == P2) & (lab1 <= lab2))
    return jnp.where(take1, P1, P2), jnp.where(take1, lab1, lab2)


def _pack(climb, rsteps, tsteps):
    # tsteps saturates one short of its field max: a fully saturated pack
    # would otherwise equal the _INF unreachable sentinel exactly
    return ((jnp.minimum(climb, _CLIMB_MAX) << (RSTEP_BITS + TSTEP_BITS))
            | (jnp.minimum(rsteps, _RSTEP_MAX) << TSTEP_BITS)
            | jnp.minimum(tsteps, _TSTEP_MAX - 1))


def _transfer(P, C, t, L):
    """Move a carried front across a segment with total ascent C,
    trailing no-ascent run t, and length L: ascent accumulates; the
    reset-step counter restarts at t when the segment ascends, else
    grows by L; total steps always grow by L.  INF stays absorbing."""
    climb = (P >> (RSTEP_BITS + TSTEP_BITS)) + C
    rsteps = jnp.where(C > 0, t, ((P >> TSTEP_BITS) & _RSTEP_MAX) + L)
    tsteps = (P & _TSTEP_MAX) + L
    return jnp.where(P == _INF, _INF, _pack(climb, rsteps, tsteps))


def _ws_combine(left, right):
    """Compose two min-plus path segments.

    An element is ``(A, lab, C, t, L, m)``: (A, lab) = cheapest packed
    (ascent, reset-steps, total-steps, label) ending at the segment's
    last voxel from a source WITHIN the segment; (C, t, L) = segment
    metadata (total ascent, trailing no-ascent run, length); m = segment
    free of masked voxels.  Represents
    ``f(carry) = min(A, m ? transfer(carry) : INF)``.  Associative up to
    exact packed-cost ties — the class the flood itself resolves by
    queue order.
    """
    A1, l1, C1, t1, L1, m1 = left
    A2, l2, C2, t2, L2, m2 = right
    moved = jnp.where(m2, _transfer(A1, C2, t2, L2), _INF)
    A, lab = _lex_min(moved, l1, A2, l2)
    C = jnp.minimum(C1 + C2, _CLIMB_MAX)
    t = jnp.where(C2 > 0, t2, jnp.minimum(t1 + L2, _RSTEP_MAX))
    L = jnp.minimum(L1 + L2, _TSTEP_MAX)
    return A, lab, C, t, L, m1 & m2


def _cc_combine(left, right):
    A1, m1 = left
    A2, m2 = right
    return jnp.minimum(A2, jnp.where(m2, A1, _INF)), m1 & m2


def _step_elems(hq: jnp.ndarray, axis: int, reverse: bool):
    """Per-voxel segment metadata for a directional sweep: the ascent
    entering voxel i from its predecessor, and the trailing no-ascent
    run (0 after an ascent, else 1).  Line-leading voxels have no
    predecessor; their metadata only matters for carries, which start
    at INF there."""
    h = hq.astype(jnp.int32)
    off = [0] * h.ndim
    off[axis] = 1 if reverse else -1
    from .components import _shifted

    prev = _shifted(h, off, 255)
    climb = jnp.maximum(h - prev, 0).astype(jnp.uint32)
    t = jnp.where(climb > 0, jnp.uint32(0), jnp.uint32(1))
    return climb, t


def _ws_round(state_A, state_lab, hq, m, pin_A, pin_lab, seeded,
              ndim: int):
    """One Gauss-Seidel round: 2*ndim directional scans, seeds re-pinned
    after each (a foreign front must not relabel a seed)."""
    ones = jnp.ones(hq.shape, jnp.uint32)
    for axis in range(ndim):
        for reverse in (False, True):
            C, t = _step_elems(hq, axis, reverse)
            state_A, state_lab, _, _, _, _ = jax.lax.associative_scan(
                _ws_combine, (state_A, state_lab, C, t, ones, m),
                axis=axis, reverse=reverse)
            state_A = jnp.where(seeded, pin_A, state_A)
            state_lab = jnp.where(seeded, pin_lab, state_lab)
    return state_A, state_lab


@partial(jax.jit, static_argnames=("max_rounds", "min_size", "k_cap"))
def sweep_watershed_impl(
    hq: jnp.ndarray,
    seeds: jnp.ndarray,
    mask: Optional[jnp.ndarray],
    max_rounds: int = 24,
    min_size: int = 0,
    k_cap: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Jittable core: uint8 heights, dense int32 seed ids (< 2^24).

    Returns ``(labels int32, converged bool)``.  Unreachable voxels
    (outside mask, or cut off by it) keep label 0.  ``min_size`` strips
    fragments below the threshold and re-floods their voxels from the
    surviving fragments (the reference's watershed-and-size-filter,
    utils/volume_utils.py:123-139); it requires a static ``k_cap`` bound
    on the seed-id space for the on-device size histogram.
    """
    shape = hq.shape
    ndim = len(shape)
    m = jnp.ones(shape, bool) if mask is None else mask.astype(bool)
    seeded = (seeds > 0) & m
    # a seed's cost starts at its OWN height: within a basin the ascent
    # total to v is then ~ h[v] regardless of seed depth, so a deep/high
    # seed cannot "ride a contour" into a neighbor's above-saddle
    # shoulder for free (seed-cost-0 variants lose whole shoulder bands
    # to the deepest neighbor: measured VI ~1.0 vs the flood)
    pin_A = jnp.where(seeded, _pack(hq.astype(jnp.uint32),
                                    jnp.uint32(0), jnp.uint32(0)), _INF)
    pin_lab = jnp.where(seeded, seeds.astype(jnp.uint32), _INF)

    def run_rounds(A, lab, pA, plab, pinned):
        def body(carry):
            cA, clab, _, it = carry
            nA, nlab = _ws_round(cA, clab, hq, m, pA, plab, pinned, ndim)
            return (nA, nlab, jnp.any((nA != cA) | (nlab != clab)),
                    it + 1)

        A, lab, changed, _ = jax.lax.while_loop(
            lambda c: c[2] & (c[3] < max_rounds), body,
            (A, lab, jnp.bool_(True), jnp.int32(0)))
        return A, lab, ~changed

    P, lab, converged = run_rounds(pin_A, pin_lab, pin_A, pin_lab, seeded)

    if min_size:
        if not k_cap:
            raise ValueError("min_size needs a static k_cap")
        labels = jnp.where(m & (P < _INF), lab,
                           0).astype(jnp.uint32)
        clipped = jnp.minimum(labels, jnp.uint32(k_cap)).astype(jnp.int32)
        sizes = jax.ops.segment_sum(
            jnp.where(m, 1, 0).reshape(-1), clipped.reshape(-1),
            num_segments=k_cap + 1)
        small = (sizes < min_size) & (sizes > 0)
        small = small.at[0].set(False)
        strip = small[clipped]
        # stripped voxels revert to unlabeled; surviving fragment BODIES
        # act as the new seed set (every labeled voxel is already a
        # fixpoint source), so the re-flood is just more rounds
        P = jnp.where(strip, _INF, P)
        lab = jnp.where(strip, _INF, lab)
        pinned2 = seeded & ~strip
        P, lab, conv2 = run_rounds(P, lab, pin_A, pin_lab, pinned2)
        converged &= conv2

    labels = jnp.where(m & (P < _INF), lab, 0)
    return labels.astype(jnp.int32), converged


@partial(jax.jit, static_argnames=("max_rounds",))
def sweep_cc_impl(mask: jnp.ndarray, max_rounds: int = 32):
    """Connected components (face connectivity) by min-linear-index
    propagation with the same directional-scan machinery.  Returns
    ``(labels int32 — root_index + 1, 0 outside mask —, converged)``;
    identical labeling contract to ``ops.components.connected_components``.
    """
    shape = mask.shape
    ndim = len(shape)
    n = int(np.prod(shape))
    m = mask.astype(bool)
    idx = jnp.arange(n, dtype=jnp.uint32).reshape(shape)
    state0 = jnp.where(m, idx, _INF)

    def one_round(s):
        for axis in range(ndim):
            for reverse in (False, True):
                s, _ = jax.lax.associative_scan(
                    _cc_combine, (s, m), axis=axis, reverse=reverse)
        return s

    def body(carry):
        s, _, it = carry
        s2 = one_round(s)
        return s2, jnp.any(s2 != s), it + 1

    state, changed, _ = jax.lax.while_loop(
        lambda c: c[1] & (c[2] < max_rounds), body,
        (state0, jnp.bool_(True), jnp.int32(0)))
    labels = jnp.where(m, state + 1, 0).astype(jnp.int32)
    return labels, ~changed


def compact_ids(labels: jnp.ndarray, cap: int):
    """Dense-rank positive ids (device np.unique analog): presence flags +
    cumsum.  Ids must be < ``cap``.  Returns ``(dense int32 — 1..k, same
    zeros —, k)``."""
    flat = labels.reshape(-1).astype(jnp.int32)
    pres = jnp.zeros((cap + 2,), jnp.int32).at[
        jnp.minimum(flat, cap + 1)].set(1, mode="drop")
    pres = pres.at[0].set(0)
    rank = jnp.cumsum(pres)
    dense = jnp.where(flat > 0, rank[jnp.minimum(flat, cap + 1)], 0)
    return dense.reshape(labels.shape).astype(jnp.int32), rank[cap + 1]


def sweep_watershed(
    height,
    seeds,
    mask=None,
    connectivity: int = 1,
    min_size: int = 0,
    max_rounds: int = 48,
) -> jnp.ndarray:
    """Host-facing wrapper matching ``ops.watershed.seeded_watershed``:
    float heights (normalized to uint8 levels), arbitrary positive seed
    ids.  Quantization to 256 levels matches the hybrid path's uint8
    flood (the reference's own CNN outputs are uint8,
    inference/inference.py:235)."""
    if connectivity != 1:
        raise ValueError("sweep watershed propagates along faces "
                         "(connectivity=1)")
    height = jnp.asarray(height)
    seeds = jnp.asarray(seeds)
    if height.dtype == jnp.uint8:
        hq = height
    else:
        h = height.astype(jnp.float32)
        lo = h.min()
        hq = jnp.clip(jnp.round((h - lo) / jnp.maximum(h.max() - lo, 1e-6)
                                * 255.0), 0, 255).astype(jnp.uint8)
    n = int(np.prod(height.shape))
    # host-side dense compaction: this wrapper is the convenience path
    # (callers may pass arbitrary, e.g. globally-offset, seed ids that
    # exceed the device rank-scatter's id range); the fused hot path
    # calls sweep_watershed_impl directly with device-compacted ids
    seeds_np = np.asarray(seeds)
    uniq = np.unique(seeds_np)
    uniq = uniq[uniq > 0]
    k = len(uniq)
    # ctt-lint: disable=dtype-int32 (this IS the sanctioned compaction: searchsorted ranks are < k <= block voxel count, never raw global ids)
    dense = np.searchsorted(uniq, seeds_np).astype("int32") + 1
    dense[seeds_np <= 0] = 0
    dense = jnp.asarray(dense)
    if min_size:
        # pow2-rounded histogram size bounds recompiles across calls
        k_cap = 1 << max(int(np.ceil(np.log2(max(k, 2)))), 6)
    else:
        k_cap = 0
    dense_lab, converged = sweep_watershed_impl(
        hq, dense, mask, max_rounds=max_rounds, min_size=min_size,
        k_cap=k_cap)
    if not bool(converged):  # pathological serpentine plateaus
        dense_lab, _ = sweep_watershed_impl(
            hq, dense, mask, max_rounds=4 * max_rounds, min_size=min_size,
            k_cap=k_cap)
    # map dense ranks back to the caller's seed ids
    if uniq.size and uniq[-1] >= np.iinfo(np.int32).max:
        raise ValueError("seed ids exceed int32")
    lab = np.asarray(dense_lab)
    out = np.zeros(lab.shape, np.int64)
    fg = lab > 0
    out[fg] = uniq.astype(np.int64)[lab[fg] - 1]
    return jnp.asarray(out.astype(np.int32))


def rle_encode(flat: jnp.ndarray, cap: int):
    """Run-length encode a flat label array on device: returns
    ``(starts uint32[cap], values int32[cap], n_runs, ok)``.  Invalid
    slots scatter out of bounds (mode='drop') — fixed-cap buffers, the
    host downloads only the ``n_runs`` prefix (chunked dynamic slices).
    Segmentation volumes are piecewise constant, so runs ~ voxels /
    mean-run-length — an order of magnitude less link traffic than the
    dense grid."""
    n = int(flat.shape[0])
    brk = jnp.concatenate([jnp.ones((1,), bool), flat[1:] != flat[:-1]])
    tgt = jnp.cumsum(brk.astype(jnp.int32)) - 1
    n_runs = jnp.where(n > 0, tgt[-1] + 1, 0)
    ok = n_runs <= cap
    tgt = jnp.where(brk & (tgt < cap), tgt, cap + 2)
    starts = jnp.zeros((cap + 1,), jnp.uint32).at[tgt].set(
        jnp.arange(n, dtype=jnp.uint32), mode="drop")[:cap]
    values = jnp.zeros((cap + 1,), jnp.int32).at[tgt].set(
        flat.astype(jnp.int32), mode="drop")[:cap]
    return starts, values, n_runs, ok


def rle_decode(starts: np.ndarray, values: np.ndarray, total: int) -> np.ndarray:
    """Host-side inverse of :func:`rle_encode` (numpy repeat)."""
    starts = np.asarray(starts, dtype=np.int64)
    lengths = np.diff(np.append(starts, total))
    return np.repeat(np.asarray(values), lengths)


#: forced run break interval for the packed encoder: lengths must fit 16
#: bits, so runs are split at this stride (adds ~n/stride extra runs)
RLE_STRIDE = np.uint32(1 << 15)


def rle_encode_packed(flat: jnp.ndarray, cap: int):
    """Run-length encode label ids < 2^16 into ONE uint32 stream,
    ``length << 16 | value`` per run (runs force-split every RLE_STRIDE
    elements so lengths fit).  The host downloads the fixed-cap buffer
    with a single transfer — no device-side prefix program that would
    queue behind in-flight block programs — and decodes with one
    ``np.repeat``.  Returns ``(packed uint32[cap], n_runs, ok)``; ok
    is False on cap overflow OR ids >= 2^16 (caller falls back to a
    dense download)."""
    n = int(flat.shape[0])
    idx = jnp.arange(n, dtype=jnp.uint32)
    brk = jnp.concatenate([jnp.ones((1,), bool),
                           flat[1:] != flat[:-1]])
    brk |= (idx % RLE_STRIDE) == 0
    tgt = jnp.cumsum(brk.astype(jnp.int32)) - 1
    n_runs = jnp.where(n > 0, tgt[-1] + 1, 0)
    ok = (n_runs <= cap) & (flat.max() < (1 << 16))
    # run length AT each break position = next break index - own index,
    # from a reversed exclusive cummin of break indices — lengths then
    # ride the same packed word as the value, so the encoder pays ONE
    # O(n) scatter pass instead of two (starts + values)
    m = jnp.where(brk, idx, jnp.uint32(n))
    # lax.cummin is the lowered scan primitive; associative_scan's
    # recursive slicing formulation stalled the remote XLA compile at
    # this length
    nb = jax.lax.cummin(m, reverse=True)
    nb_next = jnp.concatenate([nb[1:], jnp.full((1,), n, jnp.uint32)])
    lengths = jnp.where(brk, nb_next - idx, 0)
    packed_full = (lengths << 16) | (flat.astype(jnp.uint32)
                                     & jnp.uint32(0xFFFF))
    tgt_c = jnp.where(brk & (tgt < cap), tgt, cap + 2)
    packed = jnp.zeros((cap + 1,), jnp.uint32).at[tgt_c].set(
        packed_full, mode="drop")[:cap]
    return packed, n_runs, ok


def rle_decode_packed(packed: np.ndarray, n_runs: int,
                      total: int) -> np.ndarray:
    """Host-side inverse of :func:`rle_encode_packed`."""
    arr = np.asarray(packed[:n_runs])
    lengths = (arr >> 16).astype(np.int64)
    values = (arr & 0xFFFF).astype(np.uint16)
    out = np.repeat(values, lengths)
    if out.size != total:
        raise ValueError(f"RLE decode size {out.size} != {total}")
    return out
