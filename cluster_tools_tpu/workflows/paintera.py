"""Paintera-format conversion + legacy BigCat export.

Re-specification of the reference's ``paintera/`` package
(conversion_workflow.py:104-357 — steps: copy labels to the paintera data
group, multiscale label downsampling, per-block unique-label lists,
label-to-block lookup, fragment-segment assignment, java-axis-order (XYZ)
metadata; unique_block_labels.py:123-145, label_block_mapping.py:103-117)
and the ``bigcat/`` package (bigcat_workflow.py:13-115 — fragment-segment
pairs + offset attrs in HDF5).

Layout produced under ``<path>/<label_group>``:

    data/s0..sN                multiscale label volumes
    unique-labels/s<i>         per-block unique-label lists (varlen)
    label-to-block-mapping/s<i>  per-label block-id lists (varlen)
    fragment-segment-assignment  (2, N) fragment->segment pairs
"""

from __future__ import annotations

import os
import shutil
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.blocking import Blocking
from ..core.runtime import BlockTask
from ..core.storage import VarlenDataset, file_reader
from ..core.workflow import FileTarget, Task
from .copy_volume import CopyVolumeTask
from .downscaling import DownscaleTask, _factor3


class UniqueBlockLabels(BlockTask):
    """Per-block unique label lists for one scale level (reference:
    unique_block_labels.py:123-145, incl. the label-multiset variant —
    with ``from_multiset`` the input is a multiset level written by
    workflows/label_multisets.py and uniques come from the multiset ids
    without touching the dense volume)."""

    task_name = "unique_block_labels"

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 output_key: str, identifier: str = "",
                 from_multiset: bool = False, **kw):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.output_key = output_key
        self.identifier = identifier
        self.from_multiset = from_multiset
        super().__init__(**kw)

    def run_impl(self):
        if self.from_multiset:
            src = VarlenDataset(os.path.join(self.input_path,
                                             self.input_key),
                                dtype="uint64", mode="r")
            shape = list(src.attrs["multisetShape"])
            block_shape = list(src.attrs["blockShape"])
        else:
            with file_reader(self.input_path, "r") as f:
                shape = list(f[self.input_key].shape)
            block_shape = [min(b, s) for b, s in
                           zip(self.global_block_shape(), shape)]
        block_list = self.blocks_in_volume(shape, block_shape)
        self.run_jobs(block_list, {
            "input_path": self.input_path, "input_key": self.input_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "shape": shape, "block_shape": block_shape,
            "from_multiset": self.from_multiset,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        out = VarlenDataset(os.path.join(cfg["output_path"],
                                         cfg["output_key"]), dtype="uint64")
        if cfg.get("from_multiset"):
            from .label_multisets import load_multiset_block

            src = VarlenDataset(os.path.join(cfg["input_path"],
                                             cfg["input_key"]),
                                dtype="uint64", mode="r")
            for block_id in job_config["block_list"]:
                entry = load_multiset_block(cfg["input_path"],
                                            cfg["input_key"], block_id,
                                            ds=src)
                ids = (np.zeros(0, "uint64") if entry is None
                       else np.unique(entry[1]))
                out.write_chunk((block_id,), ids.astype("uint64"))
                log_fn(f"processed block {block_id}")
            return
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        f_in = file_reader(cfg["input_path"], "r")
        ds = f_in[cfg["input_key"]]
        for block_id in job_config["block_list"]:
            uniques = np.unique(ds[blocking.get_block(block_id).bb])
            out.write_chunk((block_id,), uniques.astype("uint64"))
            log_fn(f"processed block {block_id}")


class LabelBlockMapping(BlockTask):
    """Invert the per-block unique lists into a per-label block-id lookup,
    sharded over label-id ranges (reference: label_block_mapping.py:103-117
    ``ndist.serializeBlockMapping``)."""

    task_name = "label_block_mapping"

    def __init__(self, uniques_path: str, uniques_key: str, output_path: str,
                 output_key: str, n_labels: Optional[int] = None,
                 labels_path: str = "", labels_key: str = "",
                 identifier: str = "", **kw):
        self.uniques_path = uniques_path
        self.uniques_key = uniques_key
        self.output_path = output_path
        self.output_key = output_key
        self.n_labels = n_labels
        self.labels_path = labels_path
        self.labels_key = labels_key
        self.identifier = identifier
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"id_chunk_size": int(1e6)})
        return conf

    def run_impl(self):
        self.resolve_n_labels()
        chunk = int(self.task_config.get("id_chunk_size", 1e6))
        self.run_jobs(self.id_chunks(self.n_labels, chunk), {
            "uniques_path": self.uniques_path,
            "uniques_key": self.uniques_key,
            "output_path": self.output_path, "output_key": self.output_key,
            "n_labels": self.n_labels, "id_chunk_size": chunk,
        }, n_jobs=self.max_jobs)

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        cfg = job_config["config"]
        chunk, n_labels = cfg["id_chunk_size"], cfg["n_labels"]
        uniques = VarlenDataset(os.path.join(cfg["uniques_path"],
                                             cfg["uniques_key"]),
                                dtype="uint64")
        # one pass over the block lists, binned into owned label ranges
        ranges = {bid: (bid * chunk, min((bid + 1) * chunk, n_labels))
                  for bid in job_config["block_list"]}
        mapping: Dict[int, Dict[int, List[int]]] = {
            bid: {} for bid in ranges}
        for chunk_id in uniques.chunk_ids():
            ids = uniques.read_chunk(chunk_id)
            if ids is None:
                continue
            block = int(chunk_id[0])
            for bid, (lo, hi) in ranges.items():
                m = (ids >= lo) & (ids < hi)
                for lab in ids[m]:
                    mapping[bid].setdefault(int(lab), []).append(block)
        out = VarlenDataset(os.path.join(cfg["output_path"],
                                         cfg["output_key"]), dtype="uint64")
        for bid, (lo, hi) in ranges.items():
            for lab, blocks in mapping[bid].items():
                out.write_chunk((lab,), np.asarray(blocks, "uint64"))
            log_fn(f"processed block {bid}")


def label_to_blocks(path: str, key: str, label_id: int):
    """Blocks containing ``label_id`` (readBlockMapping equivalent)."""
    ds = VarlenDataset(os.path.join(path, key), dtype="uint64")
    return ds.read_chunk((label_id,))


def assignment_to_pairs(table: np.ndarray) -> np.ndarray:
    """Assignment table -> (2, N) paintera fragment->segment pairs.

    Accepts either a dense 1-d table (index = fragment id) or sparse
    (N, 2) ``(fragment, segment)`` rows.  Background (fragment 0) is
    dropped and segment ids are offset past the largest fragment id so
    the two id spaces never collide — the paintera convention shared by
    the conversion export, the BigCat export, and the edits/ assignment
    patcher (one definition, ISSUE 19 satellite)."""
    if table.ndim == 2:
        frag, seg = table[:, 0], table[:, 1]
    else:
        frag = np.arange(len(table), dtype="uint64")
        seg = table
    keep = frag != 0
    offset = int(frag.max()) + 1 if len(frag) else 1
    return np.stack([frag[keep], seg[keep] + offset], axis=0).astype("uint64")


def pairs_to_table(pairs: np.ndarray,
                   n_labels: Optional[int] = None) -> np.ndarray:
    """Invert :func:`assignment_to_pairs` back to a dense table.

    The offset is recovered as ``max(fragment id) + 1`` — exactly what
    the forward direction used, since dropping fragment 0 never changes
    the maximum.  ``n_labels`` sizes the table (defaults to the smallest
    table covering every fragment id); an empty pair set round-trips to
    an all-background table."""
    pairs = np.asarray(pairs)
    if pairs.size == 0:
        return np.zeros(0 if n_labels is None else int(n_labels), "uint64")
    frag, seg = pairs[0].astype("uint64"), pairs[1].astype("uint64")
    offset = int(frag.max()) + 1
    n = int(n_labels) if n_labels is not None else offset
    table = np.zeros(n, "uint64")
    table[frag.astype("int64")] = seg - np.uint64(offset)
    return table


def load_fragment_segment_assignment(path: str, label_group: str):
    """The (2, N) pairs dataset of a paintera group, or None if absent."""
    key = os.path.join(label_group, "fragment-segment-assignment")
    with file_reader(path, "r") as f:
        if key not in f:
            return None
        return f[key][:]


def write_fragment_segment_assignment(path: str, label_group: str,
                                      pairs: np.ndarray) -> None:
    """(Re)write the (2, N) pairs dataset — the edits/ patcher's path for
    keeping an attached paintera project consistent after an edit.

    ``require_dataset`` refuses shape changes by design, so when N moved
    (merges change the pair count) a dir-backed dataset is deleted and
    recreated; same-shape rewrites go in place."""
    key = os.path.join(label_group, "fragment-segment-assignment")
    pairs = np.asarray(pairs, dtype="uint64")
    with file_reader(path) as f:
        if key in f and tuple(f[key].shape) == tuple(pairs.shape):
            f[key][:] = pairs
            return
    ds_dir = os.path.join(path, key)
    if os.path.isdir(ds_dir):
        shutil.rmtree(ds_dir)
    with file_reader(path) as f:
        f.require_dataset(key, data=pairs, shape=pairs.shape,
                          chunks=(2, max(min(int(1e6), pairs.shape[1]), 1)))


class FragmentSegmentAssignment(Task):
    """(2, N) fragment->segment table inside the paintera group (reference:
    conversion_workflow.py fragment_segment_assignment step)."""

    def __init__(self, path: str, label_group: str, assignment_path: str,
                 assignment_key: Optional[str], tmp_folder: str,
                 dependency: Optional[Task] = None):
        self.path = path
        self.label_group = label_group
        self.assignment_path = assignment_path
        self.assignment_key = assignment_key
        self.tmp_folder = tmp_folder
        self.dependency = dependency
        super().__init__()

    def requires(self):
        return self.dependency

    def run(self):
        from .write import load_assignments

        table = load_assignments(self.assignment_path, self.assignment_key)
        pairs = assignment_to_pairs(table)
        write_fragment_segment_assignment(self.path, self.label_group, pairs)
        self.output().touch()

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "fragment_segment_assignment.status"))


class WritePainteraMetadata(Task):
    """Paintera group attributes (reference: WritePainteraMetadata,
    conversion_workflow.py:21-101): painteraData type, maxId,
    labelBlockLookup, multiScale + per-scale downsamplingFactors in XYZ
    axis order."""

    def __init__(self, path: str, label_group: str, scale_factors,
                 resolution, offset, max_id, tmp_folder: str,
                 dependency: Optional[Task] = None):
        # max_id may be an (path, key) tuple resolved at run time
        self.path = path
        self.label_group = label_group
        self.scale_factors = [_factor3(s) for s in scale_factors]
        self.resolution = list(resolution)
        self.offset = list(offset)
        self.max_id = max_id
        self.tmp_folder = tmp_folder
        self.dependency = dependency
        super().__init__()

    def requires(self):
        return self.dependency

    def run(self):
        max_id = self.max_id
        if isinstance(max_id, (tuple, list)):
            from ..core.storage import read_max_id

            max_id = read_max_id(*max_id)
        with file_reader(self.path) as f:
            group = f.require_group(self.label_group)
            group.attrs["painteraData"] = {"type": "label"}
            group.attrs["maxId"] = int(max_id)
            pattern = os.path.join(self.label_group,
                                   "label-to-block-mapping", "s%d")
            group.attrs["labelBlockLookup"] = {
                "type": "n5-filesystem",
                "root": os.path.abspath(self.path),
                "scaleDatasetPattern": pattern,
            }
            data_group = f.require_group(
                os.path.join(self.label_group, "data"))
            data_group.attrs["maxId"] = int(max_id)
            data_group.attrs["multiScale"] = True
            # java n5 axis order is XYZ; ours is ZYX -> reverse
            data_group.attrs["resolution"] = self.resolution[::-1]
            data_group.attrs["offset"] = self.offset[::-1]
            effective = [1, 1, 1]
            for scale, factor in enumerate(self.scale_factors):
                effective = [e * s for e, s in zip(effective, factor)]
                f[os.path.join(self.label_group, "data",
                               f"s{scale + 1}")].attrs[
                    "downsamplingFactors"] = effective[::-1]
        self.output().touch()

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "paintera_metadata.status"))


class PainteraConversionWorkflow(Task):
    """Full conversion: copy labels -> multiscale (label-safe) downsample ->
    per-scale unique-block lists -> label-to-block lookup -> assignment ->
    metadata (reference: ConversionWorkflow, conversion_workflow.py:104-357).
    """

    def __init__(self, input_path: str, input_key: str, path: str,
                 label_group: str, scale_factors: Sequence,
                 tmp_folder: str, config_dir: str, max_jobs: int = 1,
                 target: str = "local", assignment_path: str = "",
                 assignment_key: Optional[str] = None,
                 resolution=(1.0, 1.0, 1.0), offset=(0.0, 0.0, 0.0),
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.path = path
        self.label_group = label_group
        self.scale_factors = list(scale_factors)
        self.assignment_path = assignment_path
        self.assignment_key = assignment_key
        self.resolution = resolution
        self.offset = offset
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        data_prefix = os.path.join(self.label_group, "data")

        # step 1: copy labels to data/s0
        dep: Task = CopyVolumeTask(
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.path,
            output_key=os.path.join(data_prefix, "s0"),
            identifier="paintera_labels", dependency=self.dependency,
            **common)
        # step 2: label-safe multiscale
        for scale, factor in enumerate(self.scale_factors):
            dep = DownscaleTask(
                input_path=self.path,
                input_key=os.path.join(data_prefix, f"s{scale}"),
                output_path=self.path,
                output_key=os.path.join(data_prefix, f"s{scale + 1}"),
                scale_factor=factor, sampler="nearest",
                identifier=f"paintera_s{scale + 1}",
                dependency=dep, **common)
        # step 3+4: uniques + label-to-block lookup per scale
        n_scales = len(self.scale_factors) + 1
        for scale in range(n_scales):
            uniques_key = os.path.join(self.label_group, "unique-labels",
                                       f"s{scale}")
            dep = UniqueBlockLabels(
                input_path=self.path,
                input_key=os.path.join(data_prefix, f"s{scale}"),
                output_path=self.path, output_key=uniques_key,
                identifier=f"s{scale}", dependency=dep, **common)
            dep = LabelBlockMapping(
                uniques_path=self.path, uniques_key=uniques_key,
                output_path=self.path,
                output_key=os.path.join(self.label_group,
                                        "label-to-block-mapping",
                                        f"s{scale}"),
                labels_path=self.input_path, labels_key=self.input_key,
                identifier=f"s{scale}",
                dependency=dep, **common)
        # step 5: fragment-segment assignment (optional)
        if self.assignment_path:
            dep = FragmentSegmentAssignment(
                path=self.path, label_group=self.label_group,
                assignment_path=self.assignment_path,
                assignment_key=self.assignment_key,
                tmp_folder=self.tmp_folder, dependency=dep)
        # step 6: metadata
        return WritePainteraMetadata(
            path=self.path, label_group=self.label_group,
            scale_factors=self.scale_factors, resolution=self.resolution,
            offset=self.offset, max_id=(self.input_path, self.input_key),
            tmp_folder=self.tmp_folder, dependency=dep)

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "paintera_metadata.status"))


class BigcatWorkflow(Task):
    """Legacy BigCat export: fragment volume + fragment-segment pairs +
    offset attrs in HDF5 (reference: bigcat/bigcat_workflow.py:13-115)."""

    def __init__(self, input_path: str, input_key: str, output_path: str,
                 assignment_path: str, assignment_key: Optional[str],
                 tmp_folder: str, config_dir: str, max_jobs: int = 1,
                 target: str = "local", resolution=(1.0, 1.0, 1.0),
                 dependency: Optional[Task] = None):
        self.input_path = input_path
        self.input_key = input_key
        self.output_path = output_path
        self.assignment_path = assignment_path
        self.assignment_key = assignment_key
        self.resolution = resolution
        self.tmp_folder = tmp_folder
        self.config_dir = config_dir
        self.max_jobs = max_jobs
        self.target = target
        self.dependency = dependency
        super().__init__()

    def requires(self):
        common = dict(tmp_folder=self.tmp_folder, config_dir=self.config_dir,
                      max_jobs=self.max_jobs, target=self.target)
        copy = CopyVolumeTask(
            input_path=self.input_path, input_key=self.input_key,
            output_path=self.output_path,
            output_key="volumes/labels/fragments", identifier="bigcat",
            dependency=self.dependency, **common)
        return _BigcatFinalize(
            output_path=self.output_path,
            assignment_path=self.assignment_path,
            assignment_key=self.assignment_key,
            resolution=self.resolution, tmp_folder=self.tmp_folder,
            dependency=copy)

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "bigcat_finalize.status"))


class _BigcatFinalize(Task):
    def __init__(self, output_path: str, assignment_path: str,
                 assignment_key, resolution, tmp_folder: str,
                 dependency: Optional[Task] = None):
        self.output_path = output_path
        self.assignment_path = assignment_path
        self.assignment_key = assignment_key
        self.resolution = resolution
        self.tmp_folder = tmp_folder
        self.dependency = dependency
        super().__init__()

    def requires(self):
        return self.dependency

    def run(self):
        from .write import load_assignments

        table = load_assignments(self.assignment_path, self.assignment_key)
        pairs = assignment_to_pairs(table)
        with file_reader(self.output_path) as f:
            f.require_dataset("fragment_segment_lut",
                              data=pairs.astype("uint64"), shape=pairs.shape,
                              chunks=(2, max(min(int(1e6),
                                                 pairs.shape[1]), 1)))
            ds = f["volumes/labels/fragments"]
            ds.attrs["resolution"] = list(self.resolution)
            ds.attrs["offset"] = [0.0, 0.0, 0.0]
            f.attrs["next_id"] = int(pairs.max()) + 1
        self.output().touch()

    def output(self):
        return FileTarget(os.path.join(self.tmp_folder,
                                       "bigcat_finalize.status"))
