"""Skeletonization example (reference: example/skeletons.py).

    python example/skeletons.py /tmp/ctt_skeletons
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(workdir):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.workflows.skeletons import (SkeletonWorkflow,
                                                       load_skeleton)

    os.makedirs(workdir, exist_ok=True)
    data = os.path.join(workdir, "data.n5")
    config_dir = os.path.join(workdir, "configs")
    ConfigDir(config_dir).write_global_config({"block_shape": [16, 64, 64]})

    # two tube-like objects
    seg = np.zeros((16, 64, 64), "uint64")
    seg[6:10, 6:10, 4:60] = 1
    seg[6:10, 40:44, 4:60] = 2
    with file_reader(data) as f:
        ds = f.create_dataset("seg", data=seg, chunks=[16, 64, 64])
        ds.attrs["maxId"] = 2

    wf = SkeletonWorkflow(
        input_path=data, input_key="seg", output_path=data,
        output_key="skeletons", tmp_folder=os.path.join(workdir, "tmp"),
        config_dir=config_dir, max_jobs=2, target="local")
    assert ctt.build([wf])

    for label in (1, 2):
        coords = load_skeleton(data, "skeletons", label)
        print(f"object {label}: {len(coords)} skeleton voxels, "
              f"x-extent {coords[:, 2].min()}..{coords[:, 2].max()}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/ctt_skeletons")
