"""Append-only proofreading edit log (ISSUE 19 tentpole, part 1).

One JSONL file per segmentation: each record is a single merge/split
edit with a correlation id that follows the edit through the resolver,
the incremental solver, telemetry spans, and any flight-recorder dump.

Atomicity model: every append is ONE ``os.write`` of one complete
``\\n``-terminated JSON line onto an ``O_APPEND`` descriptor, followed
by an fsync — so concurrent appenders never interleave bytes within a
record, and a crash can only ever truncate the final line.  The reader
tolerates exactly that (a torn, unterminated tail is skipped unless
``strict``), which is the classic write-ahead-log contract and the
reason replay is safe after any interruption.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence, Tuple

#: legal edit operations: "merge" biases every pairwise edge between the
#: listed fragments attractive, "split" biases them repulsive
OPS = ("merge", "split")


@dataclass(frozen=True)
class EditRecord:
    """One replayable proofreading decision."""
    edit_id: str          #: correlation id (spans, flight records, status)
    seq: int              #: position in the log, 0-based, monotonic
    op: str               #: "merge" | "split"
    fragments: Tuple[int, ...]  #: >= 2 watershed fragment ids, nonzero
    time: float           #: wall-clock seconds at append
    note: str = ""        #: free-form provenance (user, tool, session)

    def to_json(self) -> str:
        return json.dumps({
            "edit_id": self.edit_id, "seq": self.seq, "op": self.op,
            "fragments": list(self.fragments), "time": self.time,
            "note": self.note,
        }, sort_keys=True)

    @staticmethod
    def from_json(line: str) -> "EditRecord":
        d = json.loads(line)
        return EditRecord(edit_id=str(d["edit_id"]), seq=int(d["seq"]),
                          op=str(d["op"]),
                          fragments=tuple(int(f) for f in d["fragments"]),
                          time=float(d["time"]), note=str(d.get("note", "")))


def _validate(op: str, fragments: Sequence[int]) -> Tuple[int, ...]:
    if op not in OPS:
        raise ValueError(f"unknown edit op {op!r}; expected one of {OPS}")
    frs = tuple(sorted({int(f) for f in fragments}))
    if len(frs) < 2:
        raise ValueError(
            f"an edit needs >= 2 distinct fragments, got {fragments!r}")
    if frs[0] <= 0:
        raise ValueError(
            f"fragment ids must be positive (0 is background): {frs}")
    return frs


class EditLog:
    """Append-only JSONL log of :class:`EditRecord`; see module docstring
    for the atomicity contract."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._next_seq: Optional[int] = None

    # -- append ------------------------------------------------------------

    def append(self, op: str, fragments: Sequence[int], *, note: str = "",
               edit_id: Optional[str] = None) -> EditRecord:
        """Validate, stamp, and durably append one edit; returns the
        record (with its assigned seq and correlation id)."""
        frs = _validate(op, fragments)
        with self._lock:
            if self._next_seq is None:
                # WAL recovery before the first append: a torn tail from
                # an interrupted writer is truncated away, so the new
                # record never concatenates onto a half-written line
                self._recover()
                self._next_seq = len(self.records())
            rec = EditRecord(
                edit_id=edit_id or uuid.uuid4().hex[:12],
                seq=self._next_seq, op=op, fragments=frs,
                time=time.time(), note=note)
            payload = (rec.to_json() + "\n").encode("utf-8")
            fd = os.open(self.path,
                         os.O_WRONLY | os.O_APPEND | os.O_CREAT, 0o644)
            try:
                os.write(fd, payload)
                os.fsync(fd)
            finally:
                os.close(fd)
            self._next_seq += 1
        return rec

    def _recover(self) -> None:
        """Truncate a torn (unterminated) trailing line, if any — the
        interrupted append it came from never happened."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            raw = f.read()
        if raw and not raw.endswith(b"\n"):
            keep = raw.rfind(b"\n") + 1     # 0 when no newline at all
            with open(self.path, "r+b") as f:
                f.truncate(keep)

    # -- read / replay -----------------------------------------------------

    def records(self, *, strict: bool = False) -> List[EditRecord]:
        """Parse the log.  A torn (unterminated) trailing line is skipped
        — the interrupted append never happened; ``strict=True`` raises on
        it instead.  Seq numbers must be 0..n-1 in order (an out-of-order
        log means two writers disagreed about history; always an error)."""
        if not os.path.exists(self.path):
            return []
        with open(self.path, "rb") as f:
            raw = f.read()
        lines = raw.split(b"\n")
        torn = lines[-1]  # b"" when the last record was fully terminated
        if torn and strict:
            raise ValueError(
                f"torn trailing record in {self.path!r}: {torn[:80]!r}")
        out = []
        for line in lines[:-1]:
            if not line.strip():
                continue
            out.append(EditRecord.from_json(line.decode("utf-8")))
        for i, rec in enumerate(out):
            if rec.seq != i:
                raise ValueError(
                    f"non-monotonic edit log {self.path!r}: record {i} "
                    f"has seq {rec.seq}")
        return out

    def replay(self, apply_fn: Callable[[EditRecord], None]) -> int:
        """Re-apply every durable record in order; returns the count.
        With a deterministic ``apply_fn`` (the edits session is), replay
        reconstructs the exact post-edit state from the log alone."""
        recs = self.records()
        for rec in recs:
            apply_fn(rec)
        return len(recs)

    def __len__(self) -> int:
        return len(self.records())
