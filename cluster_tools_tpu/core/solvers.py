"""Multicut solver registry (reference: utils/segmentation_utils.py:22-150).

All solvers take ``(n_nodes, uv_ids, costs)`` over dense node ids and return
a dense uint64 node labeling.  Positive cost = attractive.  The combinatorial
kernels are first-party C++ (cluster_tools_tpu.native) with numpy fallbacks.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Optional

import numpy as np

from .. import native


def multicut_gaec(n_nodes: int, uv_ids: np.ndarray, costs: np.ndarray,
                  time_limit: Optional[float] = None,
                  n_threads: int = 1) -> np.ndarray:
    return native.multicut_gaec(n_nodes, uv_ids, costs)


def multicut_kernighan_lin(n_nodes: int, uv_ids: np.ndarray,
                           costs: np.ndarray,
                           time_limit: Optional[float] = None,
                           n_threads: int = 1) -> np.ndarray:
    return native.multicut_kernighan_lin(n_nodes, uv_ids, costs,
                                         time_limit=time_limit or 0.0)


def multicut_decomposition(n_nodes: int, uv_ids: np.ndarray,
                           costs: np.ndarray,
                           time_limit: Optional[float] = None,
                           n_threads: int = 4) -> np.ndarray:
    """Decompose into components connected by attractive edges, solve each
    component independently in threads (reference:
    segmentation_utils.py:44-126 and decomposition_multicut/)."""
    uv = np.asarray(uv_ids, dtype="int64").reshape(-1, 2)
    costs = np.asarray(costs, dtype="float64")
    attractive = costs > 0
    comp = native.ufd_merge_pairs(n_nodes, uv[attractive]).astype("int64")
    _, comp = np.unique(comp, return_inverse=True)
    labels = np.zeros(n_nodes, dtype="uint64")

    edge_comp = comp[uv[:, 0]]
    inner = comp[uv[:, 0]] == comp[uv[:, 1]]
    order = np.argsort(edge_comp[inner], kind="stable")
    inner_uv = uv[inner][order]
    inner_costs = costs[inner][order]
    inner_comp = edge_comp[inner][order]
    starts = np.flatnonzero(np.r_[True, inner_comp[1:] != inner_comp[:-1]])
    bounds = np.r_[starts, len(inner_comp)]

    def solve_comp(ci):
        lo, hi = bounds[ci], bounds[ci + 1]
        sub_uv = inner_uv[lo:hi]
        sub_costs = inner_costs[lo:hi]
        nodes = np.unique(sub_uv)
        remap = {n: i for i, n in enumerate(nodes)}
        local_uv = np.array([[remap[u], remap[v]] for u, v in sub_uv],
                            dtype="int64")
        sub = native.multicut_kernighan_lin(len(nodes), local_uv, sub_costs,
                                            time_limit=time_limit or 0.0)
        return nodes, sub

    results = []
    with ThreadPoolExecutor(max(n_threads, 1)) as tp:
        results = list(tp.map(solve_comp, range(len(starts))))

    next_label = 0
    for nodes, sub in results:
        labels[nodes] = sub + next_label
        next_label += int(sub.max()) + 1 if len(sub) else 0
    # singleton / attractive-only-component nodes not covered by inner edges
    uncovered = np.ones(n_nodes, bool)
    for nodes, _ in results:
        uncovered[nodes] = False
    n_unc = int(uncovered.sum())
    labels[uncovered] = np.arange(next_label, next_label + n_unc, dtype="uint64")
    return labels


AGGLOMERATORS: Dict[str, Callable] = {
    "greedy-additive": multicut_gaec,
    "kernighan-lin": multicut_kernighan_lin,
    "decomposition": multicut_decomposition,
    "decomposition-gaec": multicut_decomposition,
    "fusion-moves": multicut_kernighan_lin,  # stub parity (reference :130)
}


def key_to_agglomerator(key: str) -> Callable:
    """Solver lookup (reference: segmentation_utils.py:142)."""
    if key not in AGGLOMERATORS:
        raise KeyError(f"unknown agglomerator {key}; "
                       f"choose from {sorted(AGGLOMERATORS)}")
    return AGGLOMERATORS[key]
