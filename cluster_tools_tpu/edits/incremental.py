"""Incremental re-solve of an edited multicut problem (ISSUE 19, part 3).

An :class:`EditSession` holds the s0 problem in memory as a cost overlay:
a merge biases every edge between the edited fragments strongly
attractive, a split strongly repulsive (edges that do not exist yet are
appended past the base edge list, so persisted edge ids never move).
``solve`` then re-runs the blockwise ladder, but re-solves ONLY the
subproblems whose content signature no longer matches a cached solution
— everything else warm-starts from the in-memory cache or the
sub_results persisted by ``SolveSubproblems`` (which stamps the same
signature, workflows/multicut.py).

The safety contract is validate-then-reuse, never trust-the-cache: a
signature mismatch on a block OUTSIDE the edit's resolved footprint
means the persisted solution no longer describes the live problem
(stale cache); the session falls back to a full subproblem solve for
that block — wrong output is impossible, only wasted work — counts it,
and dumps a flight record carrying the edit's correlation id so the
incident is diagnosable post-hoc.
"""

from __future__ import annotations

import itertools
from typing import Dict, List, Optional, Sequence, Set, Tuple

import numpy as np

from ..core import graph as g
from ..core import telemetry
from ..core.blocking import Blocking
from ..core.runtime import stage
from ..core.solvers import key_to_agglomerator
from ..workflows import multicut as mc
from . import resolver

#: magnitude of the cost bias an edit places on an edge — far beyond any
#: accumulated boundary evidence, so a single edit decision dominates the
#: subproblem objective without resorting to +/-inf (which the solvers'
#: float arithmetic must never see)
EDIT_COST = 1.0e6


class EditSession:
    """In-memory incremental re-segmentation over one problem container.

    Single-writer by design: the resident server serializes scheduling
    quanta, so session state is only ever mutated from one worker thread.
    Only flat (``n_scales == 1``-style) containers are supported — the
    session re-runs reduce+global itself after the per-block stage, which
    is exactly what the committed workflow does at that depth.
    """

    def __init__(self, problem_path: str, *,
                 fallback_block_shape: Optional[Sequence[int]] = None,
                 agglomerator: str = "kernighan-lin",
                 time_limit: Optional[float] = None,
                 flight_dir: Optional[str] = None,
                 paintera_path: Optional[str] = None,
                 paintera_lookup_key: Optional[str] = None,
                 paintera_block_shape: Optional[Sequence[int]] = None):
        self.problem_path = problem_path
        self.flight_dir = flight_dir
        self.paintera_path = paintera_path
        self.paintera_lookup_key = paintera_lookup_key
        self.paintera_block_shape = paintera_block_shape
        self._agglomerator_key = agglomerator
        self._time_limit = time_limit

        uv_dense, n_nodes, s0_nodes = mc._load_scale_graph(problem_path, 0)
        self.base_uv = uv_dense.astype("int64")
        self.n_nodes = int(n_nodes)
        self.s0_nodes = s0_nodes
        self.costs = mc._load_costs(problem_path, 0).astype("float64").copy()
        shape, base_bs = mc._problem_geometry(
            problem_path, fallback_block_shape or [64, 64, 64])
        self.shape, self.block_shape = shape, base_bs
        self.blocking = Blocking(shape, base_bs)

        # cost/edge overlay: extra edges append past the base list so base
        # edge ids (and with them every persisted sub_result) stay valid
        self.extra_uv = np.zeros((0, 2), "int64")
        self.extra_costs = np.zeros(0, "float64")
        self._extra_index: Dict[Tuple[int, int], int] = {}
        self._graph: Optional[g.Graph] = None
        self._graph_n_extra = -1

        self._block_nodes: Dict[int, np.ndarray] = {}
        #: block id -> (content signature, cut edge ids over combined list)
        self._cache: Dict[int, Tuple[str, np.ndarray]] = {}
        self.counters = {"applied": 0, "subproblems_solved": 0,
                         "warm_reused": 0, "fallback": 0}

    # -- combined (base + overlay) problem ---------------------------------

    def combined_uv(self) -> np.ndarray:
        if len(self.extra_uv) == 0:
            return self.base_uv
        return np.concatenate([self.base_uv, self.extra_uv], axis=0)

    def combined_costs(self) -> np.ndarray:
        if len(self.extra_costs) == 0:
            return self.costs
        return np.concatenate([self.costs, self.extra_costs])

    def _graph_obj(self) -> g.Graph:
        if self._graph is None or self._graph_n_extra != len(self.extra_uv):
            self._graph = g.Graph(np.arange(self.n_nodes, dtype="uint64"),
                                  self.combined_uv().astype("uint64"))
            self._graph_n_extra = len(self.extra_uv)
        return self._graph

    # -- fragment / block geometry -----------------------------------------

    def dense_index(self, fragments: Sequence[int]) -> np.ndarray:
        """Dense node ids of original fragment labels; raises on unknown
        fragments (an edit against labels the graph never saw is a client
        error, not something to paper over)."""
        labs = np.asarray(list(fragments), dtype="uint64")
        idx = np.searchsorted(self.s0_nodes, labs)
        bad = (idx >= len(self.s0_nodes)) | (self.s0_nodes[
            np.minimum(idx, len(self.s0_nodes) - 1)] != labs)
        if bad.any():
            raise ValueError(
                f"unknown fragment ids {labs[bad][:10].tolist()} "
                f"(not in the s0 node table)")
        return idx.astype("int64")

    def block_nodes(self, block_id: int) -> np.ndarray:
        if block_id not in self._block_nodes:
            self._block_nodes[block_id] = resolver.load_block_nodes(
                self.problem_path, 0, block_id)
        return self._block_nodes[block_id]

    def affected_blocks(self, fragments: Sequence[int]) -> List[int]:
        """Minimal re-solve set for an edit on ``fragments`` (resolver
        criterion: blocks whose node set holds >= 2 of them)."""
        return resolver.resolve_affected(
            self.problem_path, fragments,
            fallback_block_shape=self.block_shape,
            paintera_path=self.paintera_path,
            paintera_lookup_key=self.paintera_lookup_key,
            paintera_block_shape=self.paintera_block_shape,
            node_loader=self.block_nodes)

    def blocks_with_fragments(self, fragments: Sequence[int]) -> List[int]:
        """Blocks whose node set intersects ``fragments`` at all — the
        output blocks the patcher must rewrite after a LUT delta."""
        frs = np.unique(np.asarray(list(fragments), dtype="uint64"))
        return [bid for bid in range(self.blocking.n_blocks)
                if len(self.block_nodes(bid))
                and bool(np.isin(self.block_nodes(bid), frs).any())]

    # -- applying edits ----------------------------------------------------

    def apply_edit(self, record) -> List[int]:
        """Overlay one :class:`~..edits.log.EditRecord` onto the costs;
        returns the affected subproblem blocks.  Deterministic, so
        replaying the log reconstructs the same state."""
        bias = EDIT_COST if record.op == "merge" else -EDIT_COST
        dense = self.dense_index(record.fragments)
        pairs = np.asarray([(min(a, b), max(a, b))
                            for a, b in itertools.combinations(dense, 2)],
                           dtype="int64").reshape(-1, 2)
        eids = g.find_edge_ids(self.base_uv.astype("uint64"),
                               pairs.astype("uint64"), strict=False)
        for (u, v), eid in zip(map(tuple, pairs), eids):
            if eid >= 0:
                self.costs[eid] = bias
            elif (u, v) in self._extra_index:
                self.extra_costs[self._extra_index[(u, v)]] = bias
            else:
                self._extra_index[(u, v)] = len(self.extra_uv)
                self.extra_uv = np.concatenate(
                    [self.extra_uv, np.asarray([[u, v]], "int64")], axis=0)
                self.extra_costs = np.concatenate(
                    [self.extra_costs, np.asarray([bias], "float64")])
        self.counters["applied"] += 1
        return self.affected_blocks(record.fragments)

    # -- per-block solve / warm-start --------------------------------------

    def block_signature(self, block_id: int):
        """(signature, dense nodes, inner ids, outer ids) of the block's
        LIVE subproblem — same hash ``SolveSubproblems`` persists, so a
        match proves the stored solution solves today's problem."""
        nodes = self.block_nodes(block_id)
        dense = (np.searchsorted(self.s0_nodes, nodes).astype("int64")
                 if len(nodes) else np.zeros(0, "int64"))
        inner, outer = self._graph_obj().extract_subgraph(
            dense.astype("uint64"))
        uv, costs = self.combined_uv(), self.combined_costs()
        sig = mc.subproblem_signature(dense, uv[inner], costs[inner])
        return sig, dense, inner, outer

    def _solve_cold(self, inner: np.ndarray,
                    outer: np.ndarray) -> np.ndarray:
        """Full subproblem solve — byte-for-byte the cold path of
        ``SolveSubproblems._solve_block`` over the combined arrays."""
        if len(inner) == 0:
            return outer.astype("int64")
        uv, costs = self.combined_uv(), self.combined_costs()
        agglomerator = key_to_agglomerator(self._agglomerator_key)
        sub_uv = uv[inner]
        sub_nodes, local_flat = np.unique(sub_uv, return_inverse=True)
        local_uv = local_flat.reshape(-1, 2).astype("int64")
        with stage("host-solve"):
            res = agglomerator(len(sub_nodes), local_uv, costs[inner],
                               time_limit=self._time_limit)
        cut_mask = res[local_uv[:, 0]] != res[local_uv[:, 1]]
        return np.concatenate([inner[cut_mask], outer]).astype("int64")

    def ensure_block(self, block_id: int, *, expected: Set[int] = frozenset(),
                     corr_id: Optional[str] = None,
                     allow_warm: bool = True) -> np.ndarray:
        """Cut-edge ids for one block, warm-started when the signature
        validates; ``expected`` is the edit's resolved footprint — a
        mismatch outside it is a stale cache (see module docstring)."""
        sig, _, inner, outer = self.block_signature(block_id)
        if allow_warm:
            mem = self._cache.get(block_id)
            if mem is not None and mem[0] == sig:
                self.counters["warm_reused"] += 1
                return mem[1]
            disk = mc.load_sub_result(self.problem_path, 0, block_id)
            if disk is not None and disk[1] == sig:
                cut = disk[0]
                self._cache[block_id] = (sig, cut)
                self.counters["warm_reused"] += 1
                return cut
            if disk is not None and block_id not in expected:
                # persisted solution no longer matches the live problem
                # and no current edit explains it: stale cache.  Fall back
                # to the full solve (never wrong output) and leave a
                # flight record under the edit's correlation id.
                self.counters["fallback"] += 1
                if self.flight_dir:
                    telemetry.flight_record(
                        self.flight_dir, "edit-warm-fallback",
                        extra={"edit_id": corr_id, "block": int(block_id),
                               "live_signature": sig,
                               "stored_signature": disk[1],
                               "expected_blocks": sorted(
                                   int(b) for b in expected)})
        cut = self._solve_cold(inner, outer)
        self._cache[block_id] = (sig, cut)
        self.counters["subproblems_solved"] += 1
        return cut

    # -- global re-solve ---------------------------------------------------

    def solve(self, *, incremental: bool = True,
              expected: Set[int] = frozenset(),
              corr_id: Optional[str] = None) -> np.ndarray:
        """Per-node segment labels (dense s0 order) after re-running the
        ladder: per-block cuts (warm or cold) -> reduce -> global solve.
        ``incremental=False`` ignores every cache — the from-scratch
        reference the identity gate compares against."""
        from .. import native

        cut_lists = [self.ensure_block(bid, expected=expected,
                                       corr_id=corr_id,
                                       allow_warm=incremental)
                     for bid in range(self.blocking.n_blocks)]
        uv, costs = self.combined_uv(), self.combined_costs()
        cut_ids = (np.unique(np.concatenate(cut_lists))
                   if any(len(c) for c in cut_lists)
                   else np.zeros(0, "int64"))
        merge_mask = np.ones(len(uv), bool)
        merge_mask[cut_ids] = False

        # reduce (workflows/multicut.py ReduceProblem, in-memory)
        with stage("host-reduce"):
            roots = native.ufd_merge_pairs(self.n_nodes, uv[merge_mask])
        _, node_labeling = np.unique(roots, return_inverse=True)
        node_labeling = node_labeling.astype("int64")
        n_new = int(node_labeling.max()) + 1 if self.n_nodes else 0
        mapped = node_labeling[uv]
        keep = mapped[:, 0] != mapped[:, 1]
        mu = np.minimum(mapped[keep][:, 0], mapped[keep][:, 1])
        mv = np.maximum(mapped[keep][:, 0], mapped[keep][:, 1])
        pair = np.stack([mu, mv], axis=1)
        new_uv, inverse = np.unique(pair, axis=0, return_inverse=True)
        new_costs = np.zeros(len(new_uv), "float64")
        np.add.at(new_costs, inverse, costs[keep])

        # global solve over the reduced problem
        agglomerator = key_to_agglomerator(self._agglomerator_key)
        with stage("host-solve"):
            labels = agglomerator(n_new, new_uv.astype("int64"), new_costs,
                                  time_limit=self._time_limit)
        return np.asarray(labels)[node_labeling]

    def replay(self, edit_log) -> int:
        """Re-apply a durable :class:`~.log.EditLog` in order."""
        return edit_log.replay(self.apply_edit)
