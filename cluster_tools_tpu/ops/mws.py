"""Mutex-watershed grid-graph edge extraction + segmentation.

TPU-native replacement for affogato's ``compute_mws_segmentation`` /
``MWSGridGraph.compute_nh_and_weights`` (reference:
utils/segmentation_utils.py:226-295, mutex_watershed/mws_blocks.py:136-174).
The split of labor follows SURVEY.md §7: edge weights, stride subsampling,
masking and noise run as one jitted device program over the affinity block
(pure slicing/elementwise — MXU-adjacent bandwidth work XLA fuses well);
the inherently sequential Kruskal-with-mutex-constraints clustering runs in
first-party C++ (native.mutex_clustering), exactly as the reference keeps it
in affogato's C++.

Edge semantics (the mutex-watershed paper's convention, which the
affogato wrapper reproduces by inverting attractive channels before an
ascending sort):

* channel ``c`` holds the affinity between anchor voxel ``i`` and voxel
  ``i + offsets[c]``; affinity 1 = same object;
* the first ``ndim`` channels (direct neighbors) give *attractive* edges
  with merge priority ``aff``;
* the remaining (long-range) channels give *mutex* edges with separation
  priority ``1 - aff``;
* all edges are processed jointly in descending priority order.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import native


def _offset_slices(off: Sequence[int], shape: Sequence[int]):
    """Anchor/partner slice tuples for one offset channel (in-bounds only)."""
    sl_a, sl_b = [], []
    for o, s in zip(off, shape):
        if o >= 0:
            sl_a.append(slice(0, s - o))
            sl_b.append(slice(o, s))
        else:
            sl_a.append(slice(-o, s))
            sl_b.append(slice(0, s + o))
    return tuple(sl_a), tuple(sl_b)


@partial(jax.jit, static_argnames=("offsets", "n_attractive", "strides",
                                   "randomize_strides", "have_mask",
                                   "noise_level"))
def _grid_edges_device(affs: jnp.ndarray, mask: jnp.ndarray, key: jnp.ndarray,
                       noise_level: float, offsets: Tuple[Tuple[int, ...], ...],
                       n_attractive: int, strides: Tuple[int, ...],
                       randomize_strides: bool, have_mask: bool):
    """Per-channel (u, v, w, valid) flat arrays; u/v are flat voxel indices.

    Mutex channels are subsampled on the stride grid (or a random subset of
    matching density when ``randomize_strides`` — reference config knob,
    mws_blocks.py:44).
    """
    shape = affs.shape[1:]
    ndim = len(shape)
    nvox = int(np.prod(shape))
    flat = jnp.arange(nvox, dtype=jnp.int32).reshape(shape)
    if noise_level > 0:
        affs = affs + noise_level * jax.random.uniform(key, affs.shape)
    out = []
    for c, off in enumerate(offsets):
        sl_a, sl_b = _offset_slices(off, shape)
        u = flat[sl_a].reshape(-1)
        v = flat[sl_b].reshape(-1)
        w = affs[c][sl_a].reshape(-1)
        valid = jnp.ones(u.shape, dtype=bool)
        if have_mask:
            valid &= mask[sl_a].reshape(-1) & mask[sl_b].reshape(-1)
        if c >= n_attractive:
            w = 1.0 - w
            if randomize_strides:
                density = 1.0 / float(np.prod(strides))
                kc = jax.random.fold_in(key, c)
                valid &= jax.random.uniform(kc, u.shape) < density
            elif any(s > 1 for s in strides):
                on_grid = jnp.ones(affs[c][sl_a].shape, dtype=bool)
                for ax in range(ndim):
                    pos = jnp.arange(on_grid.shape[ax]) + (sl_a[ax].start or 0)
                    sel = (pos % strides[ax]) == 0
                    shp = [1] * ndim
                    shp[ax] = on_grid.shape[ax]
                    on_grid &= sel.reshape(shp)
                valid &= on_grid.reshape(-1)
        out.append((u, v, w, valid))
    return out


def grid_graph_edges_host(affs: np.ndarray,
                          offsets: Sequence[Sequence[int]],
                          strides: Optional[Sequence[int]] = None,
                          mask: Optional[np.ndarray] = None,
                          id_offset: int = 0):
    """Host (numpy) edge extraction — same semantics as the device path
    for the deterministic cases (no noise, no randomized strides).

    ``id_offset`` shifts the flat voxel ids into a global frame (a
    shard-local origin's flat offset): sharded/mesh callers extract each
    shard's grid edges in its own window and concatenate without id
    collisions.

    The clustering consumer needs the FULL edge list in host memory, and
    the indices are pure arange arithmetic over data the host already
    read from the store — on link-attached accelerators the device
    detour would upload the affinities and download ~12 bytes/edge for
    arrays the host can produce for free (the reference keeps this whole
    stage in CPU C++ for the same reason, affogato)."""
    ndim = len(offsets[0])
    shape = affs.shape[1:]
    strides = tuple(int(s) for s in (strides or (1,) * ndim))
    if mask is not None:
        mask = np.asarray(mask).astype(bool)
    flat = np.arange(int(np.prod(shape)), dtype=np.int64).reshape(shape) \
        + np.int64(id_offset)
    uva, wa, uvm, wm = [], [], [], []
    for c, off in enumerate(offsets):
        sl_a, sl_b = _offset_slices(off, shape)
        u = flat[sl_a].reshape(-1)
        v = flat[sl_b].reshape(-1)
        # float32 arithmetic first, exactly like the device program —
        # computing 1-w in float64 would order some edge priorities
        # differently between the two impls
        w = affs[c][sl_a].reshape(-1).astype("float32")
        valid = np.ones(u.shape, bool)
        if mask is not None:
            valid &= (mask[sl_a].reshape(-1) & mask[sl_b].reshape(-1))
        if c >= ndim:
            w = np.float32(1.0) - w
            if any(s > 1 for s in strides):
                on_grid = np.ones(affs[c][sl_a].shape, bool)
                for ax in range(ndim):
                    pos = np.arange(on_grid.shape[ax]) \
                        + (sl_a[ax].start or 0)
                    sel = (pos % strides[ax]) == 0
                    shp = [1] * ndim
                    shp[ax] = on_grid.shape[ax]
                    on_grid &= sel.reshape(shp)
                valid &= on_grid.reshape(-1)
        uv = np.stack([u[valid], v[valid]], axis=1)
        (uva if c < ndim else uvm).append(uv)
        (wa if c < ndim else wm).append(w[valid].astype("float64"))

    def cat_uv(xs):
        return (np.concatenate(xs, axis=0) if xs
                else np.zeros((0, 2), dtype="int64"))

    return (cat_uv(uva), np.concatenate(wa) if wa else np.zeros(0),
            cat_uv(uvm), np.concatenate(wm) if wm else np.zeros(0))


def grid_graph_edges(affs: np.ndarray, offsets: Sequence[Sequence[int]],
                     strides: Optional[Sequence[int]] = None,
                     randomize_strides: bool = False,
                     mask: Optional[np.ndarray] = None,
                     noise_level: float = 0.0, seed: int = 0,
                     impl: str = "auto", id_offset: int = 0):
    """Extract (uv_attractive, w_attractive, uv_mutex, w_mutex) host arrays.

    ``impl='auto'`` uses the host path for the deterministic cases (see
    grid_graph_edges_host) and the device program when noise injection or
    randomized strides need the jax PRNG stream.  ``id_offset`` shifts
    voxel ids into a global frame (shard-local origins, see
    grid_graph_edges_host)."""
    if impl == "auto":
        impl = ("device" if (noise_level > 0 or randomize_strides)
                else "host")
    if impl == "host":
        return grid_graph_edges_host(affs, offsets, strides=strides,
                                     mask=mask, id_offset=id_offset)
    ndim = len(offsets[0])
    shape = affs.shape[1:]
    assert affs.shape[0] == len(offsets), (affs.shape, len(offsets))
    strides = tuple(int(s) for s in (strides or (1,) * ndim))
    have_mask = mask is not None
    mask_dev = jnp.asarray(
        mask.astype(bool) if have_mask else np.ones((1,) * ndim, bool))
    per_channel = _grid_edges_device(
        jnp.asarray(affs, dtype=jnp.float32), mask_dev,
        jax.random.PRNGKey(seed), float(noise_level),
        tuple(tuple(int(o) for o in off) for off in offsets),
        ndim, strides, bool(randomize_strides), have_mask)
    # FOUR concatenated downloads instead of four per channel: each
    # np.asarray is its own round trip on tunnel-attached chips, and the
    # per-channel fetches made small-block extraction latency-bound
    lengths = [int(u.shape[0]) for u, _, _, _ in per_channel]
    u_all = np.asarray(jnp.concatenate(
        [u for u, _, _, _ in per_channel])).astype("int64") + id_offset
    v_all = np.asarray(jnp.concatenate(
        [v for _, v, _, _ in per_channel])).astype("int64") + id_offset
    w_all = np.asarray(jnp.concatenate([w for _, _, w, _ in per_channel]))
    ok_all = np.asarray(jnp.concatenate(
        [ok for _, _, _, ok in per_channel]))
    uva: List[np.ndarray] = []
    wa: List[np.ndarray] = []
    uvm: List[np.ndarray] = []
    wm: List[np.ndarray] = []
    pos = 0
    for c, ln in enumerate(lengths):
        sl = slice(pos, pos + ln)
        pos += ln
        sel = ok_all[sl]
        uv = np.stack([u_all[sl][sel], v_all[sl][sel]], axis=1)
        (uva if c < ndim else uvm).append(uv)
        (wa if c < ndim else wm).append(
            w_all[sl][sel].astype("float64"))
    def cat_uv(xs):
        return (np.concatenate(xs, axis=0) if xs
                else np.zeros((0, 2), dtype="int64"))

    return (cat_uv(uva), np.concatenate(wa) if wa else np.zeros(0),
            cat_uv(uvm), np.concatenate(wm) if wm else np.zeros(0))


@partial(jax.jit, static_argnames=("offsets", "strides", "seeded"))
def _sorted_edges_device(affs, seeds, offsets: Tuple[Tuple[int, ...], ...],
                         strides: Tuple[int, ...], seeded: bool):
    """Extract ALL grid edges and sort them by DESCENDING mutex-watershed
    priority on device, returning (u, v_packed) int32 streams the host
    union-find scan consumes directly (native.mutex_clustering_sorted).

    The host Kruskal's dominant cost is its stable_sort of tens of
    millions of 24-byte edge structs; the device does that sort as one
    fused key+payload sort and ships 8 bytes/edge back.  v_packed packs
    the partner index with the edge class: bit 30 = mutex edge, bit 29 =
    dropped (zero-affinity attractive or off-stride mutex; kept in the
    stream so the layout is static, skipped by the scan via u = -1).

    ``seeds`` (int32 volume, 0 = unseeded) boost intra-seed attractive
    edges above every data weight (the two-pass seeded variant); pass a
    dummy scalar array when ``seeded`` is False.
    """
    shape = affs.shape[1:]
    ndim = len(shape)
    flat = jnp.arange(int(np.prod(shape)), dtype=jnp.int32).reshape(shape)
    sflat = seeds.reshape(-1) if seeded else None
    us, vs, ws, ms, oks = [], [], [], [], []
    for c, off in enumerate(offsets):
        sl_a, sl_b = _offset_slices(off, shape)
        u = flat[sl_a].reshape(-1)
        v = flat[sl_b].reshape(-1)
        w = affs[c][sl_a].reshape(-1).astype(jnp.float32)
        is_mutex = c >= ndim
        valid = jnp.ones(u.shape, bool)
        if is_mutex:
            w = 1.0 - w
            if any(s > 1 for s in strides):
                on_grid = jnp.ones(affs[c][sl_a].shape, bool)
                for ax in range(ndim):
                    pos = jnp.arange(on_grid.shape[ax]) \
                        + (sl_a[ax].start or 0)
                    sel = (pos % strides[ax]) == 0
                    shp = [1] * ndim
                    shp[ax] = on_grid.shape[ax]
                    on_grid &= sel.reshape(shp)
                valid &= on_grid.reshape(-1)
        else:
            if seeded:
                su, sv = sflat[u], sflat[v]
                w = jnp.where((su != 0) & (su == sv), jnp.float32(2.0), w)
            # zero-affinity attractive edges carry no merge evidence
            # (deliberate deviation from affogato, see
            # mutex_watershed_segmentation)
            valid &= w > 0
        us.append(u)
        vs.append(v)
        ws.append(w)
        ms.append(jnp.full(u.shape, is_mutex, bool))
        oks.append(valid)
    u_all = jnp.concatenate(us)
    v_all = jnp.concatenate(vs)
    w_all = jnp.concatenate(ws)
    m_all = jnp.concatenate(ms)
    ok_all = jnp.concatenate(oks)
    # invalid edges sink to the end of the descending order
    key = jnp.where(ok_all, -w_all, jnp.float32(np.inf))
    u_s = jnp.where(ok_all, u_all, -1)
    v_packed = (v_all
                | (m_all.astype(jnp.int32) << 30)
                | ((~ok_all).astype(jnp.int32) << 29))
    _, u_sorted, vp_sorted = jax.lax.sort(
        [key, u_s, v_packed], num_keys=1, is_stable=True)
    return u_sorted, vp_sorted


@partial(jax.jit, static_argnames=("outer_shape", "offsets", "strides",
                                   "seeded"))
def _sorted_edges_resident_impl(vol, origin, seeds,
                                outer_shape: Tuple[int, ...],
                                offsets: Tuple[Tuple[int, ...], ...],
                                strides: Tuple[int, ...], seeded: bool):
    affs = jax.lax.dynamic_slice(
        vol, (0,) + tuple(origin[d] for d in range(len(outer_shape))),
        (vol.shape[0],) + outer_shape)
    u_sorted, vp_sorted = _sorted_edges_device(affs, seeds, offsets,
                                               strides, seeded)
    return u_sorted, vp_sorted, affs.sum()


def compact_seeds_int32(seeds: np.ndarray) -> np.ndarray:
    """Equality-preserving block-local relabel of seed ids to int32.

    The seeded pass-2 device path feeds uint64 GLOBAL labels
    (``block_id * offset_unit + 1 + rank``) as seeds; a plain
    ``astype('int32')`` wraps once ``block_id * offset_unit > 2^31``
    (~112 blocks at bench sizes), colliding distinct seeds (false
    ``su == sv`` boosts -> wrong merges) or wrapping a seed to 0 (seed
    lost).  Only EQUALITY matters inside ``_sorted_edges_device``, so a
    dense block-local relabel is exact: 0 (unseeded) stays 0, distinct
    ids stay distinct, and the result always fits int32 (a block holds
    < 2^29 voxels, enforced below)."""
    s = np.asarray(seeds)
    if s.size == 0 or int(s.max()) < (1 << 31):
        # common case (volumes below ~112 blocks): the cast is already
        # exact — skip the O(n log n) unique over the outer block
        return s.astype("int32")
    uniq, inv = np.unique(s, return_inverse=True)
    inv = inv.astype("int32").reshape(s.shape)
    if uniq.size and uniq[0] == 0:
        return inv
    return inv + 1  # no zeros present: keep every id nonzero


def _sorted_edges_resident(affs_dev, origin, outer_shape,
                           offsets, strides,
                           seeds: Optional[np.ndarray] = None):
    """Submit one block's extract+sort against the DEVICE-RESIDENT
    affinity volume without synchronizing: dynamic-slice the outer
    window, sort every grid edge by descending priority.  Returns
    (u_sorted, v_packed, block_affinity_sum) device handles — callers
    pipeline the host scan of block i with the device sort of i+1.
    The affinity sum reproduces the host path's skip-empty-block rule
    without a separate download."""
    import jax.numpy as jnp

    if int(np.prod(outer_shape)) >= (1 << 29):
        # v_packed carries the partner voxel index in bits 0-28 (flags at
        # 29/30): a larger outer block would silently corrupt the edge
        # stream.  Callers route oversized blocks to the host path
        raise ValueError(
            f"outer block {tuple(outer_shape)} has >= 2^29 voxels — the "
            "packed edge stream cannot address it; use the host path or "
            "shrink blocks")
    seeded = seeds is not None
    seeds_in = (jnp.asarray(compact_seeds_int32(seeds))
                if seeded else jnp.zeros((1,) * len(outer_shape), jnp.int32))
    return _sorted_edges_resident_impl(
        affs_dev, jnp.asarray(origin, dtype=jnp.int32), seeds_in,
        tuple(int(s) for s in outer_shape),
        tuple(tuple(int(o) for o in off) for off in offsets),
        tuple(int(s) for s in strides), seeded)


def mutex_watershed_scan_sorted(u, vp, shape,
                                mask: Optional[np.ndarray] = None):
    """Host half of the sorted finalize: the C++ union-find scan over a
    DOWNLOADED sorted edge stream; returns uint64 labels consecutive
    from 1 (0 on masked voxels).  Split from the downloads so pipelining
    callers can attribute the link transfer (``d2h-edges``) and this
    sequential host scan (``host-scan``) to separate stages — lumping
    both under a ``sync-`` stage mis-credited the host scan to the
    accelerator path (ADVICE r5)."""
    dropped = (vp >> 29) & 1
    u = np.where(dropped != 0, np.int32(-1), u)
    v = vp & np.int32((1 << 29) - 1)
    flags = ((vp >> 30) & 1).astype(np.uint8)
    n_nodes = int(np.prod(shape))
    cluster = native.mutex_clustering_sorted(n_nodes, u, v, flags)
    labels = cluster.reshape(shape)
    if mask is not None:
        labels = np.where(mask, labels + 1, 0)
    else:
        labels = labels + 1
    uniq, inv = np.unique(labels, return_inverse=True)
    if uniq.size and uniq[0] == 0:
        labels = inv.reshape(shape).astype("uint64")
    else:
        labels = (inv.reshape(shape) + 1).astype("uint64")
    return labels


def mutex_watershed_finalize_sorted(handles, shape, asum=None,
                                    mask: Optional[np.ndarray] = None):
    """Download one block's sorted edge stream and run the host scan.
    Returns (labels, affinity_sum): uint64 labels consecutive from 1
    (0 on masked voxels); when ``asum`` (a device handle) reports an
    all-zero block the scan is skipped and labels is None."""
    u_sorted, vp_sorted = handles
    a = float(np.asarray(asum)) if asum is not None else None
    if a == 0.0:
        return None, 0.0
    labels = mutex_watershed_scan_sorted(np.asarray(u_sorted),
                                         np.asarray(vp_sorted), shape,
                                         mask=mask)
    return labels, (a if a is not None else 1.0)


def mutex_watershed_segmentation(
        affs: np.ndarray, offsets: Sequence[Sequence[int]],
        strides: Optional[Sequence[int]] = None,
        randomize_strides: bool = False,
        mask: Optional[np.ndarray] = None,
        noise_level: float = 0.0, seed: int = 0,
        seeds: Optional[np.ndarray] = None,
        return_seed_assignments: bool = False):
    """Mutex watershed over an affinity volume.

    ``seeds`` (same shape as the volume, 0 = unseeded) implement the
    reference's two-pass seeded variant (utils/segmentation_utils.py:252-295):
    direct-neighbor edges inside one seed become maximally attractive, so a
    seed region is never split; distinct seeds *may* still merge when the
    affinities support it, and the caller reconciles those merges through the
    returned (segment_label, seed_label) assignments — mirroring the
    grid-graph ``set_seed_state``/two_pass_assignments protocol.

    Returns labels (uint64, consecutive from 1; 0 on masked-out voxels), and
    optionally the seed-assignment pairs.
    """
    shape = affs.shape[1:]
    uva, wa, uvm, wm = grid_graph_edges(
        affs, offsets, strides=strides, randomize_strides=randomize_strides,
        mask=mask, noise_level=noise_level, seed=seed)
    if seeds is not None:
        sflat = np.asarray(seeds).reshape(-1)
        su, sv = sflat[uva[:, 0]], sflat[uva[:, 1]]
        same_seed = (su != 0) & (su == sv)
        # above every data weight (affinities are normalized to [0, 1]);
        # grid_graph.intra_seed_weight = 1 equivalent
        wa = np.where(same_seed, 2.0, wa)
    # an attractive edge with zero affinity carries no merge evidence;
    # keeping it would let unconstrained clusters merge arbitrarily at the
    # bottom of the priority queue (deliberate deviation from affogato, which
    # processes zero-weight edges).  After seed boosting, so intra-seed edges
    # always survive.
    keep = wa > 0
    uva, wa = uva[keep], wa[keep]
    n_nodes = int(np.prod(shape))
    cluster = native.mutex_clustering(n_nodes, uva, wa, uvm, wm)
    labels = cluster.reshape(shape)
    if mask is not None:
        labels = np.where(mask, labels + 1, 0)
    else:
        labels = labels + 1
    # consecutive relabel, keep zeros
    uniq, inv = np.unique(labels, return_inverse=True)
    if uniq.size and uniq[0] == 0:
        labels = inv.reshape(shape).astype("uint64")
    else:
        labels = (inv.reshape(shape) + 1).astype("uint64")
    if not return_seed_assignments:
        return labels
    assignments = np.zeros((0, 2), dtype="uint64")
    if seeds is not None:
        sflat = np.asarray(seeds).reshape(-1)
        lflat = labels.reshape(-1)
        seeded = sflat != 0
        if seeded.any():
            assignments = np.unique(
                np.stack([lflat[seeded].astype("uint64"),
                          sflat[seeded].astype("uint64")], axis=1), axis=0)
    return labels, assignments
