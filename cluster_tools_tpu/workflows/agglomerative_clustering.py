"""Global agglomerative clustering of the RAG.

Re-specification of the reference's ``agglomerative_clustering/`` package
(agglomerative_clustering.py:95-160 — single job: load graph + edge
features, run the edge-weighted cluster policy to a threshold, write the
node assignment table).  The priority-queue agglomeration is the first-party
native kernel (native.agglomerative_clustering, the nifty.graph.agglo
equivalent)."""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from ..core import graph as g
from ..core.runtime import BlockTask
from ..core.storage import file_reader


class AgglomerativeClustering(BlockTask):
    """Single-job RAG agglomeration (reference:
    agglomerative_clustering.py:24-92)."""

    task_name = "agglomerative_clustering"
    global_task = True
    allow_retry = False

    def __init__(self, problem_path: str, assignment_path: str,
                 threshold: float, features_key: str = "features",
                 graph_key: str = "s0/graph", **kw):
        self.problem_path = problem_path
        self.assignment_path = assignment_path
        self.threshold = threshold
        self.features_key = features_key
        self.graph_key = graph_key
        super().__init__(**kw)

    @staticmethod
    def default_task_config():
        conf = BlockTask.default_task_config()
        conf.update({"size_regularizer": 0.5})
        return conf

    def run_impl(self):
        self.run_jobs(None, {
            "problem_path": self.problem_path,
            "assignment_path": self.assignment_path,
            "threshold": self.threshold,
            "features_key": self.features_key, "graph_key": self.graph_key,
        })

    @classmethod
    def process_job(cls, job_id: int, job_config: Dict[str, Any], log_fn):
        from .. import native

        cfg = job_config["config"]
        nodes, edges, _ = g.load_graph(cfg["problem_path"], cfg["graph_key"])
        graph = g.Graph(nodes, edges)
        uv_dense = np.stack([graph.node_index(edges[:, 0]),
                             graph.node_index(edges[:, 1])], axis=1) \
            if len(edges) else np.zeros((0, 2), "int64")
        with file_reader(cfg["problem_path"], "r") as f:
            ds = f[cfg["features_key"]]
            feats = ds[:]
        edge_weights = feats[:, 0]
        edge_sizes = feats[:, feats.shape[1] - 1]
        labels = native.agglomerative_clustering(
            len(nodes), uv_dense, edge_weights, edge_sizes=edge_sizes,
            threshold=float(cfg["threshold"]),
            size_regularizer=float(cfg.get("size_regularizer", 0.5)))
        log_fn(f"agglomerated {len(nodes)} nodes -> "
               f"{len(np.unique(labels))} clusters at threshold "
               f"{cfg['threshold']}")

        from .multicut import save_assignment_table

        save_assignment_table(nodes, labels, cfg["assignment_path"])
