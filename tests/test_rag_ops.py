"""Unit tests for the device edge-stats paths (compacted + full + oracle)."""

import numpy as np
import jax.numpy as jnp
import pytest


def _random_samples(n, n_labels=40, seed=0):
    rng = np.random.RandomState(seed)
    u = rng.randint(1, n_labels, n).astype("int32")
    v = (u + rng.randint(1, 5, n)).astype("int32")
    x = rng.rand(n).astype("float32")
    ok = rng.rand(n) < 0.2
    return jnp.asarray(u), jnp.asarray(v), jnp.asarray(x), jnp.asarray(ok)


def _oracle(u, v, x, ok, e_max=256):
    from cluster_tools_tpu.ops.rag import segmented_stats

    u, v, x, ok = (np.asarray(a) for a in (u, v, x, ok))
    uv = np.stack([u[ok], v[ok]], axis=1)
    uniq, inv = np.unique(uv, axis=0, return_inverse=True)
    feats = segmented_stats(inv, x[ok], len(uniq))
    return uniq.astype("int64"), feats


@pytest.mark.parametrize("compact", [False, True])
def test_device_edge_stats_matches_oracle(compact):
    from cluster_tools_tpu.ops.rag import (device_edge_stats_finalize,
                                           device_edge_stats_submit)

    u, v, x, ok = _random_samples(5000)
    handles = device_edge_stats_submit(u, v, x, ok, e_max=512,
                                       compact=compact)
    uv, feats = device_edge_stats_finalize(handles, 512)
    uv_o, feats_o = _oracle(u, v, x, ok)
    np.testing.assert_array_equal(uv, uv_o)
    np.testing.assert_allclose(feats, feats_o, rtol=1e-4, atol=1e-5)


def test_device_edge_stats_multi_shares_layout():
    from cluster_tools_tpu.ops.rag import (device_edge_stats_finalize,
                                           device_edge_stats_submit_multi)

    u, v, x, ok = _random_samples(4096)
    x2 = jnp.asarray(np.random.RandomState(7).rand(4096).astype("float32"))
    handles = device_edge_stats_submit_multi(u, v, ok, [x, x2], e_max=512,
                                             compact=True)
    for values, h in ((x, handles[0]), (x2, handles[1])):
        uv, feats = device_edge_stats_finalize(h, 512)
        uv_o, feats_o = _oracle(u, v, values, ok)
        np.testing.assert_array_equal(uv, uv_o)
        np.testing.assert_allclose(feats, feats_o, rtol=1e-4, atol=1e-5)


def test_compaction_capacity_overflow_raises():
    from cluster_tools_tpu.ops.rag import (device_edge_stats_finalize,
                                           device_edge_stats_submit)

    n = 1 << 15
    rng = np.random.RandomState(0)
    u = jnp.asarray(rng.randint(1, 10, n).astype("int32"))
    v = u + 1
    x = jnp.asarray(rng.rand(n).astype("float32"))
    ok = jnp.ones((n,), bool)  # 100% valid > 25% capacity
    handles = device_edge_stats_submit(u, v, x, ok, e_max=512, compact=True)
    with pytest.raises(RuntimeError, match="compaction capacity"):
        device_edge_stats_finalize(handles, 512)
    # the documented escape hatch works
    handles = device_edge_stats_submit(u, v, x, ok, e_max=512, compact=False)
    uv, feats = device_edge_stats_finalize(handles, 512)
    uv_o, feats_o = _oracle(u, v, x, ok)
    np.testing.assert_array_equal(uv, uv_o)
    np.testing.assert_allclose(feats, feats_o, rtol=1e-4, atol=1e-5)


def test_hist_stats_match_sort_stats():
    """The 256-bin histogram formulation must reproduce the sorted-position
    statistics exactly for uint8 samples (same mean/var/min/quantiles/max,
    same edge order)."""
    import jax.numpy as jnp

    from cluster_tools_tpu.ops.rag import (_edge_stats_device,
                                           _edge_stats_hist_device)

    rng = np.random.RandomState(0)
    n = 4096
    u = rng.randint(1, 40, n).astype("int32")
    v = u + rng.randint(1, 10, n).astype("int32")
    raw = rng.randint(0, 256, n).astype("uint8")
    ok = rng.rand(n) < 0.8
    uv_s, feats_s, n_s, of_s = _edge_stats_device(
        jnp.asarray(u), jnp.asarray(v),
        jnp.asarray(raw.astype("float32") / 255.0), jnp.asarray(ok),
        e_max=1024)
    uv_h, feats_h, n_h, of_h = _edge_stats_hist_device(
        jnp.asarray(u), jnp.asarray(v), jnp.asarray(raw), jnp.asarray(ok),
        e_max=1024)
    assert int(n_s) == int(n_h) and int(of_s) == int(of_h) == 0
    nr = int(n_s)
    np.testing.assert_array_equal(np.asarray(uv_s)[:nr], np.asarray(uv_h)[:nr])
    np.testing.assert_allclose(np.asarray(feats_h)[:nr],
                               np.asarray(feats_s)[:nr], rtol=2e-4, atol=2e-6)


def test_hist_dual_matches_two_sample_expansion():
    """Dual-sample histogram stats must equal the two-sample path fed the
    expanded (duplicated-pair) arrays — the fused chain's uint8 route."""
    import jax.numpy as jnp

    from cluster_tools_tpu.ops.rag import (_edge_stats_hist_device,
                                           _edge_stats_hist_dual)

    rng = np.random.RandomState(1)
    n = 4096
    u = rng.randint(1, 40, n).astype("int32")
    v = u + rng.randint(1, 10, n).astype("int32")
    ra = rng.randint(0, 256, n).astype("uint8")
    rb = rng.randint(0, 256, n).astype("uint8")
    ok = rng.rand(n) < 0.8
    uv_d, feats_d, n_d, of_d = _edge_stats_hist_dual(
        jnp.asarray(u), jnp.asarray(v), jnp.asarray(ra), jnp.asarray(rb),
        jnp.asarray(ok), e_max=1024)
    uv_e, feats_e, n_e, of_e = _edge_stats_hist_device(
        jnp.asarray(np.concatenate([u, u])),
        jnp.asarray(np.concatenate([v, v])),
        jnp.asarray(np.concatenate([ra, rb])),
        jnp.asarray(np.concatenate([ok, ok])), e_max=1024)
    assert int(n_d) == int(n_e) and int(of_d) == int(of_e) == 0
    nr = int(n_d)
    np.testing.assert_array_equal(np.asarray(uv_d)[:nr],
                                  np.asarray(uv_e)[:nr])
    np.testing.assert_allclose(np.asarray(feats_d)[:nr],
                               np.asarray(feats_e)[:nr], rtol=1e-5,
                               atol=1e-7)


def test_unique_pairs_packed_and_fallback():
    """Shared edge-table dedup helper (fused face assembly + server
    tail): packed-u64 fast path and >2^32-id structured fallback agree
    on the (uniq, inverse) contract."""
    from cluster_tools_tpu.ops.rag import unique_pairs

    u = np.array([1, 2, 1, 3])
    v = np.array([2, 3, 2, 4])
    uniq, inv = unique_pairs(u, v)
    np.testing.assert_array_equal(uniq, [[1, 2], [2, 3], [3, 4]])
    np.testing.assert_array_equal(uniq[inv],
                                  np.stack([u, v], 1).astype("uint64"))
    uniq, inv = unique_pairs(np.array([], "int64"), np.array([], "int64"))
    assert uniq.shape == (0, 2) and inv.shape == (0,)
    big_u = np.array([1 << 33, 5, 1 << 33], "uint64")
    big_v = np.array([1 << 34, 6, 1 << 34], "uint64")
    uniq, inv = unique_pairs(big_u, big_v)
    assert len(uniq) == 2
    np.testing.assert_array_equal(uniq[inv], np.stack([big_u, big_v], 1))
