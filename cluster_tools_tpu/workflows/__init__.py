"""User-facing workflow re-exports (reference: cluster_tools/__init__.py)."""

from .graph import GraphWorkflow
from .inference import InferenceTask
from .multicut import MulticutWorkflow
from .mutex_watershed import MwsWorkflow, TwoPassMwsWorkflow
from .relabel import RelabelWorkflow
from .segmentation import MulticutSegmentationWorkflow, ProblemWorkflow
from .thresholded_components import ThresholdedComponentsWorkflow
from .watershed import WatershedWorkflow

__all__ = [
    "GraphWorkflow", "InferenceTask", "MulticutWorkflow", "MwsWorkflow",
    "TwoPassMwsWorkflow",
    "RelabelWorkflow", "MulticutSegmentationWorkflow", "ProblemWorkflow",
    "ThresholdedComponentsWorkflow", "WatershedWorkflow",
]
