"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip hardware is not available in CI; sharding/collective paths are
validated on a virtual CPU mesh (xla_force_host_platform_device_count), the
standard JAX technique for testing pjit/shard_map layouts without TPUs.
Must run before the first jax import anywhere in the test session.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture()
def tmp_workdir(tmp_path):
    """tmp_folder + config_dir pair with a small-block global config."""
    from cluster_tools_tpu.core.config import ConfigDir

    tmp_folder = str(tmp_path / "tmp")
    config_dir = str(tmp_path / "configs")
    cfg = ConfigDir(config_dir)
    cfg.write_global_config({"block_shape": [10, 10, 10], "max_num_retries": 0})
    return tmp_folder, config_dir
