"""Multi-host scaffolding: 2 cooperating processes complete a blockwise
workflow over the shared store (per-process block ownership, lead-only
global tasks, filesystem barriers)."""

import os
import subprocess
import sys

import numpy as np
import pytest

from cluster_tools_tpu.core.storage import file_reader
from cluster_tools_tpu.core.workflow import build

DRIVER = """
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np

if __name__ == "__main__":
    from cluster_tools_tpu.core.workflow import build
    from cluster_tools_tpu.workflows.thresholded_components import (
        ThresholdedComponentsWorkflow)

    wf = ThresholdedComponentsWorkflow(
        input_path={path!r}, input_key="vol", output_path={path!r},
        output_key="cc_multi", threshold=0.5, tmp_folder={tmp!r},
        config_dir={cfg!r}, max_jobs=4, target="inline")
    assert build([wf], raise_on_failure=True)
"""


def _volume(shape=(16, 16, 32), seed=0):
    rng = np.random.RandomState(seed)
    vol = np.zeros(shape, "float32")
    zz, yy, xx = np.meshgrid(*[np.arange(s) for s in shape], indexing="ij")
    for _ in range(30):
        c = rng.rand(3) * np.array(shape)
        d2 = (zz - c[0]) ** 2 + (yy - c[1]) ** 2 + (xx - c[2]) ** 2
        vol = np.maximum(vol, np.exp(-d2 / 3.0).astype("float32"))
    return vol


def test_two_process_blockwise_cooperation(tmp_path, tmp_workdir):
    from cluster_tools_tpu.workflows.thresholded_components import (
        ThresholdedComponentsWorkflow)

    tmp_folder, config_dir = tmp_workdir
    vol = _volume()
    path = str(tmp_path / "d.n5")
    with file_reader(path) as f:
        ds = f.require_dataset("vol", shape=vol.shape, chunks=(8, 8, 8),
                               dtype="float32")
        ds[:] = vol

    # single-process reference result
    wf = ThresholdedComponentsWorkflow(
        input_path=path, input_key="vol", output_path=path,
        output_key="cc_single", threshold=0.5,
        tmp_folder=f"{tmp_folder}_single", config_dir=config_dir,
        max_jobs=2, target="inline")
    assert build([wf], raise_on_failure=True)

    # two cooperating processes, same driver script (SPMD style)
    script = str(tmp_path / "driver.py")
    multi_tmp = f"{tmp_folder}_multi"
    with open(script, "w") as f:
        f.write(DRIVER.format(repo=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), path=path, tmp=multi_tmp,
            cfg=config_dir))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["CTT_PROCESS_COUNT"] = "2"
    procs = []
    for pid in range(2):
        e = dict(env)
        e["CTT_PROCESS_ID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, script], env=e,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = [p.communicate(timeout=300)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]

    with file_reader(path, "r") as f:
        single = f["cc_single"][:]
        multi = f["cc_multi"][:]
    np.testing.assert_array_equal(multi, single)

    # both processes actually processed blocks (job 0 AND job 1 logs)
    logs = os.listdir(os.path.join(multi_tmp, "logs"))
    assert any(name.endswith("_0.log") for name in logs)
    assert any(name.endswith("_1.log") for name in logs)
    import re

    counts = []
    for job in (0, 1):
        blocks = 0
        for name in logs:
            if name == f"block_components_{job}.log":
                with open(os.path.join(multi_tmp, "logs", name)) as f:
                    blocks = len(re.findall("processed block", f.read()))
        counts.append(blocks)
    assert all(c > 0 for c in counts), counts


RETRY_DRIVER = """
import os, sys
sys.path.insert(0, {repo!r})
import numpy as np

from cluster_tools_tpu.core.blocking import Blocking
from cluster_tools_tpu.core.runtime import BlockTask
from cluster_tools_tpu.core.storage import file_reader


class FlakyFillTask(BlockTask):
    '''Writes block_id+1 into each block; ODD blocks raise on the first
    attempt (marker files track attempts) — the multiprocess analog of the
    reference's FailingTask fixture (test/retry/failing_task.py).'''

    task_name = "flaky_fill"

    def __init__(self, path, **kw):
        self.path = path
        super().__init__(**kw)

    def run_impl(self):
        with file_reader(self.path, "r") as f:
            shape = list(f["vol"].shape)
        bs = self.global_block_shape()
        with file_reader(self.path) as f:
            f.require_dataset("filled", shape=shape, chunks=bs,
                              dtype="uint32")
        self.run_jobs(self.blocks_in_volume(shape, bs),
                      {{"path": self.path, "shape": shape,
                        "block_shape": bs,
                        "marker_dir": self.tmp_folder}})

    @classmethod
    def process_job(cls, job_id, job_config, log_fn):
        cfg = job_config["config"]
        blocking = Blocking(cfg["shape"], cfg["block_shape"])
        f = file_reader(cfg["path"])
        ds = f["filled"]
        injected = []
        for bid in job_config["block_list"]:
            marker = os.path.join(cfg["marker_dir"], f"attempt_{{bid}}")
            first = not os.path.exists(marker)
            open(marker, "a").close()
            if bid % 2 == 1 and first:
                injected.append(bid)  # skipped: no success line logged
                continue
            ds[blocking.get_block(bid).bb] = bid + 1
            log_fn(f"processed block {{bid}}")
        if injected:
            raise RuntimeError(f"injected failures for blocks {{injected}}")


if __name__ == "__main__":
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.workflow import build

    cfg = ConfigDir({cfg!r})
    cfg.write_global_config({{"block_shape": [8, 8, 8],
                              "max_num_retries": 1}})
    task = FlakyFillTask(path={path!r}, tmp_folder={tmp!r},
                         config_dir={cfg!r}, max_jobs=2, target="inline")
    assert build([task], raise_on_failure=True)
"""


def test_two_process_in_run_block_retry(tmp_path, tmp_workdir):
    """Injected per-block failures recover IN-RUN across two processes —
    no driver rerun (reference semantics cluster_tasks.py:136-170)."""
    tmp_folder, config_dir = tmp_workdir
    path = str(tmp_path / "d.n5")
    shape = (16, 16, 16)  # 8 blocks of [8,8,8]
    with file_reader(path) as f:
        ds = f.require_dataset("vol", shape=shape, chunks=(8, 8, 8),
                               dtype="float32")
        ds[:] = 0.0

    script = str(tmp_path / "driver.py")
    multi_tmp = f"{tmp_folder}_retry"
    with open(script, "w") as f:
        f.write(RETRY_DRIVER.format(
            repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            path=path, tmp=multi_tmp, cfg=config_dir))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["CTT_PROCESS_COUNT"] = "2"
    procs = []
    for pid in range(2):
        e = dict(env)
        e["CTT_PROCESS_ID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, script], env=e,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = [p.communicate(timeout=300)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]

    from cluster_tools_tpu.core.blocking import Blocking

    with file_reader(path, "r") as f:
        filled = f["filled"][:]
    blocking = Blocking(list(shape), [8, 8, 8])
    for bid in range(8):
        bb = blocking.get_block(bid).bb
        assert (filled[bb] == bid + 1).all(), f"block {bid} missing"
    # every block attempted; the in-run retry really fired (a retry log
    # line exists and the task was built by a SINGLE driver invocation)
    assert all(os.path.exists(os.path.join(multi_tmp, f"attempt_{b}"))
               for b in range(8))
    assert any("multiprocess retry" in o for o in outs), outs[0][-500:]


COLLECTIVE_DRIVER = """
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
sys.path.insert(0, {repo!r})

if __name__ == "__main__":
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P
    from cluster_tools_tpu.parallel.multihost import (init_distributed,
                                                      make_multihost_mesh)

    try:  # the version-compat import the library modules use
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    pid = int(sys.argv[1])
    init_distributed(coordinator_address="localhost:{port}",
                     num_processes=2, process_id=pid)
    assert jax.process_count() == 2
    assert jax.device_count() == 8, jax.device_count()
    mesh = make_multihost_mesh(("data", "model"), dcn_axis=0)
    assert mesh.devices.shape == (2, 4), mesh.devices.shape
    # the data axis spans BOTH processes: a psum over it is a real
    # cross-process collective (gloo transport on CPU)
    owners = np.vectorize(lambda d: d.process_index)(mesh.devices)
    assert set(owners[:, 0]) == {{0, 1}}, owners

    f = jax.jit(shard_map(lambda a: jax.lax.psum(a, "data"),
                          mesh=mesh, in_specs=P("data"),
                          out_specs=P()))
    x = jnp.arange(8.0)
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    r = np.asarray(f(xs))
    np.testing.assert_allclose(r, np.arange(8.0).reshape(2, 4).sum(0))
    print(f"p{{pid}} cross-process psum ok: {{r.tolist()}}")
"""


def test_two_process_cross_process_psum(tmp_path):
    """REAL cross-process collective: 2 jax.distributed CPU processes x 4
    virtual devices, one mesh from make_multihost_mesh, one psum over the
    process-spanning axis (the pod-scale path, gloo instead of DCN)."""
    import socket

    with socket.socket() as s:
        s.bind(("localhost", 0))
        port = s.getsockname()[1]
    script = str(tmp_path / "collective_driver.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(script, "w") as f:
        f.write(COLLECTIVE_DRIVER.format(repo=repo, port=port))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "CTT_PROCESS_COUNT", "CTT_PROCESS_ID",
                        "PYTHONPATH")}
    procs = [subprocess.Popen([sys.executable, script, str(pid)], env=env,
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
             for pid in range(2)]
    outs = [p.communicate(timeout=300)[0].decode() for p in procs]
    if any("Multiprocess computations aren't implemented" in o
           for o in outs):
        # this jaxlib's CPU backend has no cross-process collectives
        # (gloo-less build) — the path is exercised on real multihost
        pytest.skip("jaxlib CPU backend lacks multiprocess collectives")
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]
    assert all("cross-process psum ok" in o for o in outs), outs[0][-500:]


SHARD_DRIVER = """
import json, os, sys, time
sys.path.insert(0, {repo!r})

if __name__ == "__main__":
    from cluster_tools_tpu.core import telemetry
    from cluster_tools_tpu.parallel import multihost as mh

    pid = mh.process_index()
    telemetry.configure(enabled=True)
    with telemetry.span(f"job:p{{pid}}", cat="job", process_index=pid,
                        process_count=mh.process_count()):
        with telemetry.span("sync-execute", cat="stage") as sp:
            time.sleep(0.05 * (pid + 1))
            telemetry.annotate_memory(sp)
    anchor = mh.clock_anchor({tmp!r})
    mh.export_trace_shard({tmp!r}, anchor=anchor)
    mh.fs_barrier({tmp!r}, "shards-done")
    if mh.is_lead():
        m = mh.merge_trace_shards(
            {tmp!r}, os.path.join({tmp!r}, "merged_trace.json"))
        with open(os.path.join({tmp!r}, "merge_summary.json"), "w") as f:
            json.dump(m, f)
    print("shard ok")
"""


def test_two_process_trace_shards_merge(tmp_path):
    """ISSUE 17 acceptance: a 2-process run exports per-process trace
    shards (barrier-aligned clock anchors), and the lead merges them
    into ONE Perfetto-loadable trace whose rollups cross-check the
    per-process device_busy_seconds."""
    import json

    tmp = str(tmp_path / "shared")
    os.makedirs(tmp)
    script = str(tmp_path / "driver.py")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(script, "w") as f:
        f.write(SHARD_DRIVER.format(repo=repo, tmp=tmp))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["CTT_PROCESS_COUNT"] = "2"
    procs = []
    for pid in range(2):
        e = dict(env)
        e["CTT_PROCESS_ID"] = str(pid)
        procs.append(subprocess.Popen(
            [sys.executable, script], env=e,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
    outs = [p.communicate(timeout=300)[0].decode() for p in procs]
    for p, out in zip(procs, outs):
        assert p.returncode == 0, out[-2000:]

    # shards are self-describing (satellite: process identity)
    for pid in range(2):
        with open(os.path.join(tmp, f"trace_shard_p{pid}.json")) as f:
            sh = json.load(f)
        assert sh["process_index"] == pid
        assert sh["process_count"] == 2
        assert sh["spans"], sh

    with open(os.path.join(tmp, "merge_summary.json")) as f:
        m = json.load(f)
    assert m["n_processes"] == 2
    assert [p["pid"] for p in m["processes"]] == [1, 2]
    busy = {p["process_index"]: p["device_busy_s"]
            for p in m["processes"]}
    assert busy[0] >= 0.04 and busy[1] >= 0.09, busy
    # merged rollup aggregates device-busy across the mesh (each value
    # independently rounded to 4 decimals, so the sum drifts <= 2e-4)
    assert abs(m["rollups"]["device_busy_s"]
               - (busy[0] + busy[1])) < 2e-4
    assert m["rollups"]["memory"]["peak_host_rss_gb"] > 0
    # barrier-aligned anchors: offsets are small and the lead's is 0
    offs = [p["clock_offset_s"] for p in m["processes"]]
    assert min(offs) == 0.0 and max(offs) < 30.0, offs

    # one Perfetto-loadable trace with BOTH processes' pids
    with open(os.path.join(tmp, "merged_trace.json")) as f:
        events = json.load(f)["traceEvents"]
    assert {e["pid"] for e in events} == {1, 2}
    assert any(e["ph"] == "X" and e["name"] == "sync-execute"
               and e["pid"] == 2 for e in events)
    assert any(e["ph"] == "C" for e in events)   # memory counter tracks
