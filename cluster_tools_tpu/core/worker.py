"""Generic worker entrypoint for subprocess jobs.

Replaces the reference's script-copy + shebang-rewrite job materialization
(cluster_tasks.py:352-372): instead of copying each task's source file into
tmp and executing it, workers re-import the task class from the installed
package and run its ``process_job``.  Invoked as::

    python -m cluster_tools_tpu.core.worker <module> <class> <job_config.json>

stdout is the job log; success is signalled by the final "processed job %i"
line (reference protocol, utils/function_utils.py:11-16).
"""

from __future__ import annotations

import importlib
import importlib.util
import json
import sys


def main(argv) -> int:
    module_name, class_name, config_path = argv[:3]
    from .runtime import log, log_job_success

    with open(config_path) as f:
        job_config = json.load(f)
    job_id = job_config["job_id"]

    try:
        if module_name == "__main__":
            # the driver defined the task in its entry script; "__main__" here
            # is the worker itself, so force the source-file load below
            raise ModuleNotFoundError("__main__")
        module = importlib.import_module(module_name)
    except ModuleNotFoundError:
        # task class defined outside an importable package (e.g. a test file
        # or the user's driver script): load it from its source file, the
        # moral equivalent of the reference's copy-script-into-tmp job
        # materialization.  The module is loaded under a PRIVATE name —
        # loading a driver script as "__main__" would satisfy its
        # ``if __name__ == "__main__"`` guard and re-run the whole driver
        # (destructive setup included) inside every worker.
        src_file = job_config.get("src_file")
        if not src_file:
            raise
        load_name = ("_ctt_worker_driver" if module_name == "__main__"
                     else module_name)
        spec = importlib.util.spec_from_file_location(load_name, src_file)
        module = importlib.util.module_from_spec(spec)
        sys.modules[load_name] = module
        spec.loader.exec_module(module)
    task_cls = getattr(module, class_name)

    def log_fn(msg: str) -> None:
        log(msg)

    task_cls.process_job(job_id, job_config, log_fn)
    from .runtime import log_stage_times

    log_stage_times()
    log_job_success(job_id)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
