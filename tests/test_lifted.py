"""Lifted multicut stack tests: solver oracles, lifted-neighborhood BFS
oracle, and the end-to-end LiftedMulticutSegmentationWorkflow."""

import numpy as np
import pytest

from cluster_tools_tpu.core.storage import file_reader


def test_lifted_solver_known_instances():
    from cluster_tools_tpu import native

    # square 0-1-2-3-0, all local edges attractive; strong lifted repulsion
    # across the diagonal must cut the square (optimum: -8)
    uv = np.array([[0, 1], [1, 2], [2, 3], [3, 0]], "int64")
    c = np.ones(4)
    luv = np.array([[0, 2]], "int64")
    lc = np.array([-10.0])
    lab = native.lifted_multicut_kernighan_lin(4, uv, c, luv, lc)
    assert lab[0] != lab[2]
    assert native.lifted_objective(uv, c, luv, lc, lab) == -8.0

    # without lifted edges the lifted solver degrades to plain multicut
    lab = native.lifted_multicut_kernighan_lin(
        4, uv, c, np.zeros((0, 2), "int64"), np.zeros(0))
    assert len(np.unique(lab)) == 1

    # attractive lifted edge overcomes a weak local repulsion: chain 0-1-2
    # with local costs (+0.2, -0.1) and lifted 0-2 at +1.  After contracting
    # (0,1), the pair ({0,1}, 2) has priority -0.1 + 1.0 > 0 -> one cluster
    # (cutting would pay the lifted cost).
    uv = np.array([[0, 1], [1, 2]], "int64")
    c = np.array([0.2, -0.1])
    luv = np.array([[0, 2]], "int64")
    lc = np.array([1.0])
    lab = native.lifted_multicut_kernighan_lin(3, uv, c, luv, lc)
    assert len(np.unique(lab)) == 1
    # with a repulsive lifted edge instead, node 2 stays separate
    lab = native.lifted_multicut_kernighan_lin(
        3, uv, c, luv, np.array([-1.0]))
    assert lab[0] == lab[1] and lab[0] != lab[2]


def test_lifted_solver_beats_baselines_random():
    from cluster_tools_tpu import native

    rng = np.random.RandomState(1)
    for _ in range(5):
        n = 7
        uv = np.array([(i, j) for i in range(n) for j in range(i + 1, n)
                       if rng.rand() < 0.5], "int64")
        if len(uv) == 0:
            continue
        c = rng.randn(len(uv))
        luv = np.array([(i, j) for i in range(n) for j in range(i + 1, n)
                        if rng.rand() < 0.2], "int64")
        lc = rng.randn(len(luv)) * 2
        lab = native.lifted_multicut_kernighan_lin(n, uv, c, luv, lc)
        obj = native.lifted_objective(uv, c, luv, lc, lab)
        # must beat the trivial partitions
        all_one = np.zeros(n, "uint64")
        all_split = np.arange(n, dtype="uint64")
        assert obj <= native.lifted_objective(uv, c, luv, lc, all_one) + 1e-9
        assert obj <= native.lifted_objective(uv, c, luv, lc, all_split) + 1e-9


def test_lifted_neighborhood_bfs_oracle():
    from cluster_tools_tpu.workflows.lifted_features import (
        lifted_neighborhood)

    # path graph 0-1-2-3-4
    uv = np.array([[0, 1], [1, 2], [2, 3], [3, 4]], "int64")
    labels = np.array([1, 1, 2, 2, 1], "uint64")

    pairs = lifted_neighborhood(uv, 5, labels, graph_depth=2)
    assert sorted(map(tuple, pairs.tolist())) == [(0, 2), (1, 3), (2, 4)]

    pairs = lifted_neighborhood(uv, 5, labels, graph_depth=3)
    assert sorted(map(tuple, pairs.tolist())) == [
        (0, 2), (0, 3), (1, 3), (1, 4), (2, 4)]

    # mode filters
    pairs = lifted_neighborhood(uv, 5, labels, graph_depth=3, mode="same")
    same = set(map(tuple, pairs.tolist()))
    assert same == {(1, 4)}
    assert all(labels[a] == labels[b] for a, b in same)
    diff = set(map(tuple, lifted_neighborhood(
        uv, 5, labels, graph_depth=3, mode="different").tolist()))
    assert all(labels[a] != labels[b] for a, b in diff)
    assert same | diff == {(0, 2), (0, 3), (1, 3), (1, 4), (2, 4)}

    # ignore label: node 2 unlabeled -> no paths through it
    labels2 = np.array([1, 1, 0, 2, 2], "uint64")
    pairs = lifted_neighborhood(uv, 5, labels2, graph_depth=4)
    assert (2 not in pairs.ravel())
    # 0-1 and 3-4 components are disconnected without node 2: no cross pairs
    assert len(pairs) == 0


def test_lifted_segmentation_workflow(tmp_path, tmp_workdir):
    """E2E: semantic priors via lifted edges keep cells of different labels
    apart even where the boundary evidence is weak."""
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.workflows.segmentation import (
        LiftedMulticutSegmentationWorkflow)
    from tests.test_multicut import (_boundary_map, _check_recovery,
                                     _nested_voronoi)

    tmp_folder, config_dir = tmp_workdir
    true, frags = _nested_voronoi()
    bnd = _boundary_map(true)

    path = str(tmp_path / "data.n5")
    with file_reader(path) as f:
        f.create_dataset("bmap", data=bnd, chunks=(12, 12, 12))
        ds = f.create_dataset("ws", data=frags, chunks=(12, 12, 12))
        ds.attrs["maxId"] = int(frags.max())
        # semantic prior = the true cells themselves (the strongest prior)
        f.create_dataset("sem", data=true, chunks=(12, 12, 12))

    wf = LiftedMulticutSegmentationWorkflow(
        input_path=path, input_key="bmap", ws_path=path, ws_key="ws",
        labels_path=path, labels_key="sem",
        problem_path=str(tmp_path / "problem.n5"), output_path=path,
        output_key="seg", lifted_prefix="sem",
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="threads", n_scales=1, nh_graph_depth=3)
    assert ctt.build([wf], raise_on_failure=True)

    with file_reader(path, "r") as f:
        seg = f["seg"][:]
    _check_recovery(true, seg)


def test_agglomerative_clustering_workflow(tmp_path, tmp_workdir):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.workflows.segmentation import (
        AgglomerativeClusteringWorkflow)
    from tests.test_multicut import _boundary_map, _nested_voronoi

    tmp_folder, config_dir = tmp_workdir
    true, frags = _nested_voronoi()
    bnd = _boundary_map(true)
    path = str(tmp_path / "data.n5")
    with file_reader(path) as f:
        f.create_dataset("bmap", data=bnd, chunks=(12, 12, 12))
        f.create_dataset("ws", data=frags, chunks=(12, 12, 12))

    wf = AgglomerativeClusteringWorkflow(
        input_path=path, input_key="bmap", ws_path=path, ws_key="ws",
        problem_path=str(tmp_path / "problem.n5"), output_path=path,
        output_key="seg", threshold=0.5,
        tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="threads")
    assert ctt.build([wf], raise_on_failure=True)

    with file_reader(path, "r") as f:
        seg = f["seg"][:]
    # clustering below threshold 0.5 must merge fragments inside cells
    # (interior edges have ~0 boundary evidence) and not across (ridge = 1)
    n_frags = len(np.unique(frags))
    n_seg = len(np.unique(seg))
    assert n_seg < n_frags / 2
    # no merges across true boundaries for the bulk of voxels: each segment's
    # dominant true cell covers >= 95% of it
    for sid in np.unique(seg):
        cells, counts = np.unique(true[seg == sid], return_counts=True)
        assert counts.max() / counts.sum() > 0.95


def test_simple_stitching_workflow_e2e(tmp_path, tmp_workdir):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.workflows.segmentation import (
        SimpleStitchingWorkflow)
    from tests.test_stitching import _split_label_volume

    tmp_folder, config_dir = tmp_workdir
    shape, block_shape = (20, 20, 20), (10, 10, 10)
    truth, split = _split_label_volume(shape, block_shape, n_cells=3, seed=5)
    uniq = np.unique(split)
    split = np.searchsorted(uniq, split).astype("uint64") + 1
    bmap = np.zeros(shape, "float32")

    path = str(tmp_path / "data.n5")
    with file_reader(path) as f:
        f.create_dataset("ws", data=split, chunks=block_shape)
        f.create_dataset("bmap", data=bmap, chunks=block_shape)

    wf = SimpleStitchingWorkflow(
        input_path=path, input_key="bmap", ws_path=path, ws_key="ws",
        problem_path=str(tmp_path / "problem.n5"), output_path=path,
        output_key="seg", tmp_folder=tmp_folder, config_dir=config_dir,
        max_jobs=2, target="threads")
    assert ctt.build([wf], raise_on_failure=True)

    with file_reader(path, "r") as f:
        seg = f["seg"][:]
    # all fragments of one truth cell end up in one segment (no splits)
    for cell in np.unique(truth):
        assert len(np.unique(seg[truth == cell])) == 1
