"""Mesh-resident SPMD flagship (workflows/fused_pipeline._process_mesh):
one sharded program for the whole volume — halo exchange over the mesh,
collective label offsets, on-device cross-shard faces — VOI-compatible
with the blockwise path and dispatching exactly ONE compiled program."""

import json
import os
import shutil

import numpy as np
import pytest

from cluster_tools_tpu.core.storage import file_reader


def test_mesh_slab_block_shape():
    from cluster_tools_tpu.workflows.fused_pipeline import \
        mesh_slab_block_shape

    assert mesh_slab_block_shape((48, 128, 128), 4) == [12, 128, 128]
    assert mesh_slab_block_shape((50, 128, 128), 4) == [13, 128, 128]
    assert mesh_slab_block_shape((3, 8, 8), 8) == [1, 8, 8]


def test_mws_grid_edges_shard_local_origin():
    """ops/mws grid-edge extraction accepts shard-local origins: with
    ``id_offset`` the flat voxel ids shift into the global frame, so
    sharded callers concatenate shard windows without id collisions."""
    from cluster_tools_tpu.ops.mws import grid_graph_edges

    rng = np.random.RandomState(0)
    affs = rng.rand(3, 4, 5, 6).astype("float32")
    offsets = [[-1, 0, 0], [0, -1, 0], [0, 0, -1]]
    uva0, wa0, _, _ = grid_graph_edges(affs, offsets, impl="host")
    uva1, wa1, _, _ = grid_graph_edges(affs, offsets, impl="host",
                                       id_offset=1000)
    np.testing.assert_array_equal(uva1, uva0 + 1000)
    np.testing.assert_array_equal(wa1, wa0)
    uva2, wa2, _, _ = grid_graph_edges(affs, offsets, impl="device",
                                       id_offset=1000)
    np.testing.assert_array_equal(np.sort(uva2, axis=0),
                                  np.sort(uva1, axis=0))


@pytest.mark.slow
@pytest.mark.mesh
def test_mesh_program_rag_matches_host():
    """The sharded program's edge tables must union to EXACTLY the RAG of
    the labeled volume it emits — interior pairs per shard plus every
    cross-shard face pair once (the collective reduction replaces the
    host face scan, so nothing may be dropped or doubled)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from cluster_tools_tpu.ops.rag import host_label_pairs
    from cluster_tools_tpu.workflows.fused_pipeline import \
        _mesh_resident_program

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 devices")
    rng = np.random.RandomState(0)
    shape = (12, 16, 16)
    from scipy import ndimage

    vol = ndimage.gaussian_filter(rng.rand(*shape).astype("float32"), 2.0)
    vol = (vol - vol.min()) / (vol.max() - vol.min())
    vol_u8 = np.round(vol * 255).astype("uint8")

    n_shards, slab_z = 2, 6
    program, mesh = _mesh_resident_program(
        n_shards, slab_z, shape, (2, 4, 4), "uint8",
        0.5, 1.0, 1.0, 0.8, 5, 4096, 2, 1 << 14, 2)
    vol_dev = jax.device_put(vol_u8,
                             NamedSharding(mesh, P("shard", None, None)))
    lab_d, meta_d, uv_d, feats_d = program(vol_dev)
    meta = np.asarray(meta_d).astype("int64")
    assert meta[:, 4].all(), "watershed capacity"
    assert (meta[:, 2] == 0).all() and (meta[:, 3] == 0).all(), "overflow"
    lab = np.asarray(lab_d)
    ks = meta[:, 0]

    # labels globally consecutive, shard id ranges disjoint
    uniq = np.unique(lab)
    uniq = uniq[uniq > 0]
    np.testing.assert_array_equal(uniq, np.arange(1, ks.sum() + 1))
    offs = np.concatenate([[0], np.cumsum(ks)])
    for s in range(n_shards):
        sl = lab[s * slab_z:(s + 1) * slab_z]
        svals = np.unique(sl)
        svals = svals[svals > 0]
        assert (svals > offs[s]).all() and (svals <= offs[s + 1]).all()

    # union of shard tables == host RAG of the emitted label volume
    uv = np.asarray(uv_d).reshape(n_shards, -1, 2)
    got = np.concatenate([uv[s, :meta[s, 1]] for s in range(n_shards)])
    got = got[np.lexsort((got[:, 1], got[:, 0]))]
    want = host_label_pairs(lab.astype("uint64"))
    np.testing.assert_array_equal(got.astype("uint64"), want)
    # sample counts: every adjacent differing pair contributes 2 samples
    feats = np.asarray(feats_d).reshape(n_shards, -1, 10)
    cnt = np.concatenate([feats[s, :meta[s, 1], -1]
                          for s in range(n_shards)])
    assert (cnt >= 2).all() and cnt.sum() % 2 == 0


@pytest.mark.slow
@pytest.mark.mesh
def test_mesh_flagship_voi_parity(tmp_path):
    """Acceptance: the mesh-resident flagship on an emulated >= 4-device
    mesh is VOI-compatible (delta <= 0.01) with the blockwise path,
    dispatches exactly one compiled program per volume (EXEC_CACHE_STATS)
    with ONE steady-state sync-execute wait (vs one per block), and the
    problem container records the slab decomposition."""
    from scipy.spatial import cKDTree

    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core import runtime as rt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.utils.validation import (ContingencyTable,
                                                    cremi_score_from_table)

    import jax

    if len(jax.devices()) < 4:
        pytest.skip("needs >= 4 devices")

    rng = np.random.RandomState(0)
    shape = (48, 128, 128)
    pts = (rng.rand(12, 3) * np.array(shape)).astype("float32")
    tree = cKDTree(pts)
    grids = np.meshgrid(*[np.arange(s, dtype="float32") for s in shape],
                        indexing="ij")
    d, idx = tree.query(np.stack([g.ravel() for g in grids], 1), k=2)
    gt = (idx[:, 0] + 1).reshape(shape).astype("uint64")
    bnd = np.exp(-0.5 * ((d[:, 1] - d[:, 0]) / 2.0) ** 2).reshape(shape)

    path = str(tmp_path / "d.n5")
    block = [16, 64, 64]
    with file_reader(path) as f:
        ds = f.require_dataset("bmap", shape=shape, chunks=tuple(block),
                               dtype="uint8")
        ds[:] = np.round(bnd * 255).astype("uint8")

    def run(mode, tag):
        config_dir = str(tmp_path / f"configs_{tag}")
        cfg = ConfigDir(config_dir)
        cfg.write_global_config({"block_shape": block,
                                 "max_num_retries": 0})
        cfg.write_task_config("fused_segmentation", {
            "threshold": 0.4, "size_filter": 50, "halo": [2, 8, 8],
            "mesh_resident": mode == "mesh", "mesh_shards": 4})
        mc = ctt.MulticutSegmentationWorkflow(
            input_path=path, input_key="bmap", ws_path=path,
            ws_key=f"ws_{tag}", problem_path=str(tmp_path / f"p_{tag}.n5"),
            output_path=path, output_key=f"seg_{tag}",
            tmp_folder=str(tmp_path / f"tmp_{tag}"),
            config_dir=config_dir, max_jobs=2, target="tpu",
            n_scales=1, fused=True)
        assert ctt.build([mc], raise_on_failure=True)
        with file_reader(path, "r") as f:
            seg = f[f"seg_{tag}"][:]
        with open(str(tmp_path / f"tmp_{tag}" /
                      "fused_segmentation.status")) as f:
            status = json.load(f)
        return seg, status

    before = dict(rt.EXEC_CACHE_STATS)
    seg_b, st_b = run("block", "block")
    seg_m, st_m = run("mesh", "mesh")

    # exactly ONE sharded program compiled for the volume, ONE
    # steady-state wait (the blockwise path waits once per block)
    waits_m = st_m["stage_counts"]["sync-execute"]
    waits_b = st_b["stage_counts"]["sync-execute"]
    assert waits_m == 1 and waits_b > 1, (waits_m, waits_b)

    # warm re-run: zero additional compiles, pure cache hit — and the
    # per-task exec_cache telemetry in the status JSON says so too
    mid = dict(rt.EXEC_CACHE_STATS)
    seg_m2, st_m2 = run("mesh", "mesh2")
    after = dict(rt.EXEC_CACHE_STATS)
    assert after["compiles"] == mid["compiles"]
    assert after["hits"] > mid["hits"]
    np.testing.assert_array_equal(seg_m2, seg_m)
    assert st_m2["exec_cache"].get("compiles", 0) == 0, st_m2["exec_cache"]
    assert st_m2["exec_cache"].get("hits", 0) >= 1, st_m2["exec_cache"]
    assert st_m["exec_cache"].get("compiles", 0) >= 1, st_m["exec_cache"]

    # the problem container records the slab decomposition
    with file_reader(str(tmp_path / "p_mesh.n5"), "r") as f:
        assert list(f["s0/graph"].attrs["sub_graph_block_shape"]) == \
            [12, 128, 128]

    def voi(seg):
        t = ContingencyTable.from_arrays_chunked(gt, seg)
        vs, vm, are, _ = cremi_score_from_table(t)
        return vs + vm, are

    v_b, r_b = voi(seg_b)
    v_m, r_m = voi(seg_m)
    assert r_b < 0.1 and r_m < 0.1, (r_b, r_m)
    assert abs(v_b - v_m) <= 0.01, (v_b, v_m)
