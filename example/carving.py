"""Pixel classification + carving-project export example (reference:
example/ilastik — headless ilastik prediction and carving .ilp export).

The TPU framework replaces the external ilastik binary with first-party
device filter banks + an RF pixel classifier, then exports the
graph/edge-weight carving project directly:

    python example/carving.py /tmp/ctt_carving
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_data(path, shape=(32, 64, 64)):
    """Raw volume with two intensity phases + a sparse scribble labeling."""
    from cluster_tools_tpu.core.storage import file_reader

    rng = np.random.RandomState(0)
    raw = rng.rand(*shape).astype("float32") * 0.2
    raw[:, : shape[1] // 2] += 0.6  # bright phase
    scribbles = np.zeros(shape, "uint8")
    scribbles[4:8, 4:8, 4:8] = 1        # class 1: bright
    scribbles[4:8, -8:-4, 4:8] = 2      # class 2: dark
    with file_reader(path) as f:
        f.create_dataset("raw", data=raw, chunks=[16, 32, 32])
        f.create_dataset("scribbles", data=scribbles, chunks=[16, 32, 32])


def main(workdir):
    import cluster_tools_tpu as ctt
    from cluster_tools_tpu.core.config import ConfigDir
    from cluster_tools_tpu.core.storage import file_reader
    from cluster_tools_tpu.workflows.features import EdgeFeaturesWorkflow
    from cluster_tools_tpu.workflows.graph import GraphWorkflow
    from cluster_tools_tpu.workflows.pixel_classification import (
        PixelClassificationWorkflow, WriteCarving)
    from cluster_tools_tpu.workflows.watershed import WatershedWorkflow

    os.makedirs(workdir, exist_ok=True)
    data = os.path.join(workdir, "data.n5")
    config_dir = os.path.join(workdir, "configs")
    tmp = os.path.join(workdir, "tmp")
    make_data(data)
    ConfigDir(config_dir).write_global_config({"block_shape": [16, 32, 32]})

    # 1. pixel classification: scribbles -> per-class probabilities
    pc = PixelClassificationWorkflow(
        input_path=data, input_key="raw", labels_path=data,
        labels_key="scribbles", output_path=data, output_key="pred",
        n_classes=2, tmp_folder=tmp, config_dir=config_dir,
        max_jobs=4, target="local")
    assert ctt.build([pc])

    # 2. fragments + graph + edge weights over the boundary-ish channel
    ws = WatershedWorkflow(
        input_path=data, input_key="raw", output_path=data,
        output_key="ws", tmp_folder=tmp, config_dir=config_dir,
        max_jobs=4, target="local")
    graph_path = os.path.join(workdir, "graph.n5")
    gw = GraphWorkflow(
        input_path=data, input_key="ws", graph_path=graph_path,
        tmp_folder=tmp, config_dir=config_dir, max_jobs=4,
        target="local", dependency=ws)
    fw = EdgeFeaturesWorkflow(
        input_path=data, input_key="raw", labels_path=data,
        labels_key="ws", graph_path=graph_path, output_path=graph_path,
        tmp_folder=tmp, config_dir=config_dir, max_jobs=4,
        target="local", dependency=gw)
    assert ctt.build([fw])

    # 3. export the interactive carving project
    ilp = os.path.join(workdir, "carving.ilp")
    carve = WriteCarving(
        graph_path=graph_path, graph_key="graph",
        features_path=graph_path, features_key="features",
        output_path=ilp, raw_path=data, raw_key="raw",
        uid="ctt-example", tmp_folder=tmp)
    assert ctt.build([carve])

    import h5py

    with h5py.File(ilp, "r") as f:
        n_nodes = f["preprocessing/graph"].attrs["numNodes"]
        n_weights = len(f["preprocessing/graph/edgeWeights"])
    with file_reader(data, "r") as f:
        pred_shape = f["pred"].shape
    print(f"prediction channels: {pred_shape}")
    print(f"carving project: {n_nodes} nodes, {n_weights} edge weights "
          f"-> {ilp}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else "/tmp/ctt_carving")
