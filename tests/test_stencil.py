"""parallel/stencil.py: halo_exchange vs a host-side pad/roll reference on
2- and 4-device emulated meshes, non-periodic boundary handling, and the
crop_halo round-trip (the ppermute ring the mesh-resident flagship rides)."""

import numpy as np
import pytest

pytestmark = pytest.mark.mesh


def _host_halo_reference(x, halo, axis, n_shards, mode="constant", fill=0):
    """What the sharded exchange must produce, computed with plain numpy:
    split the global array into shards along ``axis``, grow each with its
    true neighbors' boundary slabs, and pad the outer volume borders."""
    shards = np.split(x, n_shards, axis=axis)
    out = []
    for i, s in enumerate(shards):
        if i > 0:
            lo = np.take(shards[i - 1],
                         range(shards[i - 1].shape[axis] - halo,
                               shards[i - 1].shape[axis]), axis=axis)
        else:
            # volume low border: numpy-style reflect EXCLUDES the border
            # plane (np.pad mode='reflect'), constant uses fill
            lo_own = np.take(s, range(1, halo + 1), axis=axis)
            lo = (np.flip(lo_own, axis=axis) if mode == "reflect"
                  else np.full_like(lo_own, fill))
        if i < n_shards - 1:
            hi = np.take(shards[i + 1], range(halo), axis=axis)
        else:
            n_ax = s.shape[axis]
            hi_own = np.take(s, range(n_ax - halo - 1, n_ax - 1),
                             axis=axis)
            hi = (np.flip(hi_own, axis=axis) if mode == "reflect"
                  else np.full_like(hi_own, fill))
        out.append(np.concatenate([lo, s, hi], axis=axis))
    return out


def _run_exchange(x, halo, axis, n_shards, mode="constant", fill=0):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from cluster_tools_tpu.parallel.mesh import single_axis_mesh
    from cluster_tools_tpu.parallel.stencil import halo_exchange

    try:
        from jax import shard_map
        kw = {"check_vma": False}
    except ImportError:
        from jax.experimental.shard_map import shard_map
        kw = {"check_rep": False}

    mesh = single_axis_mesh("space", n_shards)
    spec = [None] * x.ndim
    spec[axis] = "space"
    sp = P(*spec)

    def local(s):
        return halo_exchange(s, halo, axis, "space", mode=mode, fill=fill)

    grown = shard_map(local, mesh=mesh, in_specs=(sp,), out_specs=sp,
                      **kw)(jnp.asarray(x))
    # shard_map concatenates the per-shard outputs along the sharded axis
    return np.split(np.asarray(grown), n_shards, axis=axis)


@pytest.mark.parametrize("n_shards", [2, 4])
@pytest.mark.parametrize("mode", ["constant", "reflect"])
def test_halo_exchange_matches_host_reference(n_shards, mode):
    rng = np.random.RandomState(0)
    x = rng.rand(8 * n_shards, 5, 6).astype("float32")
    halo = 2
    got = _run_exchange(x, halo, 0, n_shards, mode=mode, fill=0.0)
    want = _host_halo_reference(x, halo, 0, n_shards, mode=mode, fill=0.0)
    assert len(got) == n_shards
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)


def test_halo_exchange_nonperiodic_fill():
    """The first/last shards must see the FILL value, never the ring
    wrap-around of the opposite volume end."""
    n_shards, halo = 4, 3
    x = np.arange(4 * n_shards * 4, dtype="float32").reshape(4 * n_shards, 4)
    got = _run_exchange(x, halo, 0, n_shards, mode="constant", fill=-1.0)
    assert (got[0][:halo] == -1.0).all()
    assert (got[-1][-halo:] == -1.0).all()
    # and the interior halos are the true neighbors, not fill
    assert (got[1][:halo] == x[4 - halo:4]).all()


def test_crop_halo_round_trip():
    import jax.numpy as jnp

    from cluster_tools_tpu.parallel.stencil import crop_halo

    rng = np.random.RandomState(1)
    x = rng.rand(16, 6, 7).astype("float32")
    n_shards, halo = 4, 2
    grown = _run_exchange(x, halo, 0, n_shards)
    shards = np.split(x, n_shards, axis=0)
    for g, s in zip(grown, shards):
        back = np.asarray(crop_halo(jnp.asarray(g), halo, 0))
        np.testing.assert_array_equal(back, s)
    # halo=0 is the identity
    np.testing.assert_array_equal(
        np.asarray(crop_halo(jnp.asarray(x), 0, 0)), x)


def test_sharded_stencil_matches_dense():
    """sharded_stencil (exchange -> local fn -> crop) == the same stencil
    applied to the full array (away from the volume borders)."""
    import jax.numpy as jnp

    from cluster_tools_tpu.parallel.mesh import single_axis_mesh
    from cluster_tools_tpu.parallel.stencil import sharded_stencil

    rng = np.random.RandomState(2)
    x = rng.rand(16, 5, 5).astype("float32")

    def box3(a):  # 3-point mean along axis 0
        return (jnp.roll(a, 1, 0) + a + jnp.roll(a, -1, 0)) / 3.0

    mesh = single_axis_mesh("space", 4)
    f = sharded_stencil(box3, mesh, halo=1, axis=0, mesh_axis="space",
                        fill=0.0)
    got = np.asarray(f(jnp.asarray(x)))
    want = np.asarray(box3(jnp.asarray(x)))
    # interior rows see identical neighborhoods; border rows differ by
    # design (fill vs wrap), so compare away from the volume ends
    np.testing.assert_allclose(got[1:-1], want[1:-1], rtol=1e-6)
