"""Flagship model + sharded training step (8-device virtual mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_unet_forward_shape():
    from cluster_tools_tpu.models.unet import create_unet

    model = create_unet(out_channels=3, features=(4, 8), anisotropic=False)
    x = jnp.zeros((1, 8, 16, 16, 1), jnp.float32)
    params = model.init(jax.random.PRNGKey(0), x)
    out = jax.jit(model.apply)(params, x)
    assert out.shape == (1, 8, 16, 16, 3)
    assert np.all((np.array(out) >= 0) & (np.array(out) <= 1))  # sigmoid


def test_mesh_factorization():
    from cluster_tools_tpu.parallel.mesh import _factorize

    assert _factorize(8) == (2, 2, 2)
    assert _factorize(4) == (2, 2, 1)
    assert _factorize(2) == (2, 1, 1)
    assert _factorize(1) == (1, 1, 1)
    assert np.prod(_factorize(6)) == 6


@pytest.mark.mesh
def test_sharded_train_step_runs_and_learns():
    from cluster_tools_tpu.models.train import train_step_for_mesh

    jitted, state, (x, y) = train_step_for_mesh(
        n_devices=8, features=(4, 8), shape=(2, 8, 16, 16))
    state, loss0 = jitted(state, x, y)
    for _ in range(3):
        state, loss = jitted(state, x, y)
    assert np.isfinite(float(loss0))
    assert float(loss) < float(loss0)  # optimizer is actually stepping


def test_halo_exchange_matches_padded_stencil():
    """sharded_stencil(mean filter) == the same stencil on the full array."""
    from jax.sharding import Mesh

    from cluster_tools_tpu.parallel.stencil import sharded_stencil

    devices = np.array(jax.devices()[:4])
    mesh = Mesh(devices, ("space",))

    def local_mean(x):  # 3-tap mean along axis 0
        return (jnp.roll(x, 1, 0) + x + jnp.roll(x, -1, 0)) / 3.0

    rng = np.random.RandomState(0)
    full = rng.rand(16, 5).astype(np.float32)

    apply = sharded_stencil(lambda x: local_mean(x), mesh, halo=1, axis=0,
                            mesh_axis="space")
    out = np.array(apply(jnp.asarray(full)))

    padded = np.pad(full, ((1, 1), (0, 0)))
    expect = (padded[:-2] + padded[1:-1] + padded[2:]) / 3.0
    np.testing.assert_allclose(out, expect, rtol=1e-6)


@pytest.mark.slow
@pytest.mark.mesh
def test_sharded_train_state_checkpoint_roundtrip(tmp_path):
    """Orbax train-state checkpointing over the 8-device mesh: save the
    sharded state after one step, restore onto the same shardings, and
    confirm bit-identical params plus the ability to keep training."""
    import jax

    from cluster_tools_tpu.models.checkpoint import (restore_train_state,
                                                     save_train_state)
    from cluster_tools_tpu.models.train import train_step_for_mesh

    jitted, state, (x, y) = train_step_for_mesh(n_devices=8)
    state1, loss1 = jitted(state, x, y)
    jax.block_until_ready(loss1)

    path = str(tmp_path / "train_ckpt")
    save_train_state(path, state1)

    abstract = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=a.sharding),
        state1)
    restored = restore_train_state(path, abstract)

    flat1 = jax.tree_util.tree_leaves(state1.params)
    flat2 = jax.tree_util.tree_leaves(restored.params)
    for a, b in zip(flat1, flat2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(restored.step) == int(state1.step)

    # training continues from the restored state with identical dynamics
    s_a, loss_a = jitted(state1, x, y)
    s_b, loss_b = jitted(restored, x, y)
    np.testing.assert_allclose(float(loss_a), float(loss_b), rtol=1e-6)
