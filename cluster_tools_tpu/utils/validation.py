"""Segmentation-quality metrics: VI, adapted Rand, CREMI score, object VI.

Re-specification of the reference's pure-python metric math
(cluster_tools/utils/validation_utils.py:9-273) with two differences:

* the contingency table is computed by vectorized key-packing + ``np.unique``
  (or on device via ops/overlaps.py) instead of a per-id C++ overlap loop;
* the VI / Rand primitives are vectorized numpy expressions instead of
  python generator sums.

API signatures and return conventions follow the reference exactly:
``variation_of_information(seg, gt) -> (vi_split, vi_merge)``,
``rand_index(seg, gt) -> (adapted_rand_error, rand_index)``,
``cremi_score(seg, gt) -> (vis, vim, are, cremi)``.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import numpy as np


# ---------------------------------------------------------------------------
# contingency tables
# ---------------------------------------------------------------------------

class ContingencyTable:
    """Sparse contingency table between label images A and B.

    ``p_ids`` is (N, 2) uint64 of co-occurring (a, b) label pairs, ``p_counts``
    the voxel count per pair; ``a_ids``/``a_counts`` (and b) are the marginal
    label sizes.  ``n_points`` is the total voxel count.
    """

    def __init__(self, p_ids: np.ndarray, p_counts: np.ndarray):
        self.p_ids = np.asarray(p_ids, dtype="uint64").reshape(-1, 2)
        self.p_counts = np.asarray(p_counts, dtype="float64")
        if len(self.p_ids) != len(self.p_counts):
            raise ValueError("pair ids and counts disagree in length")
        self.a_ids, inv_a = np.unique(self.p_ids[:, 0], return_inverse=True)
        self.a_counts = np.bincount(inv_a, weights=self.p_counts,
                                    minlength=len(self.a_ids))
        self.b_ids, inv_b = np.unique(self.p_ids[:, 1], return_inverse=True)
        self.b_counts = np.bincount(inv_b, weights=self.p_counts,
                                    minlength=len(self.b_ids))
        self._inv_a = inv_a
        self._inv_b = inv_b
        self.n_points = float(self.p_counts.sum())

    @classmethod
    def from_arrays(cls, seg_a: np.ndarray, seg_b: np.ndarray,
                    on_device: bool = False) -> "ContingencyTable":
        a = np.asarray(seg_a).ravel().astype("uint64")
        b = np.asarray(seg_b).ravel().astype("uint64")
        if a.shape != b.shape:
            raise ValueError("segmentations must have the same size")
        if a.size == 0:
            return cls(np.zeros((0, 2), "uint64"), np.zeros(0, "float64"))
        if on_device:
            from ..ops.overlaps import count_overlaps

            ia, ib, counts = count_overlaps(a, b)
            return cls(np.stack([ia, ib], axis=1), counts.astype("float64"))
        if a.max() < 2 ** 32 and b.max() < 2 ** 32:
            key = (a << np.uint64(32)) | b
            uniq, counts = np.unique(key, return_counts=True)
            p_ids = np.stack([uniq >> np.uint64(32),
                              uniq & np.uint64(0xFFFFFFFF)], axis=1)
        else:
            p_ids, counts = np.unique(np.stack([a, b], axis=1), axis=0,
                                      return_counts=True)
        return cls(p_ids, counts.astype("float64"))

    @classmethod
    def from_arrays_chunked(cls, seg_a, seg_b,
                            chunk: int = 1 << 24) -> "ContingencyTable":
        """Streaming variant of :func:`from_arrays`: the inputs are
        consumed in flat chunks (cast per chunk — callers can keep their
        narrow dtypes), so peak memory is O(chunk + unique pairs) instead
        of several full-volume uint64 temporaries.  Labels must fit 32
        bits (use from_arrays for the >4G-label edge case)."""
        a = np.asarray(seg_a).reshape(-1)
        b = np.asarray(seg_b).reshape(-1)
        if a.shape != b.shape:
            raise ValueError("segmentations must have the same size")
        keys_parts = []
        counts_parts = []
        for lo in range(0, a.size, chunk):
            aa = a[lo:lo + chunk].astype("uint64")
            bb = b[lo:lo + chunk].astype("uint64")
            if aa.size and (aa.max() >= 2 ** 32 or bb.max() >= 2 ** 32):
                raise ValueError("labels exceed 32 bits; use from_arrays")
            key = (aa << np.uint64(32)) | bb
            uniq, cnt = np.unique(key, return_counts=True)
            keys_parts.append(uniq)
            counts_parts.append(cnt.astype("float64"))
        if not keys_parts:
            return cls(np.zeros((0, 2), "uint64"), np.zeros(0, "float64"))
        keys = np.concatenate(keys_parts)
        cnts = np.concatenate(counts_parts)
        uniq, inv = np.unique(keys, return_inverse=True)
        counts = np.zeros(len(uniq), "float64")
        np.add.at(counts, inv, cnts)
        p_ids = np.stack([uniq >> np.uint64(32),
                          uniq & np.uint64(0xFFFFFFFF)], axis=1)
        return cls(p_ids, counts)

    def drop_pairs(self, mask: np.ndarray) -> "ContingencyTable":
        keep = ~np.asarray(mask, bool)
        return ContingencyTable(self.p_ids[keep], self.p_counts[keep])


def compute_ignore_mask(seg_a, seg_b, ignore_a, ignore_b) -> Optional[np.ndarray]:
    """Voxel mask selecting the points that enter the metrics (reference:
    validation_utils.py:38-53): voxels ignored in *both* inputs (or in the
    single given one) are excluded."""
    if ignore_a is None and ignore_b is None:
        return None
    mask_a = None if ignore_a is None else np.isin(seg_a, ignore_a)
    mask_b = None if ignore_b is None else np.isin(seg_b, ignore_b)
    if mask_a is None:
        ignore = mask_b
    elif mask_b is None:
        ignore = mask_a
    else:
        ignore = np.logical_and(mask_a, mask_b)
    return np.logical_not(ignore)


def drop_ignored_pairs(table: ContingencyTable,
                       ignore_a: Optional[Sequence[int]] = None,
                       ignore_b: Optional[Sequence[int]] = None
                       ) -> ContingencyTable:
    """Pair-level form of :func:`compute_ignore_mask`: each (a, b) pair stands
    for an exact voxel set, so dropping pairs ignored in both inputs (or in
    the single given one) is equivalent to voxel masking."""
    if ignore_a is None and ignore_b is None:
        return table
    in_a = (np.isin(table.p_ids[:, 0], np.asarray(ignore_a, "uint64"))
            if ignore_a is not None else None)
    in_b = (np.isin(table.p_ids[:, 1], np.asarray(ignore_b, "uint64"))
            if ignore_b is not None else None)
    if in_a is None:
        drop = in_b
    elif in_b is None:
        drop = in_a
    else:
        drop = in_a & in_b
    return table.drop_pairs(drop)


def _table_with_ignore(segmentation, groundtruth, ignore_seg, ignore_gt
                       ) -> ContingencyTable:
    """Contingency of (gt, seg) with the reference's ignore semantics."""
    mask = compute_ignore_mask(segmentation, groundtruth, ignore_seg, ignore_gt)
    seg = np.asarray(segmentation).ravel()
    gt = np.asarray(groundtruth).ravel()
    if mask is not None:
        mask = mask.ravel()
        seg, gt = seg[mask], gt[mask]
    return ContingencyTable.from_arrays(gt, seg)


# ---------------------------------------------------------------------------
# VI (reference: validation_utils.py:60-113)
# ---------------------------------------------------------------------------

def compute_vi_scores(table: ContingencyTable, use_log2: bool = True
                      ) -> Tuple[float, float]:
    """(vi_split, vi_merge) from a contingency table of (gt=A, seg=B)."""
    log = np.log2 if use_log2 else np.log
    n = table.n_points
    if n == 0:
        return 0.0, 0.0
    pa = table.a_counts / n
    pb = table.b_counts / n
    sum_a = float(-(pa * log(pa)).sum())
    sum_b = float(-(pb * log(pb)).sum())
    c = table.p_counts
    sum_ab = float(np.sum(
        c / n * log(n * c / (table.a_counts[table._inv_a]
                             * table.b_counts[table._inv_b]))))
    vi_split = sum_b - sum_ab
    vi_merge = sum_a - sum_ab
    return vi_split, vi_merge


def variation_of_information(segmentation, groundtruth, ignore_seg=None,
                             ignore_gt=None, use_log2: bool = True
                             ) -> Tuple[float, float]:
    table = _table_with_ignore(segmentation, groundtruth, ignore_seg, ignore_gt)
    return compute_vi_scores(table, use_log2=use_log2)


def compute_object_vi_scores(table: ContingencyTable, use_log2: bool = True
                             ) -> Dict[int, Tuple[float, float]]:
    """Per-gt-object (vi_split, vi_merge) (reference:
    validation_utils.py:116-134, after arXiv:1708.02599 p.16)."""
    log = np.log2 if use_log2 else np.log
    gt_sizes = table.a_counts[table._inv_a]
    seg_sizes = table.b_counts[table._inv_b]
    c = table.p_counts
    vim_terms = -c / gt_sizes * log(c / gt_sizes)
    vis_terms = -c / gt_sizes * log(c / seg_sizes)
    vim = np.bincount(table._inv_a, weights=vim_terms,
                      minlength=len(table.a_ids))
    vis = np.bincount(table._inv_a, weights=vis_terms,
                      minlength=len(table.a_ids))
    return {int(gt_id): (float(s), float(m))
            for gt_id, s, m in zip(table.a_ids, vis, vim)}


def object_vi(segmentation, groundtruth, ignore_seg=None, ignore_gt=None,
              use_log2: bool = True) -> Dict[int, Tuple[float, float]]:
    table = _table_with_ignore(segmentation, groundtruth, ignore_seg, ignore_gt)
    return compute_object_vi_scores(table, use_log2=use_log2)


# ---------------------------------------------------------------------------
# Rand (reference: validation_utils.py:178-231)
# ---------------------------------------------------------------------------

def compute_rand_scores(table: ContingencyTable) -> Tuple[float, float]:
    """(adapted_rand_error, rand_index) from a (gt, seg) contingency table."""
    n = table.n_points
    if n == 0:
        return 0.0, 1.0
    sum_a = float((table.a_counts ** 2).sum())
    sum_b = float((table.b_counts ** 2).sum())
    sum_ab = float((table.p_counts ** 2).sum())
    prec = sum_ab / sum_b
    rec = sum_ab / sum_a
    ari = 1.0 - (2 * prec * rec) / (prec + rec)
    ri = 1.0 - (sum_a + sum_b - 2 * sum_ab) / (n * n)
    return ari, ri


def rand_index(segmentation, groundtruth, ignore_seg=None, ignore_gt=None
               ) -> Tuple[float, float]:
    table = _table_with_ignore(segmentation, groundtruth, ignore_seg, ignore_gt)
    return compute_rand_scores(table)


# ---------------------------------------------------------------------------
# CREMI score (reference: validation_utils.py:234-273)
# ---------------------------------------------------------------------------

def cremi_score_from_table(table: ContingencyTable
                           ) -> Tuple[float, float, float, float]:
    """(vi_split, vi_merge, adapted_rand_error, cremi) from a (gt, seg)
    contingency table; cremi = sqrt(are * (vis + vim))."""
    vis, vim = compute_vi_scores(table, use_log2=True)
    ari, _ = compute_rand_scores(table)
    cs = float(np.sqrt(ari * (vis + vim)))
    return vis, vim, ari, cs


def cremi_score(segmentation, groundtruth, ignore_seg=None, ignore_gt=None
                ) -> Tuple[float, float, float, float]:
    table = _table_with_ignore(segmentation, groundtruth, ignore_seg, ignore_gt)
    return cremi_score_from_table(table)
