// Native combinatorial kernels for the TPU framework.
//
// The reference delegates these to external pybind11 wheels (nifty's
// Kernighan-Lin / greedy-additive multicut, boost union-find, affogato's
// mutex watershed -- SURVEY.md section 2.3).  Combinatorial, data-dependent
// algorithms do not map onto the MXU, so they live here as first-party C++
// with a flat extern "C" array API loaded via ctypes (no pybind11 in the
// image).  The device side produces the edge lists; these kernels consume
// them on the host CPU.
//
// Build: g++ -O3 -march=native -shared -fPIC solvers.cpp -o libctt_native.so

#include <algorithm>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <queue>
#include <tuple>
#include <unordered_map>
#include <unordered_set>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// union-find with path halving + union by size
// ---------------------------------------------------------------------------
struct Ufd {
    std::vector<int64_t> parent;
    std::vector<int64_t> size;
    explicit Ufd(int64_t n) : parent(n), size(n, 1) {
        std::iota(parent.begin(), parent.end(), 0);
    }
    int64_t find(int64_t x) {
        while (parent[x] != x) {
            parent[x] = parent[parent[x]];
            x = parent[x];
        }
        return x;
    }
    // returns the surviving root, or -1 if already joined
    int64_t merge(int64_t a, int64_t b) {
        a = find(a);
        b = find(b);
        if (a == b) return -1;
        if (size[a] < size[b]) std::swap(a, b);
        parent[b] = a;
        size[a] += size[b];
        return a;
    }
};

// multicut objective: sum of costs over cut edges (minimized)
double objective(int64_t n_edges, const int64_t* uv, const double* costs,
                 const uint64_t* labels) {
    double e = 0.0;
    for (int64_t i = 0; i < n_edges; ++i) {
        if (labels[uv[2 * i]] != labels[uv[2 * i + 1]]) e += costs[i];
    }
    return e;
}

}  // namespace

extern "C" {

// ---------------------------------------------------------------------------
// union-find over pair lists (boost_ufd replacement,
// reference: multicut/reduce_problem.py:161, thresholded_components)
// ---------------------------------------------------------------------------
// labels_out[i] = root of node i after merging all pairs.
void ufd_merge_pairs(int64_t n_nodes, int64_t n_pairs, const int64_t* pairs,
                     uint64_t* labels_out) {
    Ufd ufd(n_nodes);
    for (int64_t i = 0; i < n_pairs; ++i) {
        ufd.merge(pairs[2 * i], pairs[2 * i + 1]);
    }
    for (int64_t i = 0; i < n_nodes; ++i) {
        labels_out[i] = static_cast<uint64_t>(ufd.find(i));
    }
}

// ---------------------------------------------------------------------------
// greedy additive edge contraction (GAEC)
// (nifty.graph.opt.multicut greedyAdditive replacement)
// ---------------------------------------------------------------------------
// Contract the most attractive (largest positive accumulated cost) edge until
// none remains.  Dynamic graph as per-node hash maps, lazy priority queue.
// labels_out: dense component labels in [0, n_components).
int64_t mc_gaec(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                const double* costs, uint64_t* labels_out) {
    std::vector<std::unordered_map<int64_t, double>> adj(n_nodes);
    for (int64_t i = 0; i < n_edges; ++i) {
        int64_t u = uv[2 * i], v = uv[2 * i + 1];
        if (u == v) continue;
        adj[u][v] += costs[i];
        adj[v][u] += costs[i];
    }
    using Entry = std::tuple<double, int64_t, int64_t>;  // (w, u, v)
    std::priority_queue<Entry> pq;
    for (int64_t u = 0; u < n_nodes; ++u) {
        for (const auto& kv : adj[u]) {
            if (kv.first > u && kv.second > 0) pq.emplace(kv.second, u, kv.first);
        }
    }
    Ufd ufd(n_nodes);
    while (!pq.empty()) {
        auto [w, u, v] = pq.top();
        pq.pop();
        if (w <= 0) break;
        int64_t ru = ufd.find(u), rv = ufd.find(v);
        // stale entry: nodes already merged or weight changed
        if (ru == rv) continue;
        auto it = adj[ru].find(rv);
        if (it == adj[ru].end() || it->second != w || u != std::min(ru, rv) ||
            v != std::max(ru, rv)) {
            // re-push the current live pair if still attractive
            if (it != adj[ru].end() && it->second > 0) {
                pq.emplace(it->second, std::min(ru, rv), std::max(ru, rv));
            }
            continue;
        }
        // contract rv into ru (keep the larger adjacency)
        if (adj[ru].size() < adj[rv].size()) std::swap(ru, rv);
        int64_t rw = ufd.merge(ru, rv);
        if (rw != ru) std::swap(ru, rv);  // ufd chose the other root
        adj[ru].erase(rv);
        adj[rv].erase(ru);
        for (const auto& kv : adj[rv]) {
            int64_t n = kv.first;
            double nw = kv.second;
            adj[n].erase(rv);
            double& acc = adj[ru][n];
            acc += nw;
            adj[n][ru] = acc;
            if (acc > 0) pq.emplace(acc, std::min(ru, n), std::max(ru, n));
        }
        adj[rv].clear();
    }
    // dense component labels
    std::unordered_map<int64_t, uint64_t> remap;
    uint64_t next = 0;
    for (int64_t i = 0; i < n_nodes; ++i) {
        int64_t r = ufd.find(i);
        auto it = remap.find(r);
        if (it == remap.end()) it = remap.emplace(r, next++).first;
        labels_out[i] = it->second;
    }
    return static_cast<int64_t>(next);
}

// ---------------------------------------------------------------------------
// Kernighan-Lin-style greedy node moves
// (nifty multicutKernighanLin replacement: local search with joins)
// ---------------------------------------------------------------------------
// Improve labels_inout by repeatedly moving single nodes to the neighboring
// component (or a fresh singleton) with the best objective gain, until a full
// pass yields no improvement or max_passes is hit.  Returns passes used.
int64_t mc_kl_refine(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                     const double* costs, uint64_t* labels, int64_t max_passes,
                     double time_limit) {
    const auto deadline = std::chrono::steady_clock::now() +
        std::chrono::duration<double>(time_limit > 0 ? time_limit : 1e18);
    // CSR adjacency
    std::vector<int64_t> deg(n_nodes, 0);
    for (int64_t i = 0; i < n_edges; ++i) {
        ++deg[uv[2 * i]];
        ++deg[uv[2 * i + 1]];
    }
    std::vector<int64_t> off(n_nodes + 1, 0);
    for (int64_t i = 0; i < n_nodes; ++i) off[i + 1] = off[i] + deg[i];
    std::vector<int64_t> nbr(off[n_nodes]);
    std::vector<double> nw(off[n_nodes]);
    std::vector<int64_t> cur(off.begin(), off.end() - 1);
    for (int64_t i = 0; i < n_edges; ++i) {
        int64_t u = uv[2 * i], v = uv[2 * i + 1];
        nbr[cur[u]] = v;
        nw[cur[u]++] = costs[i];
        nbr[cur[v]] = u;
        nw[cur[v]++] = costs[i];
    }
    uint64_t next_label = 0;
    for (int64_t i = 0; i < n_nodes; ++i) next_label = std::max(next_label, labels[i] + 1);

    std::unordered_map<uint64_t, double> comp_w;
    int64_t pass = 0;
    for (; pass < max_passes; ++pass) {
        if (std::chrono::steady_clock::now() > deadline) break;
        bool improved = false;
        for (int64_t x = 0; x < n_nodes; ++x) {
            if (off[x + 1] == off[x]) continue;
            comp_w.clear();
            for (int64_t j = off[x]; j < off[x + 1]; ++j) {
                comp_w[labels[nbr[j]]] += nw[j];
            }
            uint64_t own = labels[x];
            double w_own = 0.0;
            auto it_own = comp_w.find(own);
            if (it_own != comp_w.end()) w_own = it_own->second;
            // candidate: fresh singleton (gain = w_own if w_own < 0)
            double best_gain = -w_own;  // delta objective of leaving to empty
            uint64_t best_label = next_label;
            for (const auto& kv : comp_w) {
                if (kv.first == own) continue;
                double gain = kv.second - w_own;  // uncut B, cut own
                if (gain > best_gain + 1e-12) {
                    best_gain = gain;
                    best_label = kv.first;
                }
            }
            if (best_gain > 1e-12) {
                labels[x] = best_label;
                if (best_label == next_label) ++next_label;
                improved = true;
            }
        }
        if (!improved) break;
    }
    return pass;
}

double mc_objective(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                    const double* costs, const uint64_t* labels) {
    (void)n_nodes;
    return objective(n_edges, uv, costs, labels);
}

// ---------------------------------------------------------------------------
// mutex watershed (affogato compute_mws_clustering replacement)
// ---------------------------------------------------------------------------
// Kruskal-style: process attractive and mutex (repulsive) edges jointly in
// descending weight order; attractive edges union unless a mutex constraint
// exists between the roots; mutex edges install constraints.
int64_t mws_clustering(int64_t n_nodes, int64_t n_attr, const int64_t* uv_attr,
                       const double* w_attr, int64_t n_mutex,
                       const int64_t* uv_mutex, const double* w_mutex,
                       uint64_t* labels_out) {
    struct E {
        double w;
        int64_t u, v;
        bool mutex;
    };
    std::vector<E> edges;
    edges.reserve(n_attr + n_mutex);
    for (int64_t i = 0; i < n_attr; ++i) {
        edges.push_back({w_attr[i], uv_attr[2 * i], uv_attr[2 * i + 1], false});
    }
    for (int64_t i = 0; i < n_mutex; ++i) {
        edges.push_back({w_mutex[i], uv_mutex[2 * i], uv_mutex[2 * i + 1], true});
    }
    std::stable_sort(edges.begin(), edges.end(),
                     [](const E& a, const E& b) { return a.w > b.w; });

    Ufd ufd(n_nodes);
    // mutex constraints per root (merged small-into-large on union)
    std::vector<std::unordered_set<int64_t>> mtx(n_nodes);
    auto have_mutex = [&](int64_t ra, int64_t rb) {
        const auto& small = mtx[ra].size() < mtx[rb].size() ? mtx[ra] : mtx[rb];
        int64_t other = (&small == &mtx[ra]) ? rb : ra;
        return small.count(other) > 0;
    };
    for (const auto& e : edges) {
        int64_t ru = ufd.find(e.u), rv = ufd.find(e.v);
        if (ru == rv) continue;
        if (e.mutex) {
            mtx[ru].insert(rv);
            mtx[rv].insert(ru);
        } else {
            if (have_mutex(ru, rv)) continue;
            int64_t keep = ufd.merge(ru, rv);
            int64_t gone = keep == ru ? rv : ru;
            // rewire the vanished root's constraints onto the survivor.
            // NO small-into-large swap here: swapping the two sets breaks
            // the back-pointer symmetry (partners of the survivor would be
            // "rewired" as if they pointed at the vanished root), leaving
            // stale entries that eventually put a root inside its own set
            // — and erasing an element of the set being iterated is UB
            // (observed as a segfault on near-uniform affinity fields)
            for (int64_t c : mtx[gone]) {
                mtx[c].erase(gone);
                if (c != keep) {
                    mtx[c].insert(keep);
                    mtx[keep].insert(c);
                }
            }
            mtx[gone].clear();
        }
    }
    std::unordered_map<int64_t, uint64_t> remap;
    uint64_t next = 0;
    for (int64_t i = 0; i < n_nodes; ++i) {
        int64_t r = ufd.find(i);
        auto it = remap.find(r);
        if (it == remap.end()) it = remap.emplace(r, next++).first;
        labels_out[i] = it->second;
    }
    return static_cast<int64_t>(next);
}

// Mutex-watershed scan over a PRE-SORTED edge stream: the caller (the
// device path) already extracted the edges and sorted them by descending
// priority on the accelerator, so this is only the inherently sequential
// constrained union-find — no 24-byte edge structs, no host sort (the
// std::stable_sort above is the dominant cost of mws_clustering at
// tens of millions of edges).  u[i] < 0 marks a dropped edge (the
// zero-affinity filter applied on device).  mutex_flag[i] != 0 marks a
// mutex (repulsive) edge.
int64_t mws_clustering_sorted(int64_t n_nodes, int64_t n_edges,
                              const int32_t* u, const int32_t* v,
                              const uint8_t* mutex_flag,
                              uint64_t* labels_out) {
    Ufd ufd(n_nodes);
    std::vector<std::unordered_set<int64_t>> mtx(n_nodes);
    auto have_mutex = [&](int64_t ra, int64_t rb) {
        const auto& small = mtx[ra].size() < mtx[rb].size() ? mtx[ra] : mtx[rb];
        int64_t other = (&small == &mtx[ra]) ? rb : ra;
        return small.count(other) > 0;
    };
    for (int64_t i = 0; i < n_edges; ++i) {
        if (u[i] < 0) continue;
        int64_t ru = ufd.find(u[i]), rv = ufd.find(v[i]);
        if (ru == rv) continue;
        if (mutex_flag[i]) {
            mtx[ru].insert(rv);
            mtx[rv].insert(ru);
        } else {
            if (have_mutex(ru, rv)) continue;
            int64_t keep = ufd.merge(ru, rv);
            int64_t gone = keep == ru ? rv : ru;
            // same rewiring discipline as mws_clustering above (no
            // small-into-large swap: it breaks back-pointer symmetry)
            for (int64_t c : mtx[gone]) {
                mtx[c].erase(gone);
                if (c != keep) {
                    mtx[c].insert(keep);
                    mtx[keep].insert(c);
                }
            }
            mtx[gone].clear();
        }
    }
    std::unordered_map<int64_t, uint64_t> remap;
    uint64_t next = 0;
    for (int64_t i = 0; i < n_nodes; ++i) {
        int64_t r = ufd.find(i);
        auto it = remap.find(r);
        if (it == remap.end()) it = remap.emplace(r, next++).first;
        labels_out[i] = it->second;
    }
    return static_cast<int64_t>(next);
}

// ---------------------------------------------------------------------------
// lifted multicut (nifty.graph.opt.lifted_multicut replacement,
// reference: utils/segmentation_utils.py:153-223)
// ---------------------------------------------------------------------------
// Greedy additive contraction for the lifted objective: only LOCAL edges are
// contractible (components must stay connected in the local graph), but the
// contraction priority of a local pair includes the accumulated LIFTED cost
// between the two components.
int64_t lmc_gaec(int64_t n_nodes, int64_t n_local, const int64_t* uv_local,
                 const double* costs_local, int64_t n_lifted,
                 const int64_t* uv_lifted, const double* costs_lifted,
                 uint64_t* labels_out) {
    std::vector<std::unordered_map<int64_t, double>> adj(n_nodes);   // local
    std::vector<std::unordered_map<int64_t, double>> lift(n_nodes);  // lifted
    for (int64_t i = 0; i < n_local; ++i) {
        int64_t u = uv_local[2 * i], v = uv_local[2 * i + 1];
        if (u == v) continue;
        adj[u][v] += costs_local[i];
        adj[v][u] += costs_local[i];
    }
    for (int64_t i = 0; i < n_lifted; ++i) {
        int64_t u = uv_lifted[2 * i], v = uv_lifted[2 * i + 1];
        if (u == v) continue;
        lift[u][v] += costs_lifted[i];
        lift[v][u] += costs_lifted[i];
    }
    auto pair_w = [&](int64_t ru, int64_t rv) {
        double w = 0.0;
        auto it = adj[ru].find(rv);
        if (it != adj[ru].end()) w += it->second;
        auto jt = lift[ru].find(rv);
        if (jt != lift[ru].end()) w += jt->second;
        return w;
    };
    using Entry = std::tuple<double, int64_t, int64_t>;
    std::priority_queue<Entry> pq;
    for (int64_t u = 0; u < n_nodes; ++u) {
        for (const auto& kv : adj[u]) {
            if (kv.first > u) {
                double w = pair_w(u, kv.first);
                if (w > 0) pq.emplace(w, u, kv.first);
            }
        }
    }
    Ufd ufd(n_nodes);
    while (!pq.empty()) {
        auto [w, u, v] = pq.top();
        pq.pop();
        if (w <= 0) break;
        int64_t ru = ufd.find(u), rv = ufd.find(v);
        if (ru == rv) continue;
        if (adj[ru].find(rv) == adj[ru].end()) continue;  // no local edge
        double live = pair_w(ru, rv);
        if (live != w || u != std::min(ru, rv) || v != std::max(ru, rv)) {
            if (live > 0) pq.emplace(live, std::min(ru, rv), std::max(ru, rv));
            continue;
        }
        if (adj[ru].size() + lift[ru].size() <
            adj[rv].size() + lift[rv].size()) {
            std::swap(ru, rv);
        }
        int64_t rw = ufd.merge(ru, rv);
        if (rw != ru) std::swap(ru, rv);
        adj[ru].erase(rv);
        adj[rv].erase(ru);
        lift[ru].erase(rv);
        lift[rv].erase(ru);
        for (const auto& kv : adj[rv]) {
            int64_t n = kv.first;
            adj[n].erase(rv);
            double& acc = adj[ru][n];
            acc += kv.second;
            adj[n][ru] = acc;
        }
        for (const auto& kv : lift[rv]) {
            int64_t n = kv.first;
            lift[n].erase(rv);
            double& acc = lift[ru][n];
            acc += kv.second;
            lift[n][ru] = acc;
        }
        adj[rv].clear();
        lift[rv].clear();
        for (const auto& kv : adj[ru]) {  // refresh priorities of live pairs
            double nw = pair_w(ru, kv.first);
            if (nw > 0) {
                pq.emplace(nw, std::min(ru, kv.first), std::max(ru, kv.first));
            }
        }
    }
    std::unordered_map<int64_t, uint64_t> remap;
    uint64_t next = 0;
    for (int64_t i = 0; i < n_nodes; ++i) {
        int64_t r = ufd.find(i);
        auto it = remap.find(r);
        if (it == remap.end()) it = remap.emplace(r, next++).first;
        labels_out[i] = it->second;
    }
    return static_cast<int64_t>(next);
}

// Kernighan-Lin-style refinement for the lifted objective: node moves among
// LOCAL-neighbor components (or a fresh singleton), gains include lifted
// contributions.
int64_t lmc_kl_refine(int64_t n_nodes, int64_t n_local, const int64_t* uv_local,
                      const double* costs_local, int64_t n_lifted,
                      const int64_t* uv_lifted, const double* costs_lifted,
                      uint64_t* labels, int64_t max_passes,
                      double time_limit) {
    const auto deadline = std::chrono::steady_clock::now() +
        std::chrono::duration<double>(time_limit > 0 ? time_limit : 1e18);
    auto build_csr = [n_nodes](int64_t n_e, const int64_t* uv, const double* c,
                               std::vector<int64_t>& off,
                               std::vector<int64_t>& nbr,
                               std::vector<double>& nw) {
        std::vector<int64_t> deg(n_nodes, 0);
        for (int64_t i = 0; i < n_e; ++i) {
            ++deg[uv[2 * i]];
            ++deg[uv[2 * i + 1]];
        }
        off.assign(n_nodes + 1, 0);
        for (int64_t i = 0; i < n_nodes; ++i) off[i + 1] = off[i] + deg[i];
        nbr.resize(off[n_nodes]);
        nw.resize(off[n_nodes]);
        std::vector<int64_t> cur(off.begin(), off.end() - 1);
        for (int64_t i = 0; i < n_e; ++i) {
            int64_t u = uv[2 * i], v = uv[2 * i + 1];
            nbr[cur[u]] = v;
            nw[cur[u]++] = c[i];
            nbr[cur[v]] = u;
            nw[cur[v]++] = c[i];
        }
    };
    std::vector<int64_t> loff, lnbr, toff, tnbr;
    std::vector<double> lw, tw;
    build_csr(n_local, uv_local, costs_local, loff, lnbr, lw);
    build_csr(n_lifted, uv_lifted, costs_lifted, toff, tnbr, tw);

    uint64_t next_label = 0;
    for (int64_t i = 0; i < n_nodes; ++i) {
        next_label = std::max(next_label, labels[i] + 1);
    }
    std::unordered_map<uint64_t, double> comp_w;
    std::unordered_set<uint64_t> local_comps;
    int64_t pass = 0;
    for (; pass < max_passes; ++pass) {
        if (std::chrono::steady_clock::now() > deadline) break;
        bool improved = false;
        for (int64_t x = 0; x < n_nodes; ++x) {
            if (loff[x + 1] == loff[x]) continue;
            comp_w.clear();
            local_comps.clear();
            for (int64_t j = loff[x]; j < loff[x + 1]; ++j) {
                comp_w[labels[lnbr[j]]] += lw[j];
                local_comps.insert(labels[lnbr[j]]);
            }
            for (int64_t j = toff[x]; j < toff[x + 1]; ++j) {
                comp_w[labels[tnbr[j]]] += tw[j];
            }
            uint64_t own = labels[x];
            double w_own = 0.0;
            auto it_own = comp_w.find(own);
            if (it_own != comp_w.end()) w_own = it_own->second;
            double best_gain = -w_own;  // leave to a fresh singleton
            uint64_t best_label = next_label;
            for (uint64_t cand : local_comps) {
                if (cand == own) continue;
                double gain = comp_w[cand] - w_own;
                if (gain > best_gain + 1e-12) {
                    best_gain = gain;
                    best_label = cand;
                }
            }
            if (best_gain > 1e-12) {
                labels[x] = best_label;
                if (best_label == next_label) ++next_label;
                improved = true;
            }
        }
        if (!improved) break;
    }
    return pass;
}

// ---------------------------------------------------------------------------
// edge-weighted agglomerative clustering
// (nifty.graph.agglo edgeWeighted/mala cluster-policy replacement,
// reference: utils/segmentation_utils.py:298-321, watershed/agglomerate.py)
// ---------------------------------------------------------------------------
// Merge the lowest-weight edge (weight = size-weighted mean boundary
// probability, maintained under contraction) while it stays below
// `threshold`.  `size_regularizer` > 0 biases against growing large nodes:
// priority = w * (harmonic-mean of node sizes / 2)^size_regularizer —
// the mala-style size regularization.
int64_t agglomerate_edge_weighted(int64_t n_nodes, int64_t n_edges,
                                  const int64_t* uv, const double* weights,
                                  const double* edge_sizes,
                                  const double* node_sizes, double threshold,
                                  double size_regularizer,
                                  uint64_t* labels_out) {
    // adjacency with accumulated (weight*size, size) per live pair
    struct Acc {
        double ws, s;
    };
    std::vector<std::unordered_map<int64_t, Acc>> adj(n_nodes);
    for (int64_t i = 0; i < n_edges; ++i) {
        int64_t u = uv[2 * i], v = uv[2 * i + 1];
        if (u == v) continue;
        double s = edge_sizes ? edge_sizes[i] : 1.0;
        Acc& a = adj[u][v];
        a.ws += weights[i] * s;
        a.s += s;
        adj[v][u] = a;
    }
    std::vector<double> nsize(n_nodes, 1.0);
    if (node_sizes) nsize.assign(node_sizes, node_sizes + n_nodes);

    Ufd ufd(n_nodes);
    auto priority = [&](int64_t ru, int64_t rv, const Acc& a) {
        double p = a.ws / a.s;
        if (size_regularizer > 0.0) {
            double hm = 2.0 / (1.0 / nsize[ru] + 1.0 / nsize[rv]);
            p *= std::pow(hm / 2.0, size_regularizer);
        }
        return p;
    };
    using Entry = std::tuple<double, int64_t, int64_t>;  // (-p, u, v): min-heap
    std::priority_queue<Entry> pq;
    for (int64_t u = 0; u < n_nodes; ++u) {
        for (const auto& kv : adj[u]) {
            if (kv.first > u) pq.emplace(-priority(u, kv.first, kv.second), u, kv.first);
        }
    }
    while (!pq.empty()) {
        auto [np_, u, v] = pq.top();
        pq.pop();
        double p = -np_;
        if (p >= threshold) break;
        int64_t ru = ufd.find(u), rv = ufd.find(v);
        if (ru == rv) continue;
        auto it = adj[ru].find(rv);
        if (it == adj[ru].end()) continue;
        double live_p = priority(ru, rv, it->second);
        if (live_p != p || u != std::min(ru, rv) || v != std::max(ru, rv)) {
            // stale: re-push the live pair (it may still be below threshold)
            pq.emplace(-live_p, std::min(ru, rv), std::max(ru, rv));
            continue;
        }
        if (adj[ru].size() < adj[rv].size()) std::swap(ru, rv);
        int64_t rw = ufd.merge(ru, rv);
        if (rw != ru) std::swap(ru, rv);
        nsize[ru] += nsize[rv];
        adj[ru].erase(rv);
        adj[rv].erase(ru);
        for (const auto& kv : adj[rv]) {
            int64_t n = kv.first;
            adj[n].erase(rv);
            Acc& acc = adj[ru][n];
            acc.ws += kv.second.ws;
            acc.s += kv.second.s;
            adj[n][ru] = acc;
            int64_t rn = ufd.find(n);
            pq.emplace(-priority(ru, rn, acc), std::min(ru, n), std::max(ru, n));
        }
        adj[rv].clear();
    }
    std::unordered_map<int64_t, uint64_t> remap;
    uint64_t next = 0;
    for (int64_t i = 0; i < n_nodes; ++i) {
        int64_t r = ufd.find(i);
        auto it = remap.find(r);
        if (it == remap.end()) it = remap.emplace(r, next++).first;
        labels_out[i] = it->second;
    }
    return static_cast<int64_t>(next);
}

// edge-weighted seeded watershed on a graph
// (nifty.graph.edgeWeightedWatershedsSegmentation replacement,
// reference: postprocess/graph_watershed_assignments.py:172)
// Grows seed labels along maximum-weight edges (Prim-style).
void graph_watershed(int64_t n_nodes, int64_t n_edges, const int64_t* uv,
                     const double* weights, uint64_t* seeds_inout) {
    std::vector<int64_t> deg(n_nodes, 0);
    for (int64_t i = 0; i < n_edges; ++i) {
        ++deg[uv[2 * i]];
        ++deg[uv[2 * i + 1]];
    }
    std::vector<int64_t> off(n_nodes + 1, 0);
    for (int64_t i = 0; i < n_nodes; ++i) off[i + 1] = off[i] + deg[i];
    std::vector<int64_t> nbr(off[n_nodes]);
    std::vector<double> nw(off[n_nodes]);
    {
        std::vector<int64_t> cur(off.begin(), off.end() - 1);
        for (int64_t i = 0; i < n_edges; ++i) {
            int64_t u = uv[2 * i], v = uv[2 * i + 1];
            nbr[cur[u]] = v;
            nw[cur[u]++] = weights[i];
            nbr[cur[v]] = u;
            nw[cur[v]++] = weights[i];
        }
    }
    using Entry = std::tuple<double, int64_t, int64_t>;  // (w, from, to)
    std::priority_queue<Entry> pq;
    for (int64_t i = 0; i < n_nodes; ++i) {
        if (seeds_inout[i] == 0) continue;
        for (int64_t j = off[i]; j < off[i + 1]; ++j) {
            if (seeds_inout[nbr[j]] == 0) pq.emplace(nw[j], i, nbr[j]);
        }
    }
    while (!pq.empty()) {
        auto [w, from, to] = pq.top();
        pq.pop();
        if (seeds_inout[to] != 0) continue;
        seeds_inout[to] = seeds_inout[from];
        for (int64_t j = off[to]; j < off[to + 1]; ++j) {
            if (seeds_inout[nbr[j]] == 0) pq.emplace(nw[j], to, nbr[j]);
        }
    }
}

// ---------------------------------------------------------------------------
// 3d skeletonization by topological thinning
// (skimage.morphology.skeletonize_3d replacement for the skeletons
// component, reference: skeletons/skeletonize.py:129-157; skimage is not in
// the image, so the thinning is first-party)
// ---------------------------------------------------------------------------
namespace {

inline int manhattan(int i) {  // local 3x3x3 index -> |dz|+|dy|+|dx|
    int z = i / 9 - 1, y = (i / 3) % 3 - 1, x = i % 3 - 1;
    return std::abs(z) + std::abs(y) + std::abs(x);
}

// number of 26-connected components of OBJECT voxels in the 26-neighborhood
// (center excluded)
int cc_object_26(const bool* m) {
    int comp[27];
    for (int i = 0; i < 27; ++i) comp[i] = -1;
    int n_comp = 0;
    for (int seed = 0; seed < 27; ++seed) {
        if (seed == 13 || !m[seed] || comp[seed] != -1) continue;
        int stack[27], sp = 0;
        stack[sp++] = seed;
        comp[seed] = n_comp;
        while (sp) {
            int cur = stack[--sp];
            int cz = cur / 9, cy = (cur / 3) % 3, cx = cur % 3;
            for (int oz = -1; oz <= 1; ++oz)
                for (int oy = -1; oy <= 1; ++oy)
                    for (int ox = -1; ox <= 1; ++ox) {
                        if (!(oz | oy | ox)) continue;
                        int nz = cz + oz, ny = cy + oy, nx = cx + ox;
                        if (nz < 0 || nz > 2 || ny < 0 || ny > 2 ||
                            nx < 0 || nx > 2) continue;
                        int nidx = nz * 9 + ny * 3 + nx;
                        if (nidx == 13 || !m[nidx] || comp[nidx] != -1)
                            continue;
                        comp[nidx] = n_comp;
                        stack[sp++] = nidx;
                    }
        }
        ++n_comp;
    }
    return n_comp;
}

// number of 6-connected components of BACKGROUND voxels in the
// 18-neighborhood that contain a face-neighbor of the center
int cc_background_6(const bool* m) {
    int comp[27];
    for (int i = 0; i < 27; ++i) comp[i] = -1;
    int n_comp = 0;
    for (int seed = 0; seed < 27; ++seed) {
        if (seed == 13 || m[seed] || comp[seed] != -1) continue;
        if (manhattan(seed) > 2) continue;  // corners not in N18
        int stack[27], sp = 0;
        stack[sp++] = seed;
        comp[seed] = 0;
        bool touches = manhattan(seed) == 1;
        while (sp) {
            int cur = stack[--sp];
            int cz = cur / 9, cy = (cur / 3) % 3, cx = cur % 3;
            const int d6[6][3] = {{-1, 0, 0}, {1, 0, 0}, {0, -1, 0},
                                  {0, 1, 0},  {0, 0, -1}, {0, 0, 1}};
            for (const auto& d : d6) {
                int nz = cz + d[0], ny = cy + d[1], nx = cx + d[2];
                if (nz < 0 || nz > 2 || ny < 0 || ny > 2 ||
                    nx < 0 || nx > 2) continue;
                int nidx = nz * 9 + ny * 3 + nx;
                if (nidx == 13 || m[nidx] || comp[nidx] != -1) continue;
                if (manhattan(nidx) > 2) continue;
                comp[nidx] = 0;
                stack[sp++] = nidx;
                if (manhattan(nidx) == 1) touches = true;
            }
        }
        if (touches) ++n_comp;
    }
    return n_comp;
}

}  // namespace

// Thin a binary volume to a 1-voxel-wide skeleton.  `vol` is 0/1 uint8 of
// shape (sz, sy, sx), modified in place.  Border-peeling with the standard
// simple-point test (object stays 26-connected, background stays
// 6-connected across the deletion) and curve-endpoint preservation.
void skeletonize_3d(uint8_t* vol, int64_t sz, int64_t sy, int64_t sx) {
    auto at = [&](int64_t z, int64_t y, int64_t x) -> uint8_t {
        if (z < 0 || z >= sz || y < 0 || y >= sy || x < 0 || x >= sx)
            return 0;
        return vol[z * sy * sx + y * sx + x];
    };
    std::vector<int64_t> candidates;
    bool changed = true;
    while (changed) {
        changed = false;
        // six directional sub-iterations keep the skeleton centered
        const int dirs[6][3] = {{-1, 0, 0}, {1, 0, 0}, {0, -1, 0},
                                {0, 1, 0},  {0, 0, -1}, {0, 0, 1}};
        for (const auto& d : dirs) {
            candidates.clear();
            for (int64_t z = 0; z < sz; ++z)
                for (int64_t y = 0; y < sy; ++y)
                    for (int64_t x = 0; x < sx; ++x) {
                        int64_t idx = z * sy * sx + y * sx + x;
                        if (!vol[idx]) continue;
                        // border in direction d
                        if (at(z + d[0], y + d[1], x + d[2])) continue;
                        // endpoint: exactly one object neighbor -> keep
                        int n_obj = 0;
                        for (int oz = -1; oz <= 1; ++oz)
                            for (int oy = -1; oy <= 1; ++oy)
                                for (int ox = -1; ox <= 1; ++ox)
                                    if ((oz | oy | ox) &&
                                        at(z + oz, y + oy, x + ox))
                                        ++n_obj;
                        if (n_obj <= 1) continue;
                        // simple point test on the 3x3x3 neighborhood
                        bool m[27];
                        for (int oz = -1; oz <= 1; ++oz)
                            for (int oy = -1; oy <= 1; ++oy)
                                for (int ox = -1; ox <= 1; ++ox)
                                    m[(oz + 1) * 9 + (oy + 1) * 3 + ox + 1] =
                                        at(z + oz, y + oy, x + ox) != 0;
                        if (cc_object_26(m) != 1) continue;
                        if (cc_background_6(m) != 1) continue;
                        candidates.push_back(idx);
                    }
            // delete sequentially, re-checking the simple-point condition
            // (a neighbor deleted earlier in this pass can change it)
            for (int64_t idx : candidates) {
                int64_t z = idx / (sy * sx), y = (idx / sx) % sy, x = idx % sx;
                int n_obj = 0;
                for (int oz = -1; oz <= 1; ++oz)
                    for (int oy = -1; oy <= 1; ++oy)
                        for (int ox = -1; ox <= 1; ++ox)
                            if ((oz | oy | ox) && at(z + oz, y + oy, x + ox))
                                ++n_obj;
                if (n_obj <= 1) continue;
                bool m[27];
                for (int oz = -1; oz <= 1; ++oz)
                    for (int oy = -1; oy <= 1; ++oy)
                        for (int ox = -1; ox <= 1; ++ox)
                            m[(oz + 1) * 9 + (oy + 1) * 3 + ox + 1] =
                                at(z + oz, y + oy, x + ox) != 0;
                if (cc_object_26(m) != 1) continue;
                if (cc_background_6(m) != 1) continue;
                vol[idx] = 0;
                changed = true;
            }
        }
    }
}

// Seeded 3D watershed by priority flood over a uint8 height map — the
// vigra watershedsNew algorithm (reference: utils/volume_utils.py:124
// `vigra.analysis.watershedsNew`): seeds grow in increasing height order,
// FIFO within a level, 6-connectivity.  A monotone 256-bucket queue makes
// it exact O(n) without a heap.  `labels` carries the seeds in (0 = free)
// and the full labeling out; every voxel connected to a seed gets labeled.
void seeded_watershed_u8(const uint8_t* height, int64_t sz, int64_t sy,
                         int64_t sx, int64_t* labels) {
    const int64_t n = sz * sy * sx;
    std::vector<std::vector<int64_t>> buckets(256);
    for (int64_t i = 0; i < n; ++i)
        if (labels[i] > 0) buckets[height[i]].push_back(i);
    const int64_t strides[3] = {sy * sx, sx, 1};
    const int64_t dims[3] = {sz, sy, sx};
    for (int level = 0; level < 256; ++level) {
        auto& q = buckets[level];
        // q grows while we scan it (same-level FIFO flood): index loop
        for (size_t h = 0; h < q.size(); ++h) {
            const int64_t v = q[h];
            int64_t coord[3];
            coord[0] = v / strides[0];
            coord[1] = (v / sx) % sy;
            coord[2] = v % sx;
            for (int d = 0; d < 3; ++d)
                for (int s = -1; s <= 1; s += 2) {
                    const int64_t c = coord[d] + s;
                    if (c < 0 || c >= dims[d]) continue;
                    const int64_t u = v + s * strides[d];
                    if (labels[u] != 0) continue;
                    labels[u] = labels[v];
                    const int lu = height[u] < level ? level : height[u];
                    buckets[lu].push_back(u);
                }
        }
        q.clear();
        q.shrink_to_fit();
    }
}

// Size filter with LOCAL regrow: fragments below min_size are cleared and
// their voxels re-flooded from the surviving neighborhood — touches only
// the small fragments' voxels instead of re-running the full watershed
// (reference semantics: utils/volume_utils.py:123-139 watershed-and-
// size-filter, which regrows via a second full pass).
void size_filter_u8(const uint8_t* height, int64_t sz, int64_t sy,
                    int64_t sx, int64_t* labels, int64_t min_size) {
    const int64_t n = sz * sy * sx;
    int64_t max_label = 0;
    for (int64_t i = 0; i < n; ++i)
        if (labels[i] > max_label) max_label = labels[i];
    std::vector<int64_t> counts(max_label + 1, 0);
    for (int64_t i = 0; i < n; ++i)
        if (labels[i] > 0) ++counts[labels[i]];
    std::vector<uint8_t> small(max_label + 1, 0);
    bool any = false;
    for (int64_t l = 1; l <= max_label; ++l)
        if (counts[l] > 0 && counts[l] < min_size) {
            small[l] = 1;
            any = true;
        }
    if (!any) return;
    const int64_t strides[3] = {sy * sx, sx, 1};
    const int64_t dims[3] = {sz, sy, sx};
    std::vector<std::vector<int64_t>> buckets(256);
    // clear small fragments to the -2 sentinel; seed the refill queues
    // with their surviving neighbors.  The flood expands ONLY into -2
    // voxels, so pre-existing background (label 0, e.g. masked regions)
    // is never claimed — the regrow touches exactly the removed voxels.
    for (int64_t i = 0; i < n; ++i)
        if (labels[i] > 0 && small[labels[i]]) labels[i] = -2;
    for (int64_t i = 0; i < n; ++i) {
        if (labels[i] <= 0) continue;
        const int64_t cz = i / strides[0], cy = (i / sx) % sy, cx = i % sx;
        const int64_t coord[3] = {cz, cy, cx};
        bool frontier = false;
        for (int d = 0; d < 3 && !frontier; ++d)
            for (int s = -1; s <= 1 && !frontier; s += 2) {
                const int64_t c = coord[d] + s;
                if (c < 0 || c >= dims[d]) continue;
                if (labels[i + s * strides[d]] == -2) frontier = true;
            }
        if (frontier) buckets[height[i]].push_back(i);
    }
    for (int level = 0; level < 256; ++level) {
        auto& q = buckets[level];
        for (size_t h = 0; h < q.size(); ++h) {
            const int64_t v = q[h];
            const int64_t coord[3] = {v / strides[0], (v / sx) % sy,
                                      v % sx};
            for (int d = 0; d < 3; ++d)
                for (int s = -1; s <= 1; s += 2) {
                    const int64_t c = coord[d] + s;
                    if (c < 0 || c >= dims[d]) continue;
                    const int64_t u = v + s * strides[d];
                    if (labels[u] != -2) continue;
                    labels[u] = labels[v];
                    const int lu = height[u] < level ? level : height[u];
                    buckets[lu].push_back(u);
                }
        }
        q.clear();
    }
    // unreachable removed voxels (no surviving neighbor path) become 0
    for (int64_t i = 0; i < n; ++i)
        if (labels[i] == -2) labels[i] = 0;
}

}  // extern "C"
