"""Multi-host orchestration: jax.distributed plumbing, per-host block
ownership, filesystem barriers, DCN-aware meshes.

The reference reaches many nodes through its batch system — one sbatch per
job, the shared filesystem as the data plane (reference:
cluster_tasks.py:375-490).  The TPU-native replacement keeps the shared
store as the data plane (it already guarantees race-freedom by
chunk-aligned writes) and replaces the scheduler with SPMD processes:

* every process runs the SAME driver script; ``jax.distributed.initialize``
  (or the ``CTT_PROCESS_COUNT``/``CTT_PROCESS_ID`` env pair for CPU smoke
  tests without a coordination service) tells each process who it is;
* blockwise tasks shard their block list round-robin per process — process
  p executes job p of an n_processes-job layout, so the job protocol and
  the log-line success detection apply unchanged (core/runtime.py).
  Block-granular RETRY is driver-rerun only in this mode: a failed job
  fails the task on every process, and re-running the driver script
  redoes the incomplete tasks (the single-process in-run retry loop would
  need a cross-process consensus on the failed-block set);
* global (reduce-style) tasks run on the LEAD process only; everyone else
  waits at a filesystem barrier and then reads the lead's results/logs —
  the reference's barrier-only synchronization, kept deliberately;
* device meshes spanning hosts come from ``make_multihost_mesh``: the
  outer (data/blocks) axis maps across processes over DCN, inner axes stay
  within a host's chips over ICI (jax.experimental.mesh_utils).

Limits (documented, by design of this round): collectives across processes
require real multi-host devices (TPU pods) — the CPU smoke test exercises
ownership + barriers + store cooperation, not cross-process psums; retry
of a FAILED process's blocks needs an external restart of that process
(the reference needs the same for a lost node).
"""

from __future__ import annotations

import os
import time
from typing import List, Optional, Sequence

import numpy as np


def init_distributed(coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> None:
    """Initialize jax.distributed from args or the standard env variables
    (COORDINATOR_ADDRESS / NUM_PROCESSES / PROCESS_ID).  No-op when
    single-process or already initialized."""
    import jax

    coordinator_address = (coordinator_address
                           or os.environ.get("COORDINATOR_ADDRESS"))
    num_processes = num_processes or int(
        os.environ.get("NUM_PROCESSES", "0")) or None
    process_id = (process_id if process_id is not None
                  else int(os.environ.get("PROCESS_ID", "-1")))
    if coordinator_address is None or num_processes in (None, 1):
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id if process_id >= 0 else None)
    except RuntimeError:
        pass  # already initialized


def process_count() -> int:
    """Number of cooperating processes: jax.distributed when initialized,
    else the CTT_PROCESS_COUNT env (the CPU smoke-test path), else 1."""
    env = os.environ.get("CTT_PROCESS_COUNT")
    if env:
        return int(env)
    try:
        import jax

        return jax.process_count()
    except Exception:
        return 1


def process_index() -> int:
    env = os.environ.get("CTT_PROCESS_ID")
    if env:
        return int(env)
    try:
        import jax

        return jax.process_index()
    except Exception:
        return 0


def is_lead() -> bool:
    return process_index() == 0


def owned_blocks(block_list: Sequence[int]) -> List[int]:
    """This process's round-robin share of a block list (the reference's
    ``block_list[job_id::n_jobs]`` layout, cluster_tasks.py:322-332)."""
    return list(block_list)[process_index()::process_count()]


def fs_barrier(tmp_folder: str, name: str, timeout: float = 600.0,
               poll: float = 0.05) -> None:
    """Filesystem barrier over the shared tmp folder (the reference's
    control plane is exactly files + polling; cluster_tasks.py:466-490).

    COUNTER-based so reruns stay correct: each process persists a per-
    barrier round counter, increments it on entry, and waits until every
    process's counter reaches its own round — stale sentinels from a
    previous (crashed or completed) run can never satisfy a new round, and
    every process passes the same barriers in the same DAG order."""
    pc = process_count()
    if pc <= 1:
        return
    bdir = os.path.join(tmp_folder, "barriers", name)
    os.makedirs(bdir, exist_ok=True)
    mine = os.path.join(bdir, f"p{process_index()}.count")
    prev = 0
    if os.path.exists(mine):
        with open(mine) as f:
            prev = int(f.read().strip() or 0)
    my_round = prev + 1
    tmp = mine + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(my_round))
    os.replace(tmp, mine)
    deadline = time.time() + timeout
    while True:
        counts = []
        for p in range(pc):
            path = os.path.join(bdir, f"p{p}.count")
            try:
                with open(path) as f:
                    counts.append(int(f.read().strip() or 0))
            except (FileNotFoundError, ValueError):
                counts.append(0)
        if all(c >= my_round for c in counts):
            return
        if time.time() > deadline:
            raise TimeoutError(
                f"barrier {name}: rounds {counts} < {my_round} after "
                f"{timeout}s")
        time.sleep(poll)


def make_multihost_mesh(axis_names: Sequence[str] = ("data", "model"),
                        dcn_axis: int = 0):
    """Mesh spanning all hosts: the ``dcn_axis`` runs across processes
    (DCN), the remaining axes across each host's local chips (ICI) — the
    standard hybrid layout (jax.experimental.mesh_utils
    create_hybrid_device_mesh).  Falls back to a flat mesh when
    single-process."""
    import jax
    from jax.sharding import Mesh

    pc = 1
    try:
        pc = jax.process_count()
    except Exception:
        pass
    n_local = max(len(jax.devices()) // max(pc, 1), 1)
    if pc <= 1:
        # single host: all devices on the first non-dcn axis
        sizes = [1] * len(axis_names)
        other = (dcn_axis + 1) % len(axis_names) if len(axis_names) > 1 \
            else dcn_axis
        sizes[other] = len(jax.devices())
        arr = np.array(jax.devices()).reshape(sizes)
        return Mesh(arr, tuple(axis_names))
    from jax.experimental import mesh_utils

    dcn_shape = [1] * len(axis_names)
    dcn_shape[dcn_axis] = pc
    ici_shape = [1] * len(axis_names)
    ici_shape[(dcn_axis + 1) % len(axis_names)] = n_local
    devices = mesh_utils.create_hybrid_device_mesh(
        ici_shape, dcn_shape, devices=jax.devices())
    return Mesh(devices, tuple(axis_names))
