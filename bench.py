"""Benchmark: DT-watershed block pipeline throughput (voxels/sec).

Config 1 of BASELINE.json ("Distance-transform watershed on a CREMI-like
boundary map, single block") at the reference's standard block size
[50, 512, 512] (reference: cluster_tasks.py:217 default block_shape).  The
device path is the framework's jitted EDT -> seeds -> seeded-watershed
pipeline (cluster_tools_tpu/ops); the baseline is the same pipeline computed
with scipy.ndimage on the host CPU — the stand-in for the reference's
vigra-based `target='local'` per-block compute (reference:
watershed/watershed.py:285-341).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import sys
import time

import numpy as np

SHAPE = (50, 512, 512)  # the reference's standard block (cluster_tasks.py:217)


def synthetic_boundary_map(shape, n_cells=160, seed=0):
    """Smooth cell-boundary-like map in [0, 1]: distance ridges of a random
    point set, the standard synthetic stand-in for an EM membrane map."""
    rng = np.random.RandomState(seed)
    pts = (rng.rand(n_cells, 3) * np.array(shape)).astype("float32")
    zz, yy, xx = np.meshgrid(*[np.arange(s, dtype="float32") for s in shape],
                             indexing="ij")
    d = np.full(shape, np.inf, "float32")
    d2 = np.full(shape, np.inf, "float32")
    for p in pts:
        dist = np.sqrt((zz - p[0]) ** 2 + (yy - p[1]) ** 2 + (xx - p[2]) ** 2)
        nearer = dist < d
        d2 = np.where(nearer, d, np.minimum(d2, dist))
        d = np.where(nearer, dist, d)
    ridge = np.exp(-0.5 * ((d2 - d) / 2.0) ** 2)  # ~1 on ridges, ~0 inside
    return ridge.astype(np.float32)


def bench_device(data, cfg, repeats=4):
    """Streamed block throughput: the deployment pattern overlaps transfers
    with compute (run_ws_blocks_stream), so the metric is stream rate, not
    single-block latency."""
    from cluster_tools_tpu.workflows.watershed import run_ws_blocks_stream

    run_ws_blocks_stream([data], cfg)  # warmup: compile
    blocks = [data] * repeats
    t0 = time.perf_counter()
    run_ws_blocks_stream(blocks, cfg)
    return (time.perf_counter() - t0) / repeats


def bench_scipy(data, cfg):
    from scipy import ndimage as ndi

    t0 = time.perf_counter()
    threshold = cfg["threshold"]
    fg = data < threshold
    dt = ndi.distance_transform_edt(fg).astype(np.float32)
    hmap = ndi.gaussian_filter(data, cfg["sigma_weights"])
    height = cfg["alpha"] * hmap + (1 - cfg["alpha"]) * (1 - dt / max(dt.max(), 1e-6))
    dts = ndi.gaussian_filter(dt, cfg["sigma_seeds"])
    maxima = (ndi.maximum_filter(dts, size=5) == dts) & fg
    seeds, _ = ndi.label(maxima)
    q = (height * 255).astype(np.uint8)
    ndi.watershed_ift(q, seeds.astype(np.int32))
    return time.perf_counter() - t0


def main():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    cfg = {"threshold": 0.5, "sigma_seeds": 2.0, "sigma_weights": 2.0,
           "alpha": 0.8, "size_filter": 0}
    data = synthetic_boundary_map(SHAPE)
    n_voxels = int(np.prod(SHAPE))

    dev_t = bench_device(data, cfg)
    cpu_t = bench_scipy(data, cfg)

    value = n_voxels / dev_t
    baseline = n_voxels / cpu_t
    print(json.dumps({
        "metric": "dt_watershed_block_throughput",
        "value": round(value, 1),
        "unit": "voxels/sec",
        "vs_baseline": round(value / baseline, 3),
    }))


if __name__ == "__main__":
    main()
