"""Open-loop load harness: schedule determinism, virtual-time replay
(identical histogram bucket counts per seed — the ISSUE 16 acceptance
bar), threaded open-loop smoke, and the `bench.py serve --smoke` schema.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from cluster_tools_tpu.core import loadgen, slo
from cluster_tools_tpu.core.server import AdmissionRejected

SPEC = loadgen.LoadSpec(seed=11, rate_hz=150.0, n_requests=200,
                        n_tenants=120)


# ---------------------------------------------------------------------------
# schedule generation
# ---------------------------------------------------------------------------

def test_schedule_deterministic_per_seed():
    a = loadgen.generate_schedule(SPEC)
    b = loadgen.generate_schedule(SPEC)
    assert a == b
    c = loadgen.generate_schedule(SPEC._replace(seed=12))
    assert a != c


def test_schedule_open_loop_properties():
    sched = loadgen.generate_schedule(SPEC)
    assert len(sched) == SPEC.n_requests
    # arrivals are sorted (open loop: the schedule is fixed up front)
    ts = [a.t for a in sched]
    assert ts == sorted(ts)
    # mean inter-arrival ~ 1/rate (Poisson, loose 3x bound)
    mean_gap = ts[-1] / len(ts)
    assert 1 / (3 * SPEC.rate_hz) < mean_gap < 3 / SPEC.rate_hz
    # the mix shows up: both lanes, all ROI classes, many tenants
    assert {a.lane for a in sched} == {"edit", "bulk"}
    assert {a.roi for a in sched} == {"small", "medium", "large"}
    assert len({a.tenant for a in sched}) > 50


def test_roi_class_maps_to_block_count():
    sched = loadgen.generate_schedule(SPEC)
    by_roi = {a.roi: a.n_blocks for a in sched}
    assert by_roi == {"small": 1, "medium": 4, "large": 16}
    pipe = loadgen.SyntheticPipeline(clock=loadgen.VirtualClock())
    for a in sched[:10]:
        assert pipe.request_n_blocks(loadgen.synthetic_volume(a)) \
            == a.n_blocks


# ---------------------------------------------------------------------------
# virtual-time mode (deterministic tier-1 replay)
# ---------------------------------------------------------------------------

def _virtual(tmpdir, spec=SPEC, **kw):
    return loadgen.run_virtual(spec, str(tmpdir),
                               slo_engine=slo.SLOEngine(), **kw)


def test_virtual_mode_identical_bucket_counts(tmp_path):
    """The acceptance criterion: same seed -> identical request schedule
    AND identical histogram bucket counts on the stub pipeline."""
    rows = []
    buckets = []
    for d in ("a", "b"):
        r = _virtual(tmp_path / d)
        lat, wait, tenant = r["server"].latency_histograms()
        rows.append(r)
        buckets.append({
            "lat": {k: h.cumulative() for k, h in lat.items()},
            "wait": {k: h.cumulative() for k, h in wait.items()},
            "tenant": {k: h.cumulative() for k, h in tenant.items()},
        })
    assert [tuple(a) for a in rows[0]["schedule"]] == \
        [tuple(a) for a in rows[1]["schedule"]]
    assert buckets[0] == buckets[1]
    assert rows[0]["lanes"] == rows[1]["lanes"]
    assert rows[0]["served"] == SPEC.n_requests


def test_virtual_mode_latency_charged_from_scheduled_arrival(tmp_path):
    """Open-loop semantics: under overload, latency includes the time a
    request spent waiting BEHIND the schedule, so the tail compounds."""
    hot = SPEC._replace(rate_hz=2000.0, n_requests=300)
    r = _virtual(tmp_path, spec=hot)
    # offered 2000 req/s vs ~60 req/s capacity: p99 must dwarf the
    # isolated service time (worst class: 2+16*4+1 = 67 ms)
    worst = max(v["p99_s"] for v in r["lanes"].values())
    assert worst > 0.5
    # and the SLO engine must call it overloaded
    assert r["slo"]["overload"] is True


def test_virtual_mode_unsaturated_has_no_overload(tmp_path):
    light = SPEC._replace(rate_hz=20.0, n_requests=60)
    r = _virtual(tmp_path, spec=light)
    assert r["slo"]["overload"] is False
    assert r["served"] == 60
    assert r["failed"] == 0


def test_fault_injection_feeds_availability(tmp_path):
    clock = loadgen.VirtualClock()
    pipe = loadgen.SyntheticPipeline(clock=clock, fail_every=5)
    r = loadgen.run_virtual(SPEC._replace(n_requests=50), str(tmp_path),
                            pipeline=pipe, slo_engine=slo.SLOEngine())
    assert r["failed"] == 10
    avail = [o for o in r["slo"]["objectives"]
             if o["name"] == "availability"][0]
    assert avail["windows"][-1]["bad"] >= 10


def test_admission_hook_rejections_counted(tmp_path):
    calls = []

    def hook(tenant, lane, overloaded):
        calls.append((tenant, lane, overloaded))
        return lane != "bulk"        # shed the bulk lane entirely

    r = _virtual(tmp_path, admission_hook=hook)
    assert r["rejected"] > 0
    assert "bulk" not in r["lanes"]
    assert r["served"] + r["rejected"] == SPEC.n_requests
    assert {l for _, l, _ in calls} == {"edit", "bulk"}


def test_virtual_requires_clock_driven_pipeline(tmp_path):
    with pytest.raises(ValueError):
        loadgen.run_virtual(SPEC, str(tmp_path),
                            pipeline=loadgen.SyntheticPipeline())


# ---------------------------------------------------------------------------
# threaded mode (real worker thread, real sleeps — kept tiny for tier-1)
# ---------------------------------------------------------------------------

def test_threaded_open_loop_smoke(tmp_path):
    spec = loadgen.LoadSpec(seed=3, rate_hz=200.0, n_requests=40,
                            n_tenants=10)
    pipe = loadgen.SyntheticPipeline(prepare_s=1e-4, block_s=2e-4,
                                     finalize_s=1e-4)
    eng = slo.SLOEngine()
    r = loadgen.run_threaded(spec, str(tmp_path), pipeline=pipe,
                             slo_engine=eng, metrics_path=None)
    assert r["drained"] is True
    assert r["served"] == 40
    assert r["mode"] == "threaded"
    assert set(r["lanes"]) <= {"edit", "bulk"}
    assert r["slo"]["objectives"]
    for lane_row in r["lanes"].values():
        assert 0.0 <= lane_row["p50_s"] <= lane_row["p99_s"]


# ---------------------------------------------------------------------------
# bench.py serve --smoke schema (no XLA, subprocess — satellite CI task)
# ---------------------------------------------------------------------------

def test_bench_serve_smoke_schema(tmp_path):
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = str(tmp_path / "BENCH_serve_smoke.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py"), "serve",
         "--smoke", "--out", out],
        cwd=here, env=env, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.load(open(out))
    assert doc["metric"] == "serve_load"
    assert doc["mode"] == "smoke-virtual"
    rows = doc["stub_levels"]
    assert len(rows) >= 3
    offered = [r["offered_hz"] for r in rows]
    assert offered == sorted(offered) and len(set(offered)) >= 3
    for row in rows:
        for lane_row in row["lanes"].values():
            for k in ("n", "p50_s", "p95_s", "p99_s"):
                assert k in lane_row
        assert "overload" in row["slo"]
        for obj in row["slo"]["objectives"]:
            for w in obj["windows"]:
                assert "burn_rate" in w and "max_burn" in w
    assert doc["slo_objectives"] and doc["burn_windows"]
    # the real-pipeline row is the `slow` path, absent from --smoke
    assert doc["real_pipeline"] is None
    # the one-line summary the bench prints must be valid JSON
    last = proc.stdout.strip().splitlines()[-1]
    assert json.loads(last)["metric"] == "serve_load"
