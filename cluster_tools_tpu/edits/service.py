"""Server-facing edit pipeline (ISSUE 19 tentpole, part 5).

:class:`EditPipeline` speaks the resident server's duck-typed pipeline
protocol with the EDIT PAYLOAD as the "volume": a request submitted on
the ``edit`` lane carries ``{"op": "merge"|"split", "fragments": [...]}``
and flows submit -> resolve -> incremental solve -> LUT patch -> block
rewrite, one scheduling quantum per affected subproblem — so a cheap
edit yields the worker after each block exactly like bulk traffic does,
and the lane-priority claim order in core/server.py keeps its queue-wait
low while a bulk tenant streams ROI requests.

Every phase runs under its registered ``edit:*`` stage so the spans land
in the same telemetry the bulk path uses, and per-edit results carry the
edit-log correlation id end to end (status JSON, flight records).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core import telemetry
from ..core.runtime import stage
from .incremental import EditSession
from .log import EditLog
from .patcher import (patch_assignment_table, patch_paintera_assignment)


class EditPipeline:
    """Adapter from proofreading edits to the server pipeline protocol.

    ``assignment_path`` is the dense LUT the bulk workflow wrote
    (``.npy``); ``ws/output`` name the fragment volume and segmentation
    to patch (block grid must match the problem's
    ``sub_graph_block_shape`` so touched-block ids line up; that is the
    grid both were produced on).  Omitting ``output_path`` skips the
    block rewrite (LUT-only serving).
    """

    def __init__(self, session: EditSession, edit_log: EditLog,
                 assignment_path: str, *,
                 ws_path: Optional[str] = None,
                 ws_key: Optional[str] = None,
                 output_path: Optional[str] = None,
                 output_key: Optional[str] = None,
                 paintera_path: Optional[str] = None,
                 paintera_label_group: Optional[str] = None,
                 write_block_shape: Optional[Sequence[int]] = None):
        self.session = session
        self.log = edit_log
        self.assignment_path = assignment_path
        self.ws_path, self.ws_key = ws_path, ws_key
        self.output_path, self.output_key = output_path, output_key
        self.paintera_path = paintera_path
        self.paintera_label_group = paintera_label_group
        self.write_block_shape = list(write_block_shape
                                      or session.block_shape)
        self.blocks_rewritten = 0
        self.round_trip_hist = telemetry.Histogram()

    # -- server pipeline protocol ------------------------------------------

    def request_n_blocks(self, edit: Dict[str, Any]) -> int:
        """One scheduling quantum per affected subproblem (at least one —
        an edit between fragments sharing no block still needs its
        reduce/global pass in finalize)."""
        return max(1, len(self.session.affected_blocks(edit["fragments"])))

    def prepare(self, edit: Dict[str, Any]) -> Dict[str, Any]:
        t0 = time.perf_counter()
        with stage("edit:resolve"):
            rec = self.log.append(edit["op"], edit["fragments"],
                                  note=str(edit.get("note", "")),
                                  edit_id=edit.get("edit_id"))
            affected = self.session.apply_edit(rec)
        return {"record": rec, "affected": affected, "t0": t0}

    def run_block(self, ctx: Dict[str, Any], block_index: int):
        affected: List[int] = ctx["affected"]
        if block_index >= len(affected):
            return None
        with stage("edit:solve"):
            self.session.ensure_block(affected[block_index],
                                      expected=set(affected),
                                      corr_id=ctx["record"].edit_id)
        return int(affected[block_index])

    def finalize(self, ctx: Dict[str, Any],
                 block_results: Dict[int, Any]) -> Dict[str, Any]:
        rec = ctx["record"]
        expected = set(ctx["affected"])
        with stage("edit:solve"):
            labels = self.session.solve(incremental=True, expected=expected,
                                        corr_id=rec.edit_id)
        with stage("edit:patch"):
            new_table, changed = patch_assignment_table(
                self.assignment_path, self.session.s0_nodes, labels)
            patch_paintera_assignment(self.paintera_path,
                                      self.paintera_label_group, new_table)
        touched: List[int] = []
        if changed.size and self.output_path:
            with stage("edit:write"):
                touched = self.session.blocks_with_fragments(changed)
                from ..workflows.write import rewrite_blocks

                self.blocks_rewritten += rewrite_blocks(
                    self.ws_path, self.ws_key, self.output_path,
                    self.output_key, new_table, touched,
                    self.write_block_shape)
        dt = time.perf_counter() - ctx["t0"]
        self.round_trip_hist.observe(dt)
        return {
            "edit_id": rec.edit_id, "seq": rec.seq, "op": rec.op,
            "fragments": list(rec.fragments),
            "affected_blocks": [int(b) for b in ctx["affected"]],
            "changed_fragments": int(changed.size),
            "touched_blocks": [int(b) for b in touched],
            "round_trip_s": dt,
            "counters": dict(self.session.counters),
        }

    # -- observability ------------------------------------------------------

    def metrics_families(self):
        """Prometheus families under the registered ``ctt_edit_*`` names
        (mergeable into the server's ``write_metrics`` output)."""
        c = self.session.counters
        return [
            ("ctt_edit_applied_total", "counter",
             "Proofreading edits applied to the live session",
             [(None, c["applied"])]),
            ("ctt_edit_subproblems_total", "counter",
             "Subproblems solved cold by the edit lane",
             [(None, c["subproblems_solved"])]),
            ("ctt_edit_warm_reused_total", "counter",
             "Subproblem solutions reused after signature validation",
             [(None, c["warm_reused"])]),
            ("ctt_edit_fallback_total", "counter",
             "Stale-cache fallbacks to a full subproblem solve",
             [(None, c["fallback"])]),
            ("ctt_edit_blocks_rewritten_total", "counter",
             "Output blocks rewritten by the assignment patcher",
             [(None, self.blocks_rewritten)]),
            telemetry.histogram_family(
                "ctt_edit_round_trip_seconds",
                "End-to-end edit round-trip (submit overlay to rewrite)",
                [(None, self.round_trip_hist)]),
        ]
