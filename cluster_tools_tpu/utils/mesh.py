"""Mesh extraction + smoothing for segmented objects.

Re-specification of the reference's ``utils/mesh_utils.py`` (marching cubes
via skimage + graph-neighbor smoothing :11-109).  skimage is not in the
image, so the iso-surface extraction is first-party **marching tetrahedra**:
each cell of the voxel grid is split into 6 tetrahedra; every tetrahedron
with a mixed-sign corner configuration emits 1-2 triangles with vertices at
edge midpoint interpolations.  Marching tetrahedra needs no 256-case table,
produces a watertight surface, and vectorizes over all cells at once."""

from __future__ import annotations

from typing import Tuple

import numpy as np

# the standard 6-tetrahedra decomposition of the unit cube around the main
# diagonal 0-7 (corner indices in binary ordering c = (dz<<2 | dy<<1 | dx));
# odd-parity cells use the mirrored table (c -> 7-c) so the induced face
# diagonals match between neighboring cells — without the parity flip the
# surface cracks along cell faces
_TETS = np.array([
    [0, 1, 3, 7],
    [0, 3, 2, 7],
    [0, 2, 6, 7],
    [0, 6, 4, 7],
    [0, 4, 5, 7],
    [0, 5, 1, 7],
], dtype="int64")
_TETS_ODD = 7 - _TETS

_CORNERS = np.array([[(c >> 2) & 1, (c >> 1) & 1, c & 1]
                     for c in range(8)], dtype="float64")


def marching_tetrahedra(volume: np.ndarray, level: float = 0.5
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """(vertices, faces) of the ``volume == level`` iso-surface.

    ``vertices``: (V, 3) float zyx coordinates; ``faces``: (F, 3) int64
    vertex indices.  Vertices shared between triangles are merged.
    """
    vol = np.asarray(volume, dtype="float64")
    if vol.ndim != 3:
        raise ValueError("marching_tetrahedra expects a 3d volume")
    nz, ny, nx = [s - 1 for s in vol.shape]
    if min(nz, ny, nx) < 1:
        return np.zeros((0, 3)), np.zeros((0, 3), "int64")

    # cell corner values: (cells, 8)
    base_all = np.stack(
        np.meshgrid(np.arange(nz), np.arange(ny), np.arange(nx),
                    indexing="ij"), -1).reshape(-1, 3)
    corner_idx = base_all[:, None, :] + _CORNERS[None].astype("int64")
    vals_all = vol[corner_idx[..., 0], corner_idx[..., 1], corner_idx[..., 2]]

    tris = []
    parity = base_all.sum(axis=1) % 2
    for par, tets in ((0, _TETS), (1, _TETS_ODD)):
        group = parity == par
        base = base_all[group]
        vals = vals_all[group]
        if len(base) == 0:
            continue
        tris.extend(_extract_tets(base, vals, tets, level))

    if not tris:
        return np.zeros((0, 3)), np.zeros((0, 3), "int64")
    tri = np.concatenate(tris, axis=0)          # (F, 3, 3)
    # merge shared vertices (quantized to kill float noise)
    flat = np.round(tri.reshape(-1, 3), 6)
    verts, inv = np.unique(flat, axis=0, return_inverse=True)
    faces = inv.reshape(-1, 3)
    # drop degenerate triangles
    ok = ((faces[:, 0] != faces[:, 1]) & (faces[:, 1] != faces[:, 2])
          & (faces[:, 0] != faces[:, 2]))
    return verts, faces[ok].astype("int64")


def _extract_tets(base, vals, tet_table, level):
    tris = []
    for tet in tet_table:
        tv = vals[:, tet]                      # (cells, 4)
        inside = tv > level                    # (cells, 4) bool
        n_in = inside.sum(axis=1)
        # corner positions of this tet for every cell: (cells, 4, 3)
        pos = base[:, None, :] + _CORNERS[tet][None]

        def edge_point(sel, a, b):
            va, vb = tv[sel, a], tv[sel, b]
            t = (level - va) / (vb - va)
            return pos[sel, a] + t[:, None] * (pos[sel, b] - pos[sel, a])

        for k, flip in ((1, False), (3, True)):
            # exactly one corner on the in-side (k=1) or out-side (k=3):
            # one triangle from that corner's three edges
            sel = np.flatnonzero(n_in == k)
            if len(sel) == 0:
                continue
            lone_in = inside[sel] if k == 1 else ~inside[sel]
            lone = np.argmax(lone_in, axis=1)
            others = np.array([[b for b in range(4) if b != a]
                               for a in range(4)])[lone]
            p = [edge_point(sel, lone, others[:, j]) for j in range(3)]
            tris.append(np.stack(p, axis=1))
        # 2-2 split: quad from the four crossing edges -> two triangles
        sel = np.flatnonzero(n_in == 2)
        if len(sel):
            ins = np.argsort(~inside[sel], axis=1)[:, :2]   # the two inside
            outs = np.argsort(inside[sel], axis=1)[:, :2]   # the two outside
            ins.sort(axis=1)
            outs.sort(axis=1)
            q00 = edge_point(sel, ins[:, 0], outs[:, 0])
            q01 = edge_point(sel, ins[:, 0], outs[:, 1])
            q11 = edge_point(sel, ins[:, 1], outs[:, 1])
            q10 = edge_point(sel, ins[:, 1], outs[:, 0])
            tris.append(np.stack([q00, q01, q11], axis=1))
            tris.append(np.stack([q00, q11, q10], axis=1))
    return tris


def smooth_mesh(vertices: np.ndarray, faces: np.ndarray,
                iterations: int = 5, lam: float = 0.5) -> np.ndarray:
    """Laplacian smoothing: move each vertex toward the mean of its mesh
    neighbors (reference: mesh_utils.py:11-34 graph-neighbor smoothing)."""
    verts = np.asarray(vertices, dtype="float64").copy()
    faces = np.asarray(faces, dtype="int64")
    n = len(verts)
    if n == 0 or len(faces) == 0:
        return verts
    # vertex adjacency from the face edges
    edges = np.concatenate([faces[:, [0, 1]], faces[:, [1, 2]],
                            faces[:, [2, 0]]])
    edges = np.unique(np.sort(edges, axis=1), axis=0)
    for _ in range(iterations):
        acc = np.zeros_like(verts)
        deg = np.zeros(n)
        np.add.at(acc, edges[:, 0], verts[edges[:, 1]])
        np.add.at(acc, edges[:, 1], verts[edges[:, 0]])
        np.add.at(deg, edges[:, 0], 1)
        np.add.at(deg, edges[:, 1], 1)
        mean = acc / np.maximum(deg, 1)[:, None]
        verts = verts + lam * (mean - verts)
    return verts


def object_mesh(seg: np.ndarray, label_id: int, smoothing_iterations: int = 0
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Mesh of one segment (the compute_meshes entry point the reference
    left as an empty placeholder, meshes/compute_meshes.py)."""
    obj = (np.asarray(seg) == label_id).astype("float64")
    # pad so surfaces at the volume border close
    obj = np.pad(obj, 1)
    verts, faces = marching_tetrahedra(obj, level=0.5)
    verts = verts - 1.0  # undo the pad offset
    if smoothing_iterations:
        verts = smooth_mesh(verts, faces, iterations=smoothing_iterations)
    return verts, faces
